"""Node providers: how the autoscaler actually gets machines.

Reference: python/ray/autoscaler/node_provider.py (ABC) + per-cloud
implementations; the fake provider mirrors
autoscaler/_private/fake_multi_node/node_provider.py — "launching" a node
starts a real in-process NodeAgent, so autoscaler end-to-end tests run
without a cloud (SURVEY.md §4 keystone).
"""

from __future__ import annotations

from typing import Optional


class NodeProvider:
    """Launch/terminate worker nodes for one node type."""

    def create_node(self, node_config: dict) -> str:
        """Start a node; returns a provider-scoped node name."""
        raise NotImplementedError

    def terminate_node(self, name: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> list[str]:
        raise NotImplementedError


class FakeNodeProvider(NodeProvider):
    """Launches real in-process NodeAgents against a control plane.

    One provider node may be a MULTI-HOST TPU slice (``hosts`` in the node
    config): a single create_node brings up all of its host agents sharing a
    slice_name label — matching the cloud provider, where one TPU slice
    create yields every host VM at once (GCETPUNodeProvider ssh --worker=all).
    """

    def __init__(self, cp_addr: tuple[str, int], inproc_workers: bool = False):
        self._cp_addr = tuple(cp_addr)
        self._inproc = bool(inproc_workers)
        self._agents: dict[str, list] = {}  # name -> [NodeAgent, ...]
        self._counter = 0

    def create_node(self, node_config: dict) -> str:
        from ray_tpu.core.node_agent import NodeAgent

        self._counter += 1
        name = f"fake-{self._counter}"
        hosts = max(1, int(node_config.get("hosts", 1)))
        agents = []
        for i in range(hosts):
            labels = dict(node_config.get("labels") or {})
            labels["provider_node_name"] = name
            if hosts > 1:
                # slice identity: every host carries the slice name and its
                # worker index (what the real TPU metadata server provides)
                labels.setdefault("slice_name", name)
                labels["tpu_worker_id"] = str(i)
                labels.setdefault("topology", "")
            agents.append(NodeAgent(
                self._cp_addr,
                resources=dict(node_config.get("resources") or {}),
                labels=labels, inproc_workers=self._inproc))
        self._agents[name] = agents
        return name

    def terminate_node(self, name: str) -> None:
        for agent in self._agents.pop(name, []):
            try:
                agent.stop()
            except Exception:  # noqa: BLE001 - drain may have raced parts
                pass

    def non_terminated_nodes(self) -> list[str]:
        return list(self._agents)

    def agent(self, name: str):
        agents = self._agents.get(name)
        return agents[0] if agents else None

    def agents(self, name: str) -> list:
        return list(self._agents.get(name, []))


class GCETPUNodeProvider(NodeProvider):
    """GCE/GKE TPU slice provider (the cloud target for this framework —
    reference: autoscaler/gcp/ + TPU pod scheduling). Shells out to
    `gcloud compute tpus tpu-vm` so no SDK dependency is needed; requires
    credentials + network, so everything is lazy and failures are explicit.
    """

    def __init__(self, project: str, zone: str, cluster_address: str,
                 accelerator_type: str = "v5litepod-8",
                 runtime_version: str = "tpu-ubuntu2204-base"):
        self.project = project
        self.zone = zone
        self.cluster_address = cluster_address
        self.accelerator_type = accelerator_type
        self.runtime_version = runtime_version
        self._nodes: set[str] = set()
        self._counter = 0

    def _gcloud(self, *args: str) -> str:
        import subprocess
        out = subprocess.run(
            ["gcloud", "compute", "tpus", "tpu-vm", *args,
             f"--project={self.project}", f"--zone={self.zone}",
             "--format=json"],
            capture_output=True, text=True, timeout=600)
        if out.returncode != 0:
            raise RuntimeError(f"gcloud failed: {out.stderr[-500:]}")
        return out.stdout

    def create_node(self, node_config: dict) -> str:
        self._counter += 1
        name = node_config.get("name") or f"ray-tpu-node-{self._counter}"
        accel = node_config.get("accelerator_type", self.accelerator_type)
        self._gcloud(
            "create", name, f"--accelerator-type={accel}",
            f"--version={node_config.get('runtime_version', self.runtime_version)}")
        # bootstrap: every TPU VM host joins as a worker node, labelled with
        # the provider node name so the autoscaler can match CP nodes back
        # to cloud instances for idle scale-down
        self._gcloud(
            "ssh", name, "--worker=all", "--command",
            f"python -m ray_tpu start --address {self.cluster_address} "
            f"--labels provider_node_name={name}")
        self._nodes.add(name)
        return name

    def terminate_node(self, name: str) -> None:
        self._gcloud("delete", name, "--quiet")
        self._nodes.discard(name)

    def non_terminated_nodes(self) -> list[str]:
        return sorted(self._nodes)


class KubernetesNodeProvider(NodeProvider):
    """KubeRay-style provider: each node is a pod running a node agent
    (reference: autoscaler/kuberay/ + the KubeRay operator's worker
    groups, collapsed to the provider interface — this framework's
    controller/agent processes ARE the pod entrypoint, so the operator's
    CRD layer reduces to pod create/delete/list).

    Shells out to `kubectl` (no kubernetes SDK dependency; gated with a
    clear error when absent). Pods run `python -m ray_tpu start --address
    <head>` with resources from the node_config; a `ray-tpu-node` label
    keys listing and the provider-name label lets the autoscaler match CP
    nodes back to pods for idle scale-down.
    """

    _LABEL = "ray-tpu-node"

    def __init__(self, cluster_address: str, *, namespace: str = "default",
                 image: str = "ray-tpu:latest",
                 pod_template: Optional[dict] = None):
        import shutil as _shutil
        if _shutil.which("kubectl") is None:
            raise RuntimeError(
                "KubernetesNodeProvider requires kubectl on PATH "
                "(not present in this image)")
        self.cluster_address = cluster_address
        self.namespace = namespace
        self.image = image
        self.pod_template = pod_template or {}
        self._counter = 0

    def _kubectl(self, *args: str, stdin: Optional[str] = None) -> str:
        import subprocess
        out = subprocess.run(
            ["kubectl", "-n", self.namespace, *args],
            input=stdin, capture_output=True, text=True, timeout=300)
        if out.returncode != 0:
            raise RuntimeError(f"kubectl failed: {out.stderr[-500:]}")
        return out.stdout

    def create_node(self, node_config: dict) -> str:
        import json as _json
        import uuid as _uuid
        self._counter += 1
        # unique suffix: the counter resets on autoscaler restart, and a
        # bare counter name would collide with a pod the previous
        # incarnation left behind
        name = node_config.get("name") or \
            f"ray-tpu-worker-{self._counter}-{_uuid.uuid4().hex[:6]}"
        resources = dict(node_config.get("resources") or {})
        cpu = float(resources.get("CPU", 1))
        # millicores: fractional CPUs are normal in Ray-style dicts and a
        # truncated "0" request hard-throttles the pod
        requests = {"cpu": f"{int(cpu * 1000)}m"}
        if resources.get("TPU"):
            requests["google.com/tpu"] = str(int(resources["TPU"]))
        pod = {
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": name,
                         "labels": {self._LABEL: "true",
                                    "provider-node-name": name}},
            "spec": {
                **{k: v for k, v in self.pod_template.items()
                   if k not in ("containers",)},
                "restartPolicy": "Never",
                "containers": [{
                    "name": "node",
                    "image": node_config.get("image", self.image),
                    # --labels speaks the CLI's k=v[,k2=v2] format — the
                    # provider_node_name label is how the autoscaler maps
                    # CP nodes back to pods for idle scale-down
                    "command": ["python", "-m", "ray_tpu", "start",
                                "--address", self.cluster_address,
                                "--labels", ",".join(
                                    f"{k}={v}" for k, v in
                                    {"provider_node_name": name,
                                     **(node_config.get("labels") or {})}
                                    .items())],
                    "resources": {"requests": requests,
                                  "limits": dict(requests)},
                }],
            },
        }
        if "containers" in self.pod_template:
            raise ValueError(
                "pod_template must not define 'containers' (the provider "
                "owns the node-agent container); use sidecar-free "
                "templates for tolerations/nodeSelector/etc.")
        # `create`, NOT `apply`: apply is idempotent, so a name collision
        # with a leftover pod "succeeds" without starting anything and the
        # instance manager counts phantom capacity. create fails loudly
        # (_kubectl raises) and the launch lands in ALLOCATION_FAILED.
        self._kubectl("create", "-f", "-", stdin=_json.dumps(pod))
        return name

    def terminate_node(self, name: str) -> None:
        self._kubectl("delete", "pod", name, "--ignore-not-found=true",
                      "--wait=false")

    def non_terminated_nodes(self) -> list[str]:
        import json as _json
        out = self._kubectl("get", "pods", "-l", f"{self._LABEL}=true",
                            "-o", "json")
        items = _json.loads(out or "{}").get("items", [])
        alive = []
        for pod in items:
            phase = (pod.get("status") or {}).get("phase", "")
            deleting = (pod.get("metadata") or {}).get("deletionTimestamp")
            # a gracefully-terminating pod keeps phase Running with only
            # deletionTimestamp set — it is NOT live capacity
            if phase in ("Pending", "Running") and not deleting:
                alive.append(pod["metadata"]["name"])
        return alive
