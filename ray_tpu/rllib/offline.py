"""Offline RL: episode recording, BC, and discrete CQL.

Mirrors the reference's offline stack (rllib/offline/ — offline_data.py
feeds recorded episodes through Ray Data; rllib/algorithms/bc,
rllib/algorithms/cql). Episodes are recorded to npz; `OfflineData` serves
shuffled minibatches either from the file or from a ray_tpu.data Dataset
(the reference's route), so the data plane and the RL library compose.
"""

from __future__ import annotations

import os
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.env import make_env
from ray_tpu.rllib.models import mlp_apply, mlp_init


def record_episodes(env_spec, policy: Callable[[np.ndarray], int], path: str,
                    *, num_episodes: int = 100, max_steps: int = 500,
                    seed: int = 0) -> str:
    """Roll out `policy` and save (obs, actions, rewards, next_obs, dones)
    transitions to an npz (ref: rllib/offline/offline_env_runner.py)."""
    env = make_env(env_spec)
    obs_l, act_l, rew_l, next_l, done_l = [], [], [], [], []
    for ep in range(num_episodes):
        obs = env.reset(seed=seed + ep)
        for _ in range(max_steps):
            a = int(policy(obs))
            nxt, r, term, trunc = env.step(a)
            obs_l.append(obs)
            act_l.append(a)
            rew_l.append(r)
            next_l.append(nxt)
            done_l.append(float(term))
            obs = nxt
            if term or trunc:
                break
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    np.savez(path, obs=np.asarray(obs_l, np.float32),
             actions=np.asarray(act_l, np.int32),
             rewards=np.asarray(rew_l, np.float32),
             next_obs=np.asarray(next_l, np.float32),
             dones=np.asarray(done_l, np.float32))
    return path


class OfflineData:
    """Minibatch server over recorded transitions (ref: offline_data.py).

    Accepts an npz path or a ray_tpu.data Dataset whose columns match the
    transition schema."""

    def __init__(self, source, seed: int = 0):
        if isinstance(source, str):
            z = np.load(source)
            self._data = {k: z[k] for k in
                          ("obs", "actions", "rewards", "next_obs", "dones")}
        else:  # ray_tpu.data Dataset
            cols: dict[str, list] = {}
            for batch in source.iter_batches(batch_size=4096,
                                             batch_format="numpy"):
                for k, v in batch.items():
                    cols.setdefault(k, []).append(np.asarray(v))
            def densify(a):
                # arrow list columns come back as object arrays of rows
                if a.dtype == object:
                    return np.stack([np.asarray(x, np.float32) for x in a])
                return a
            self._data = {k: densify(np.concatenate(v))
                          for k, v in cols.items()}
            self._data["actions"] = self._data["actions"].astype(np.int32)
        self._n = len(self._data["obs"])
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._n

    def sample(self, batch_size: int) -> dict:
        idx = self._rng.integers(0, self._n, batch_size)
        return {k: v[idx] for k, v in self._data.items()}


class _OfflineAlgorithm(Algorithm):
    """Base for offline algos: no env runners are sampled during training
    (the dataset IS the experience); evaluate() still uses the env."""

    @classmethod
    def get_default_config(cls) -> AlgorithmConfig:
        cfg = AlgorithmConfig(algo_cls=cls)
        cfg.lr = 1e-3
        cfg.num_env_runners = 0
        return cfg

    def __init__(self, config: AlgorithmConfig):
        src = config.train_kwargs.get("input_")
        if src is None:
            raise ValueError(
                "offline algorithms need config.training(input_=<npz path "
                "or ray_tpu.data Dataset>)")
        # BEFORE super().__init__: setup() runs inside it and advantage-
        # style algos (MARWIL) precompute over the dataset there
        self.data = OfflineData(src, seed=config.seed)
        super().__init__(config)


class BC(_OfflineAlgorithm):
    """Behavior cloning (ref: rllib/algorithms/bc/bc.py): cross-entropy on
    the dataset's actions."""

    def setup(self) -> None:
        kw = self.config.train_kwargs
        self._batch_size = kw.get("train_batch_size", 256)
        self._updates_per_iter = kw.get("updates_per_iter", 100)
        self._opt = optax.adam(self.config.lr)
        self._opt_state = self._opt.init(self.params["pi"])

        def loss_fn(pi, b):
            logits = mlp_apply(pi, b["obs"])
            logp = jax.nn.log_softmax(logits)
            return -jnp.take_along_axis(
                logp, b["actions"][:, None], axis=1).mean()

        @jax.jit
        def update(pi, opt_state, b):
            loss, grads = jax.value_and_grad(loss_fn)(pi, b)
            updates, opt_state = self._opt.update(grads, opt_state, pi)
            return optax.apply_updates(pi, updates), opt_state, loss

        self._update = update

    def training_step(self) -> dict:
        loss = 0.0
        for _ in range(self._updates_per_iter):
            b = self.data.sample(self._batch_size)
            self.params["pi"], self._opt_state, loss = self._update(
                self.params["pi"], self._opt_state, b)
        self._timesteps += self._updates_per_iter * self._batch_size
        return {"bc_loss": float(loss), "dataset_size": len(self.data)}



class CQL(_OfflineAlgorithm):
    """Discrete conservative Q-learning (ref: rllib/algorithms/cql/):
    double-DQN TD loss + the CQL regularizer
    alpha_cql * E[logsumexp_a Q(s,a) - Q(s, a_data)], which pushes down
    out-of-distribution action values so the greedy policy stays inside the
    dataset's support."""

    def setup(self) -> None:
        kw = self.config.train_kwargs
        self._batch_size = kw.get("train_batch_size", 256)
        self._updates_per_iter = kw.get("updates_per_iter", 100)
        self._target_update_freq = kw.get("target_update_freq", 100)
        self._alpha_cql = kw.get("cql_alpha", 1.0)
        env = make_env(self.config.env_spec)
        sizes = [env.observation_dim, *self.config.hidden, env.num_actions]
        k = jax.random.PRNGKey(self.config.seed + 2)
        q = mlp_init(k, sizes)
        # the greedy policy IS the Q net: share it under "pi" so
        # compute_single_action / evaluate need no special-casing
        self.params = {"pi": q}
        self._target = jax.tree.map(jnp.copy, q)
        self._opt = optax.adam(self.config.lr)
        self._opt_state = self._opt.init(self.params)
        gamma, alpha_cql = self.config.gamma, self._alpha_cql

        def loss_fn(params, target, b):
            q = mlp_apply(params["pi"], b["obs"])
            a = b["actions"][:, None]
            q_sa = jnp.take_along_axis(q, a, axis=1)[:, 0]
            next_online = mlp_apply(params["pi"], b["next_obs"])
            next_a = jnp.argmax(next_online, axis=1)
            next_q = jnp.take_along_axis(
                mlp_apply(target, b["next_obs"]), next_a[:, None], axis=1)[:, 0]
            td_target = b["rewards"] + gamma * (1.0 - b["dones"]) * \
                jax.lax.stop_gradient(next_q)
            td_loss = ((q_sa - td_target) ** 2).mean()
            cql_loss = (jax.scipy.special.logsumexp(q, axis=1) - q_sa).mean()
            return td_loss + alpha_cql * cql_loss, (td_loss, cql_loss)

        @jax.jit
        def update(params, target, opt_state, b):
            (_, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, target, b)
            updates, opt_state = self._opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, aux

        self._update = update

    def training_step(self) -> dict:
        td = cql = 0.0
        for i in range(self._updates_per_iter):
            b = self.data.sample(self._batch_size)
            self.params, self._opt_state, (td, cql) = self._update(
                self.params, self._target, self._opt_state, b)
            if (i + 1) % self._target_update_freq == 0:
                self._target = jax.tree.map(jnp.copy, self.params["pi"])
        self._timesteps += self._updates_per_iter * self._batch_size
        return {"td_loss": float(td), "cql_loss": float(cql),
                "dataset_size": len(self.data)}


def BCConfig() -> AlgorithmConfig:
    return BC.get_default_config()


def CQLConfig() -> AlgorithmConfig:
    return CQL.get_default_config()


class MARWIL(_OfflineAlgorithm):
    """Monotonic advantage re-weighted imitation learning (ref:
    rllib/algorithms/marwil/): behavior cloning whose log-likelihood is
    weighted by exp(beta * advantage) — transitions that beat the value
    baseline imitate harder, so mixed-quality data distills toward its
    good trajectories. beta=0 degenerates to BC (the reference notes the
    same). Advantages use return-to-go computed over the dataset's done
    boundaries."""

    def setup(self) -> None:
        kw = self.config.train_kwargs
        self._batch_size = kw.get("train_batch_size", 256)
        self._updates_per_iter = kw.get("updates_per_iter", 100)
        beta = kw.get("beta", 1.0)
        vf_c = kw.get("vf_coeff", 1.0)
        env = make_env(self.config.env_spec)
        k1, k2 = jax.random.split(jax.random.PRNGKey(self.config.seed + 3))
        sizes = [env.observation_dim, *self.config.hidden]
        self.params = {"pi": mlp_init(k1, sizes + [env.num_actions]),
                       "v": mlp_init(k2, sizes + [1])}
        self._opt = optax.adam(self.config.lr)
        self._opt_state = self._opt.init(self.params)
        # return-to-go with gamma over the recorded stream; a done resets
        # the accumulator (truncation without a done mark leaks the next
        # episode's head into the tail — the recorder marks term only,
        # matching the reference's offline json semantics)
        gamma = self.config.gamma
        rew = jnp.asarray(self.data._data["rewards"], jnp.float32)
        dones = jnp.asarray(self.data._data["dones"], jnp.float32)

        def rtg_step(acc, x):
            r, d = x
            acc = r + gamma * (1.0 - d) * acc
            return acc, acc

        # jitted reverse scan (the _gae idiom): O(n) on-device, not a
        # per-element Python loop over a potentially huge dataset
        _, rtg = jax.jit(lambda r, d: jax.lax.scan(
            rtg_step, jnp.float32(0.0), (r, d), reverse=True))(rew, dones)
        self.data._data["rtg"] = np.asarray(rtg, np.float32)

        def loss_fn(params, b):
            logits = mlp_apply(params["pi"], b["obs"])
            logp = jnp.take_along_axis(
                jax.nn.log_softmax(logits), b["actions"][:, None], axis=1)[:, 0]
            v = mlp_apply(params["v"], b["obs"])[:, 0]
            adv = b["rtg"] - v
            # stop-grad on the weight: the policy term must not push V
            w = jnp.exp(jnp.clip(
                beta * jax.lax.stop_gradient(adv), -5.0, 5.0))
            pi_loss = -(w * logp).mean()
            v_loss = (adv ** 2).mean()
            return pi_loss + vf_c * v_loss, (pi_loss, v_loss)

        @jax.jit
        def update(params, opt_state, b):
            (_, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, b)
            updates, opt_state = self._opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, aux

        self._update = update

    def training_step(self) -> dict:
        pi_l = v_l = 0.0
        for _ in range(self._updates_per_iter):
            b = self.data.sample(self._batch_size)
            self.params, self._opt_state, (pi_l, v_l) = self._update(
                self.params, self._opt_state, b)
        self._timesteps += self._updates_per_iter * self._batch_size
        return {"policy_loss": float(pi_l), "vf_loss": float(v_l),
                "dataset_size": len(self.data)}



class IQL(_OfflineAlgorithm):
    """Discrete implicit Q-learning (ref: rllib/algorithms/iql/): never
    queries Q on out-of-distribution actions. V is fit to Q by EXPECTILE
    regression (tau > 0.5 biases toward the dataset's better actions), Q
    bootstraps from V, and the policy is advantage-weighted behavior
    cloning exp((Q - V)/temperature) over dataset actions only."""

    def setup(self) -> None:
        kw = self.config.train_kwargs
        self._batch_size = kw.get("train_batch_size", 256)
        self._updates_per_iter = kw.get("updates_per_iter", 100)
        self._target_update_freq = kw.get("target_update_freq", 100)
        tau = kw.get("expectile", 0.8)
        # exp((Q-V)/temperature): LOWER temperature sharpens toward the
        # best dataset actions (IQL paper convention)
        inv_temp = 1.0 / max(1e-6, kw.get("temperature", 0.33))
        env = make_env(self.config.env_spec)
        keys = jax.random.split(jax.random.PRNGKey(self.config.seed + 4), 3)
        sizes = [env.observation_dim, *self.config.hidden]
        self.params = {"pi": mlp_init(keys[0], sizes + [env.num_actions]),
                       "q": mlp_init(keys[1], sizes + [env.num_actions]),
                       "v": mlp_init(keys[2], sizes + [1])}
        self._target_q = jax.tree.map(jnp.copy, self.params["q"])
        self._opt = optax.adam(self.config.lr)
        self._opt_state = self._opt.init(self.params)
        gamma = self.config.gamma

        def loss_fn(params, target_q, b):
            a = b["actions"][:, None]
            # V <- expectile of target-Q at DATASET actions
            q_t = jnp.take_along_axis(
                mlp_apply(target_q, b["obs"]), a, axis=1)[:, 0]
            v = mlp_apply(params["v"], b["obs"])[:, 0]
            u = jax.lax.stop_gradient(q_t) - v
            v_loss = (jnp.abs(tau - (u < 0)) * u ** 2).mean()
            # Q <- r + gamma V(s') (no max over OOD actions)
            v_next = jax.lax.stop_gradient(
                mlp_apply(params["v"], b["next_obs"])[:, 0])
            q = jnp.take_along_axis(mlp_apply(params["q"], b["obs"]),
                                    a, axis=1)[:, 0]
            q_loss = ((b["rewards"] + gamma * (1.0 - b["dones"]) * v_next
                       - q) ** 2).mean()
            # policy <- advantage-weighted BC on dataset actions
            adv = jax.lax.stop_gradient(q_t - v)
            w = jnp.exp(jnp.clip(adv * inv_temp, -5.0, 5.0))
            logp = jnp.take_along_axis(
                jax.nn.log_softmax(mlp_apply(params["pi"], b["obs"])),
                a, axis=1)[:, 0]
            pi_loss = -(w * logp).mean()
            return v_loss + q_loss + pi_loss, (v_loss, q_loss, pi_loss)

        @jax.jit
        def update(params, target_q, opt_state, b):
            (_, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, target_q, b)
            updates, opt_state = self._opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, aux

        self._update = update

    def training_step(self) -> dict:
        v_l = q_l = pi_l = 0.0
        for i in range(self._updates_per_iter):
            b = self.data.sample(self._batch_size)
            self.params, self._opt_state, (v_l, q_l, pi_l) = self._update(
                self.params, self._target_q, self._opt_state, b)
            if (i + 1) % self._target_update_freq == 0:
                self._target_q = jax.tree.map(jnp.copy, self.params["q"])
        self._timesteps += self._updates_per_iter * self._batch_size
        return {"v_loss": float(v_l), "q_loss": float(q_l),
                "policy_loss": float(pi_l), "dataset_size": len(self.data)}


def MARWILConfig() -> AlgorithmConfig:
    return MARWIL.get_default_config()


def IQLConfig() -> AlgorithmConfig:
    return IQL.get_default_config()
