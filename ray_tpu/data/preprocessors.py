"""Fit/transform preprocessors over Datasets.

TPU-native analog of the reference's preprocessor library
(python/ray/data/preprocessors/ — scalers, encoders, concatenator, chain;
base class preprocessor.py). fit() computes dataset-level statistics with
ONE aggregation pass; transform() is a stateless vectorized batch map that
fuses into the read stage like any other map. The fitted state is plain
python (dict of floats / category lists), so a fitted preprocessor pickles
into train/serve workers.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


class Preprocessor:
    """Base: fit(ds) -> self, transform(ds) -> ds, transform_batch(dict)."""

    _fitted = False

    def fit(self, ds) -> "Preprocessor":
        self._fit(ds)
        self._fitted = True
        return self

    def fit_transform(self, ds):
        return self.fit(ds).transform(ds)

    def transform(self, ds):
        if not self._fitted and self._needs_fit():
            raise RuntimeError(f"{type(self).__name__} must be fit() first")
        fn = self.transform_batch
        return ds.map_batches(fn, batch_format="numpy")

    # -- subclass hooks --------------------------------------------------
    def _fit(self, ds) -> None:
        pass

    def _needs_fit(self) -> bool:
        return True

    def transform_batch(self, batch: dict) -> dict:
        raise NotImplementedError


class StandardScaler(Preprocessor):
    """(x - mean) / std per column (reference preprocessors/scaler.py)."""

    def __init__(self, columns: list[str]):
        self.columns = list(columns)
        self.stats_: dict[str, tuple[float, float]] = {}

    def _fit(self, ds) -> None:
        from ray_tpu.data.aggregate import Mean, Std
        aggs = [a for c in self.columns for a in (Mean(c), Std(c))]
        out = ds.aggregate(*aggs)  # ONE pass for every column's stats
        for c in self.columns:
            self.stats_[c] = (float(out[f"mean({c})"]),
                              float(out[f"std({c})"]) or 1.0)

    def transform_batch(self, batch: dict) -> dict:
        for c in self.columns:
            mean, std = self.stats_[c]
            batch[c] = (np.asarray(batch[c], np.float64) - mean) / std
        return batch


class MinMaxScaler(Preprocessor):
    """(x - min) / (max - min) per column (reference scaler.py)."""

    def __init__(self, columns: list[str]):
        self.columns = list(columns)
        self.stats_: dict[str, tuple[float, float]] = {}

    def _fit(self, ds) -> None:
        from ray_tpu.data.aggregate import Max, Min
        aggs = [a for c in self.columns for a in (Min(c), Max(c))]
        out = ds.aggregate(*aggs)  # ONE pass for every column's stats
        for c in self.columns:
            lo, hi = float(out[f"min({c})"]), float(out[f"max({c})"])
            self.stats_[c] = (lo, (hi - lo) or 1.0)

    def transform_batch(self, batch: dict) -> dict:
        for c in self.columns:
            lo, span = self.stats_[c]
            batch[c] = (np.asarray(batch[c], np.float64) - lo) / span
        return batch


class LabelEncoder(Preprocessor):
    """Map categories to dense int ids (reference preprocessors/encoder.py
    LabelEncoder); unseen values encode as -1."""

    def __init__(self, label_column: str):
        self.label_column = label_column
        self.classes_: list = []

    def _fit(self, ds) -> None:
        col = self.label_column
        values = set()
        for batch in ds.iter_batches(batch_format="numpy"):
            values.update(np.asarray(batch[col]).tolist())
        self.classes_ = sorted(values)
        self._index = {v: i for i, v in enumerate(self.classes_)}

    def transform_batch(self, batch: dict) -> dict:
        idx = self._index
        col = np.asarray(batch[self.label_column])
        batch[self.label_column] = np.asarray(
            [idx.get(v, -1) for v in col.tolist()], np.int64)
        return batch


class OneHotEncoder(Preprocessor):
    """Expand a categorical column into 0/1 indicator columns
    (reference encoder.py OneHotEncoder): column -> column_<value>."""

    def __init__(self, columns: list[str]):
        self.columns = list(columns)
        self.categories_: dict[str, list] = {}

    def _fit(self, ds) -> None:
        values: dict[str, set] = {c: set() for c in self.columns}
        for batch in ds.iter_batches(batch_format="numpy"):  # ONE pass
            for c in self.columns:
                values[c].update(np.asarray(batch[c]).tolist())
        self.categories_ = {c: sorted(v) for c, v in values.items()}

    def transform_batch(self, batch: dict) -> dict:
        for c in self.columns:
            col = np.asarray(batch.pop(c))
            for v in self.categories_[c]:
                batch[f"{c}_{v}"] = (col == v).astype(np.int8)
        return batch


class Concatenator(Preprocessor):
    """Concatenate numeric columns into one vector column (reference
    preprocessors/concatenator.py) — the standard last step before
    feeding a model a single feature matrix."""

    def __init__(self, columns: list[str], output_column_name: str = "concat",
                 dtype=np.float32):
        self.columns = list(columns)
        self.output_column_name = output_column_name
        self.dtype = dtype

    def _needs_fit(self) -> bool:
        return False

    def transform_batch(self, batch: dict) -> dict:
        arrs = [np.asarray(batch.pop(c)) for c in self.columns]
        n = len(arrs[0])
        batch[self.output_column_name] = np.concatenate(
            [a.reshape(n, -1) for a in arrs], axis=1).astype(self.dtype)
        return batch


class Chain(Preprocessor):
    """Apply preprocessors in sequence (reference preprocessors/chain.py);
    each stage fits on the PREVIOUS stages' transformed output."""

    def __init__(self, *stages: Preprocessor):
        self.stages = list(stages)

    def _fit(self, ds) -> None:
        for i, stage in enumerate(self.stages):
            stage.fit(ds)
            if i < len(self.stages) - 1:
                # materialize between stages: each later fit would
                # otherwise re-execute the WHOLE untransformed pipeline
                # (including source reads) per statistic
                ds = stage.transform(ds).materialize()

    def transform(self, ds):
        for stage in self.stages:
            ds = stage.transform(ds)
        return ds

    def transform_batch(self, batch: dict) -> dict:
        for stage in self.stages:
            batch = stage.transform_batch(batch)
        return batch
