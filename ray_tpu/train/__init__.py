"""ray_tpu.train — distributed training orchestration, JAX/SPMD-first.

Public surface mirrors the reference's `ray.train` v2
(/root/reference/python/ray/train/v2/api/): trainers, config types,
report/get_context/get_checkpoint/get_dataset_shard, Checkpoint, Result.
The in-framework parallelism library (DP/FSDP/TP/PP/EP/CP) lives in
ray_tpu.parallel + ray_tpu.train.spmd.
"""

from ray_tpu.train.checkpoint import (
    AsyncCheckpointWriter,
    Checkpoint,
    CheckpointManager,
    StorageContext,
)
from ray_tpu.train.config import (
    CheckpointConfig,
    FailureConfig,
    Result,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train.context import (
    TrainContext,
    get_checkpoint,
    get_context,
    get_dataset_shard,
    report,
)
from ray_tpu.train.controller import (
    RunState,
    TrainController,
    TrainingFailedError,
)
from ray_tpu.train.scaling import (
    FixedScalingPolicy,
    FunctionScalingPolicy,
    ResizeDecision,
    ScalingPolicy,
)
from ray_tpu.train.sync import SynchronizationActor
from ray_tpu.train.trainer import DataParallelTrainer, JaxTrainer
from ray_tpu.train.worker_group import RayTrainWorker, WorkerGroup

__all__ = [
    "AsyncCheckpointWriter",
    "Checkpoint", "CheckpointConfig", "CheckpointManager", "DataParallelTrainer",
    "FixedScalingPolicy", "FunctionScalingPolicy", "ResizeDecision",
    "ScalingPolicy",
    "FailureConfig", "JaxTrainer", "RayTrainWorker", "Result", "RunConfig",
    "RunState", "ScalingConfig", "StorageContext", "SynchronizationActor",
    "TrainContext", "TrainController", "TrainingFailedError", "WorkerGroup",
    "get_checkpoint", "get_context", "get_dataset_shard", "report",
]
