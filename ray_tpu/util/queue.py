"""Distributed Queue (reference: /root/reference/python/ray/util/queue.py):
a FIFO shared between processes, backed by an actor."""

from __future__ import annotations

import asyncio
from typing import Any, Optional

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


@ray_tpu.remote
class _QueueActor:
    def __init__(self, maxsize: int):
        self._q = asyncio.Queue(maxsize=maxsize)

    async def put(self, item, timeout: Optional[float] = None) -> bool:
        try:
            if timeout is None:
                await self._q.put(item)
            else:
                await asyncio.wait_for(self._q.put(item), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    async def put_nowait(self, item) -> bool:
        try:
            self._q.put_nowait(item)
            return True
        except asyncio.QueueFull:
            return False

    async def get(self, timeout: Optional[float] = None):
        try:
            if timeout is None:
                return True, await self._q.get()
            return True, await asyncio.wait_for(self._q.get(), timeout)
        except asyncio.TimeoutError:
            return False, None

    async def get_nowait(self):
        try:
            return True, self._q.get_nowait()
        except asyncio.QueueEmpty:
            return False, None

    async def size(self) -> int:
        return self._q.qsize()

    async def empty(self) -> bool:
        return self._q.empty()

    async def full(self) -> bool:
        return self._q.full()


class Queue:
    def __init__(self, maxsize: int = 0, actor_options: Optional[dict] = None):
        opts = actor_options or {}
        self.actor = _QueueActor.options(**opts).remote(maxsize)

    def put(self, item: Any, block: bool = True,
            timeout: Optional[float] = None) -> None:
        if not block:
            if not ray_tpu.get(self.actor.put_nowait.remote(item)):
                raise Full
            return
        ok = ray_tpu.get(self.actor.put.remote(item, timeout))
        if not ok:
            raise Full

    def put_nowait(self, item: Any) -> None:
        self.put(item, block=False)

    def get(self, block: bool = True, timeout: Optional[float] = None) -> Any:
        if not block:
            ok, item = ray_tpu.get(self.actor.get_nowait.remote())
            if not ok:
                raise Empty
            return item
        ok, item = ray_tpu.get(self.actor.get.remote(timeout))
        if not ok:
            raise Empty
        return item

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def qsize(self) -> int:
        return ray_tpu.get(self.actor.size.remote())

    def size(self) -> int:
        return self.qsize()

    def empty(self) -> bool:
        return ray_tpu.get(self.actor.empty.remote())

    def full(self) -> bool:
        return ray_tpu.get(self.actor.full.remote())

    def shutdown(self) -> None:
        ray_tpu.kill(self.actor)
