"""Native (C++) runtime components, loaded via ctypes.

The compute path of ray_tpu is JAX/XLA; the runtime around it is native
where the reference's is (SURVEY.md §2.1): this package holds the C++
shared-memory arena object store (plasma equivalent —
/root/reference/src/ray/object_manager/plasma/) built as `librtpu_shm.so`.

Build model: `ensure_built()` compiles the .so with g++ on first use (cached
by source mtime under _native/build/); callers fall back to the pure-python
store when no toolchain is available.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_BUILD_DIR = os.path.join(_HERE, "build")
_SO_PATH = os.path.join(_BUILD_DIR, "librtpu_shm.so")
_SRC = os.path.join(_HERE, "shm_store.cc")

_lock = threading.Lock()
_lib = None
_build_error = None


def ensure_built():  # graftlint: disable=lock-discipline — the build lock's purpose IS to serialize the one-time g++ build
    """Compile the native library if needed; returns the .so path or None."""
    global _build_error
    with _lock:
        if os.path.exists(_SO_PATH) and \
                os.path.getmtime(_SO_PATH) >= os.path.getmtime(_SRC):
            return _SO_PATH
        if _build_error is not None:
            return None
        os.makedirs(_BUILD_DIR, exist_ok=True)
        cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
               "-o", _SO_PATH + ".tmp", _SRC, "-lrt", "-pthread"]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            os.replace(_SO_PATH + ".tmp", _SO_PATH)
            return _SO_PATH
        except (subprocess.CalledProcessError, FileNotFoundError,
                subprocess.TimeoutExpired) as e:
            _build_error = getattr(e, "stderr", b"") or str(e)
            return None


def build_error():
    return _build_error


def load_library():
    """ctypes-load the native store library (None if unavailable)."""
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
    path = ensure_built()
    if path is None:
        return None
    lib = ctypes.CDLL(path)
    lib.rtpu_store_create.restype = ctypes.c_void_p
    lib.rtpu_store_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
    lib.rtpu_store_destroy.argtypes = [ctypes.c_void_p]
    lib.rtpu_store_put.restype = ctypes.c_int
    lib.rtpu_store_put.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint64), ctypes.c_char_p, ctypes.c_uint64]
    lib.rtpu_store_seal.restype = ctypes.c_int
    lib.rtpu_store_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rtpu_store_get.restype = ctypes.c_int
    lib.rtpu_store_get.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_int)]
    lib.rtpu_store_pin.restype = ctypes.c_int
    lib.rtpu_store_pin.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_int]
    lib.rtpu_store_delete.restype = ctypes.c_int
    lib.rtpu_store_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rtpu_store_stats.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64)]
    lib.rtpu_store_base.restype = ctypes.c_void_p
    lib.rtpu_store_base.argtypes = [ctypes.c_void_p]
    lib.rtpu_store_leak_mapping.restype = None
    lib.rtpu_store_leak_mapping.argtypes = [ctypes.c_void_p]
    with _lock:
        _lib = lib
    return lib
