"""Llama-3-family transformer, TPU-first.

The flagship model for the BASELINE configs ("Llama-3-8B pretraining … v5p-64",
"Llama-3-8B serving … v5e-16"). The reference has no in-tree model — it
delegates to torch/vLLM; here the model is native JAX so the whole stack
(sharding, ring attention, pipeline, serving KV cache) composes:

- parameters are a pytree with a stacked layer dim and logical axis names, so
  any mesh (DP/FSDP/TP/CP) is a rule-table swap (ray_tpu.parallel.sharding);
- the layer loop is `lax.scan` → O(1) compile size at any depth;
- attention routes to ring attention over the "context" axis for long
  sequences (SURVEY.md §5.7) and to the Pallas flash kernel on TPU;
- GQA + RoPE + RMSNorm + SwiGLU, bf16 activations, fp32 RMSNorm accumulation
  (MXU-friendly shapes: head_dim 128, ffn multiples of 1024).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_dim: int = 14336
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    # attention implementation: "dense" | "ring" | "flash"
    attn_impl: str = "dense"
    remat: bool = True
    # checkpoint policy: "full" recomputes everything; "dots" saves matmul
    # outputs (jax.checkpoint_policies.dots_with_no_batch_dims_saveable) —
    # less recompute, more HBM; "outs" saves only block outputs
    remat_policy: str = "full"
    # cross-entropy chunk (sequence positions whose fp32 logits are live at
    # once); bigger = less scan serialization, more HBM. T (or more) = one
    # chunk, i.e. effectively unchunked.
    ce_chunk: int = 256
    # Rematerialize CE logits in the backward (checkpoint on the CE chunk
    # body). True = recompute the lm_head matmul in bwd, smallest peak HBM.
    # False = keep each chunk's fp32 logits as residuals — one extra
    # B*T*V fp32 tensor live across the backward, but the recompute matmul
    # disappears: measured 33 ms/step (0.572 -> 0.60 MFU) at 1.5B/b4/
    # seq2048 on one v5e where the 4.2 GB residual fits. Keep True for
    # HBM-tight configs (bigger batch/model per chip).
    ce_remat: bool = True
    # MLP matmul implementation for the TRAIN path: "bf16" (default) or
    # "int8" — dynamic per-tensor symmetric quantization of both operands
    # into the MXU's int8 path (2x bf16 peak on v5e), fp32 accumulation,
    # straight-through bf16 backward. Measured lever from VERDICT r3 item 8.
    mlp_impl: str = "bf16"

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads


def llama3_8b(**kw) -> LlamaConfig:
    return LlamaConfig(**kw)


def llama3_1b(**kw) -> LlamaConfig:
    """~1.2B-param config (bench-friendly on one v5e chip)."""
    d = dict(dim=2048, n_layers=16, n_heads=16, n_kv_heads=8, ffn_dim=8192,
             vocab_size=128256)
    d.update(kw)
    return LlamaConfig(**d)


def llama_tiny(**kw) -> LlamaConfig:
    """Test config: runs on the 8-device CPU mesh in seconds."""
    d = dict(vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
             ffn_dim=128, max_seq_len=256, dtype=jnp.float32, remat=False)
    d.update(kw)
    return LlamaConfig(**d)


def num_params(cfg: LlamaConfig) -> int:
    per_layer = (cfg.dim * (cfg.n_heads + 2 * cfg.n_kv_heads) * cfg.head_dim
                 + cfg.n_heads * cfg.head_dim * cfg.dim
                 + 3 * cfg.dim * cfg.ffn_dim + 2 * cfg.dim)
    return (cfg.vocab_size * cfg.dim * 2 + cfg.dim
            + cfg.n_layers * per_layer)


# ---------------------------------------------------------------------------
# params
# ---------------------------------------------------------------------------

def init_params(rng, cfg: LlamaConfig):
    """Stacked-layer param pytree. Weight layout keeps the contraction dim
    first so matmuls hit the MXU without transposes."""
    k_embed, k_layers, k_out = jax.random.split(rng, 3)
    hd = cfg.head_dim

    def dense(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                * (1.0 / np.sqrt(fan_in))).astype(cfg.dtype)

    def layer(key):
        ks = jax.random.split(key, 7)
        return {
            "attn": {
                "wq": dense(ks[0], (cfg.dim, cfg.n_heads, hd), cfg.dim),
                "wk": dense(ks[1], (cfg.dim, cfg.n_kv_heads, hd), cfg.dim),
                "wv": dense(ks[2], (cfg.dim, cfg.n_kv_heads, hd), cfg.dim),
                "wo": dense(ks[3], (cfg.n_heads, hd, cfg.dim), cfg.dim),
            },
            "mlp": {
                "w_gate": dense(ks[4], (cfg.dim, cfg.ffn_dim), cfg.dim),
                "w_up": dense(ks[5], (cfg.dim, cfg.ffn_dim), cfg.dim),
                "w_down": dense(ks[6], (cfg.ffn_dim, cfg.dim), cfg.ffn_dim),
            },
            "attn_norm": jnp.ones((cfg.dim,), jnp.float32),
            "mlp_norm": jnp.ones((cfg.dim,), jnp.float32),
        }

    layer_keys = jax.random.split(k_layers, cfg.n_layers)
    layers = jax.vmap(layer)(layer_keys)
    return {
        "embed": dense(k_embed, (cfg.vocab_size, cfg.dim), cfg.dim),
        "layers": layers,
        "final_norm": jnp.ones((cfg.dim,), jnp.float32),
        "lm_head": dense(k_out, (cfg.dim, cfg.vocab_size), cfg.dim),
    }


def logical_axes(cfg: LlamaConfig):
    """Logical sharding axes, same structure as params (consumed by
    ray_tpu.parallel.sharding.logical_to_shardings)."""
    return {
        "embed": ("vocab", "embed"),
        "layers": {
            "attn": {
                "wq": ("layers", "embed", "heads", "head_dim"),
                "wk": ("layers", "embed", "kv_heads", "head_dim"),
                "wv": ("layers", "embed", "kv_heads", "head_dim"),
                "wo": ("layers", "heads", "head_dim", "embed"),
            },
            "mlp": {
                "w_gate": ("layers", "embed", "mlp"),
                "w_up": ("layers", "embed", "mlp"),
                "w_down": ("layers", "mlp", "embed"),
            },
            "attn_norm": ("layers", None),
            "mlp_norm": ("layers", None),
        },
        "final_norm": (None,),
        "lm_head": ("embed", "vocab"),
    }


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def rms_norm(x, w, eps):
    x32 = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (x32 * scale * w).astype(x.dtype)


def rope_freqs(cfg: LlamaConfig, positions):
    """positions: [B, T] → (cos, sin) [B, T, head_dim/2], fp32."""
    inv = 1.0 / (cfg.rope_theta ** (
        jnp.arange(0, cfg.head_dim, 2, dtype=jnp.float32) / cfg.head_dim))
    ang = positions.astype(jnp.float32)[..., None] * inv  # [B,T,hd/2]
    return jnp.cos(ang), jnp.sin(ang)


def apply_rope(x, cos, sin):
    """x: [B, T, H, D]; rotate pairs (x[..., ::2], x[..., 1::2])."""
    x1 = x[..., 0::2]
    x2 = x[..., 1::2]
    c = cos[:, :, None, :]
    s = sin[:, :, None, :]
    r1 = x1 * c - x2 * s
    r2 = x2 * c + x1 * s
    out = jnp.stack([r1, r2], axis=-1).reshape(x.shape)
    return out.astype(x.dtype)


def _gqa_expand(k, n_rep):
    if n_rep == 1:
        return k
    b, t, h, d = k.shape
    return jnp.broadcast_to(k[:, :, :, None, :], (b, t, h, n_rep, d)).reshape(
        b, t, h * n_rep, d)


def _quantize_int8(t):
    """Dynamic per-tensor symmetric quantization: t -> (int8, fp32 scale)."""
    s = (jnp.max(jnp.abs(t)).astype(jnp.float32) / 127.0) + 1e-12
    q = jnp.clip(jnp.round(t.astype(jnp.float32) / s), -127, 127)
    return q.astype(jnp.int8), s


@jax.custom_vjp
def int8_matmul(x, w):
    """x @ w with BOTH operands dynamically quantized to int8 and the
    contraction run on the MXU's int8 path with int32 accumulation
    (~1.55x bf16 matmul throughput measured on one v5e at bench shapes).
    Backward is straight-through bf16 (quantization treated as identity) —
    the standard int8-forward training recipe."""
    out, _ = _int8_matmul_fwd(x, w)
    return out


def _int8_matmul_fwd(x, w):
    xq, xs = _quantize_int8(x)
    wq, ws = _quantize_int8(w)
    acc = jax.lax.dot_general(
        xq, wq, (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    out = (acc.astype(jnp.float32) * (xs * ws)).astype(x.dtype)
    # save the QUANTIZED residuals: int8 + scale is half of bf16, which is
    # what lets the int8 path fit where saved-bf16 residuals OOM (measured:
    # +245MB over budget at dots-remat b4 with bf16 residuals). Backward
    # uses the dequantized approximations — consistent with the straight-
    # through estimator the forward already commits to.
    return out, (xq, xs, wq, ws)


def _int8_matmul_bwd(res, g):
    xq, xs, wq, ws = res
    # gradients arrive at the model dtype; dequantized operands join at it
    x = (xq.astype(jnp.float32) * xs).astype(g.dtype)
    w = (wq.astype(jnp.float32) * ws).astype(g.dtype)
    dx = jnp.einsum("...n,kn->...k", g, w)
    dw = jnp.einsum("...k,...n->kn", x, g)
    return dx.astype(g.dtype), dw.astype(g.dtype)


int8_matmul.defvjp(_int8_matmul_fwd, _int8_matmul_bwd)


def _mlp_matmul(h, w, cfg: LlamaConfig):
    if cfg.mlp_impl == "int8":
        return int8_matmul(h, w)
    return h @ w


def _attention(q, k, v, cfg: LlamaConfig, mesh, *, positions_offset=0):
    """Causal self-attention dispatch: ring over the context axis, Pallas
    flash on TPU, einsum fallback."""
    n_rep = cfg.n_heads // cfg.n_kv_heads
    k = _gqa_expand(k, n_rep)
    v = _gqa_expand(v, n_rep)
    if cfg.attn_impl == "ring" and mesh is not None:
        from ray_tpu.parallel.ring_attention import ring_attention
        return ring_attention(q, k, v, mesh, causal=True)
    if cfg.attn_impl == "flash":
        from ray_tpu.ops.attention import flash_attention
        return flash_attention(q, k, v, causal=True)
    sm = cfg.head_dim ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * sm
    t_q, t_k = q.shape[1], k.shape[1]
    q_pos = positions_offset + jnp.arange(t_q)
    mask = q_pos[:, None] >= jnp.arange(t_k)[None, :]
    logits = jnp.where(mask[None, None], logits, -1e30)
    p = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _layer_fwd(x, layer, cos, sin, cfg: LlamaConfig, mesh):
    from jax.ad_checkpoint import checkpoint_name
    h = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
    q = checkpoint_name(
        jnp.einsum("btd,dhk->bthk", h, layer["attn"]["wq"]), "q_proj")
    k = checkpoint_name(
        jnp.einsum("btd,dhk->bthk", h, layer["attn"]["wk"]), "k_proj")
    v = checkpoint_name(
        jnp.einsum("btd,dhk->bthk", h, layer["attn"]["wv"]), "v_proj")
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    attn = checkpoint_name(_attention(q, k, v, cfg, mesh), "attn")
    attn_out = checkpoint_name(
        jnp.einsum("bthk,hkd->btd", attn, layer["attn"]["wo"]), "attn_out")
    x = x + attn_out
    h = checkpoint_name(
        rms_norm(x, layer["mlp_norm"], cfg.norm_eps), "mlp_in")
    gate = jax.nn.silu(_mlp_matmul(h, layer["mlp"]["w_gate"], cfg))
    up = _mlp_matmul(h, layer["mlp"]["w_up"], cfg)
    x = x + checkpoint_name(
        _mlp_matmul(gate * up, layer["mlp"]["w_down"], cfg), "mlp_out")
    return x


def _remat(body, cfg: LlamaConfig):
    """Wrap a scan body in jax.checkpoint per cfg.remat_policy.

    "full": recompute everything (min HBM, ~4/3x matmul FLOPs).
    "dots": save every matmul output — includes the d_ff-wide MLP
        intermediates, ~0.5 GB/layer at B8/T2048/d2048 (OOMs one v5e at
        1.5B params even with adafactor).
    "outs": save only the residual-stream contributions (attn_out/mlp_out,
        checkpoint_name'd above) — 1/8 the HBM of "dots"; the backward
        re-runs QKV+attention+MLP but reuses the saved block outputs.
    "hybrid": save everything EXCEPT the d_ff-wide gate/up intermediates
        (q/k/v, attention + its softmax stats, attn_out, mlp_in, mlp_out — ~1/3 the HBM of
        "dots"): the backward recomputes only the two wide MLP matmuls
        (~0.4x of one forward), trading a small FLOPs tax for the HBM to
        run batch 8 where "dots" caps at 4 — narrower than the MXU likes.
        (The standard selective-checkpointing middle ground between "save
        all dots" and "save block outputs".)"""
    if cfg.remat_policy == "dots":
        return jax.checkpoint(
            body,
            policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
    if cfg.remat_policy == "hybrid":
        return jax.checkpoint(
            body,
            policy=jax.checkpoint_policies.save_only_these_names(
                "q_proj", "k_proj", "v_proj", "attn", "attn_lse", "attn_out",
                "mlp_in", "mlp_out"))
    if cfg.remat_policy == "outs":
        return jax.checkpoint(
            body,
            policy=jax.checkpoint_policies.save_only_these_names(
                "attn_out", "mlp_out"))
    return jax.checkpoint(body)


def forward(params, tokens, cfg: LlamaConfig, mesh=None):
    """tokens [B, T] → logits [B, T, vocab]."""
    x = hidden_states(params, tokens, cfg, mesh)
    return (x @ params["lm_head"]).astype(jnp.float32)


def hidden_states(params, tokens, cfg: LlamaConfig, mesh=None):
    """tokens [B, T] → final-norm hidden states [B, T, D] (no lm_head)."""
    b, t = tokens.shape
    x = params["embed"][tokens].astype(cfg.dtype)
    positions = jnp.broadcast_to(jnp.arange(t), (b, t))
    cos, sin = rope_freqs(cfg, positions)

    def body(x, layer):
        return _layer_fwd(x, layer, cos, sin, cfg, mesh), None

    if cfg.remat:
        body = _remat(body, cfg)
    x, _ = jax.lax.scan(body, x, params["layers"])
    return rms_norm(x, params["final_norm"], cfg.norm_eps)


def chunked_cross_entropy(lm_head, hidden, targets, chunk: int = 256,
                          remat: bool = True):
    """Next-token CE without ever materializing fp32 [B, T, vocab].

    The naive log_softmax over the full sequence allocates B·T·V fp32 —
    7.8 GiB at B=8, T=2048, V=128k, more than half a v5e's HBM. Scanning
    sequence chunks keeps the live logits at B·chunk·V and lets XLA overlap
    the lm_head matmul of one chunk with the reduction of the previous.

    ``remat=False`` drops the checkpoint: each chunk's fp32 logits persist
    as backward residuals (full B·T·V again, but live only across the CE
    backward region) in exchange for skipping the lm_head recompute matmul
    — measured 33 ms/step at 1.5B/b4/seq2048 (see LlamaConfig.ce_remat).
    """
    b, t, d = hidden.shape
    chunk = min(chunk, t)
    n = -(-t // chunk)  # pad the tail: next-token CE always sees t = T-1,
    # which is never divisible by a power-of-two chunk — an exact-division
    # fallback would silently collapse to one full-logits chunk
    pad = n * chunk - t
    if pad:
        hidden = jnp.pad(hidden, ((0, 0), (0, pad), (0, 0)))
        targets = jnp.pad(targets, ((0, 0), (0, pad)), constant_values=-1)
    hid = hidden.reshape(b, n, chunk, d).transpose(1, 0, 2, 3)
    tgt = targets.reshape(b, n, chunk).transpose(1, 0, 2)

    def body(acc, xs):
        h, y = xs
        logits = (h @ lm_head).astype(jnp.float32)       # [B, chunk, V]
        lse = jax.nn.logsumexp(logits, axis=-1)
        ll = jnp.take_along_axis(
            logits, jnp.maximum(y, 0)[..., None], axis=-1)[..., 0] - lse
        ll = jnp.where(y >= 0, ll, 0.0)  # padded positions contribute 0
        return acc + jnp.sum(ll), None

    if remat:
        # checkpoint: without it the scan's backward saves EVERY chunk's
        # fp32 logits as residuals — the full B·T·V tensor again
        body = jax.checkpoint(body)
    total, _ = jax.lax.scan(body, jnp.float32(0.0), (hid, tgt))
    return -total / (b * t)


def loss_fn(params, batch, cfg: LlamaConfig, mesh=None):
    """Next-token cross-entropy; batch: {"tokens": [B, T+1]} or tokens array."""
    tokens = batch["tokens"] if isinstance(batch, dict) else batch
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    hidden = hidden_states(params, inputs, cfg, mesh)
    return chunked_cross_entropy(params["lm_head"], hidden, targets,
                                 chunk=cfg.ce_chunk, remat=cfg.ce_remat)


# ---------------------------------------------------------------------------
# decode path (serving): single-token step against a KV cache
# ---------------------------------------------------------------------------

def init_kv_cache(cfg: LlamaConfig, batch: int, max_len: int | None = None):
    max_len = max_len or cfg.max_seq_len
    shape = (cfg.n_layers, batch, max_len, cfg.n_kv_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, cfg.dtype), "v": jnp.zeros(shape, cfg.dtype),
            "length": jnp.zeros((batch,), jnp.int32)}


def decode_step(params, cache, tokens, cfg: LlamaConfig):
    """One decode step for a batch of sequences (continuous-batching inner op).

    tokens: [B] current token per sequence; cache holds per-sequence lengths.
    Returns (logits [B, vocab], new_cache).
    """
    b = tokens.shape[0]
    x = params["embed"][tokens[:, None]].astype(cfg.dtype)  # [B,1,D]
    positions = cache["length"][:, None]  # [B,1]
    cos, sin = rope_freqs(cfg, positions)
    max_len = cache["k"].shape[2]
    pos_mask = jnp.arange(max_len)[None, :] <= cache["length"][:, None]  # [B,L]

    def body(carry, inputs):
        x, = carry
        layer, k_cache, v_cache = inputs
        h = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        q = jnp.einsum("btd,dhk->bthk", h, layer["attn"]["wq"])
        k = jnp.einsum("btd,dhk->bthk", h, layer["attn"]["wk"])
        v = jnp.einsum("btd,dhk->bthk", h, layer["attn"]["wv"])
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        # write k/v at each sequence's current length
        onehot = jax.nn.one_hot(cache["length"], max_len, dtype=k.dtype)  # [B,L]
        k_cache = k_cache * (1 - onehot[..., None, None]) + (
            onehot[..., None, None] * k[:, 0][:, None])
        v_cache = v_cache * (1 - onehot[..., None, None]) + (
            onehot[..., None, None] * v[:, 0][:, None])
        n_rep = cfg.n_heads // cfg.n_kv_heads
        k_full = _gqa_expand(k_cache, n_rep)
        v_full = _gqa_expand(v_cache, n_rep)
        sm = cfg.head_dim ** -0.5
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k_full).astype(jnp.float32) * sm
        logits = jnp.where(pos_mask[:, None, None, :], logits, -1e30)
        p = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
        attn = jnp.einsum("bhqk,bkhd->bqhd", p, v_full)
        x = x + jnp.einsum("bthk,hkd->btd", attn, layer["attn"]["wo"])
        h2 = rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
        gate = jax.nn.silu(h2 @ layer["mlp"]["w_gate"])
        up = h2 @ layer["mlp"]["w_up"]
        x = x + (gate * up) @ layer["mlp"]["w_down"]
        return (x,), (k_cache, v_cache)

    (x,), (new_k, new_v) = jax.lax.scan(
        body, (x,), (params["layers"], cache["k"], cache["v"]))
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = (x[:, 0] @ params["lm_head"]).astype(jnp.float32)
    new_cache = {"k": new_k, "v": new_v, "length": cache["length"] + 1}
    return logits, new_cache


def prefill(params, cache, tokens, cfg: LlamaConfig, lengths=None):
    """Prefill the KV cache with prompt tokens [B, T_prompt]; returns logits of
    the last position per sequence and the filled cache."""
    b, t = tokens.shape
    if lengths is None:
        lengths = jnp.full((b,), t, jnp.int32)
    x = params["embed"][tokens].astype(cfg.dtype)
    positions = jnp.broadcast_to(jnp.arange(t), (b, t))
    cos, sin = rope_freqs(cfg, positions)
    max_len = cache["k"].shape[2]

    def body(carry, inputs):
        x, = carry
        layer, k_cache, v_cache = inputs
        h = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        q = jnp.einsum("btd,dhk->bthk", h, layer["attn"]["wq"])
        k = jnp.einsum("btd,dhk->bthk", h, layer["attn"]["wk"])
        v = jnp.einsum("btd,dhk->bthk", h, layer["attn"]["wv"])
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        attn = _attention(q, k, v, cfg, None)
        x = x + jnp.einsum("bthk,hkd->btd", attn, layer["attn"]["wo"])
        h2 = rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
        gate = jax.nn.silu(h2 @ layer["mlp"]["w_gate"])
        up = h2 @ layer["mlp"]["w_up"]
        x = x + (gate * up) @ layer["mlp"]["w_down"]
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, k.astype(k_cache.dtype), (0, 0, 0, 0))
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, v.astype(v_cache.dtype), (0, 0, 0, 0))
        return (x,), (k_cache, v_cache)

    (x,), (new_k, new_v) = jax.lax.scan(
        body, (x,), (params["layers"], cache["k"], cache["v"]))
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    last = jnp.take_along_axis(x, (lengths - 1)[:, None, None], axis=1)[:, 0]
    logits = (last @ params["lm_head"]).astype(jnp.float32)
    return logits, {"k": new_k, "v": new_v, "length": lengths}


# ---------------------------------------------------------------------------
# checkpoint io (flat-npz format; the serving engine's checkpoint_path and
# offline eval both read it — reference models load torch/safetensors via
# vLLM; here the canonical on-disk form is a flattened jax pytree)
# ---------------------------------------------------------------------------

def _flatten_params(params, prefix=""):
    out = {}
    if isinstance(params, dict):
        for k, v in params.items():
            out.update(_flatten_params(v, f"{prefix}{k}/"))
        return out
    out[prefix.rstrip("/")] = np.asarray(params)
    return out


def save_params(params, path: str) -> str:
    """Write params as ONE .npz of flattened pytree paths (atomic rename).
    `path` may be a file ('x.npz') or a directory (-> dir/params.npz)."""
    import os
    if not path.endswith(".npz"):
        os.makedirs(path, exist_ok=True)
        path = os.path.join(path, "params.npz")
    tmp = path + ".tmp.npz"  # keep the suffix: np.savez appends it otherwise
    try:
        np.savez(tmp, **_flatten_params(params))
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return path


def load_params(path: str, cfg: LlamaConfig | None = None):
    """Load a save_params checkpoint back into the nested pytree. With a
    cfg, shapes are validated against a fresh init's structure."""
    import os
    if os.path.isdir(path):
        path = os.path.join(path, "params.npz")
    flat = np.load(path)
    params: dict = {}
    for key in flat.files:
        node = params
        parts = key.split("/")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = jnp.asarray(flat[key])
    if cfg is not None:
        expect = jax.eval_shape(lambda: init_params(jax.random.PRNGKey(0), cfg))
        exp_flat = _flatten_params_shapes(expect)
        got_flat = {k: tuple(np.asarray(flat[k]).shape) for k in flat.files}
        if exp_flat != got_flat:
            missing = set(exp_flat) - set(got_flat)
            extra = set(got_flat) - set(exp_flat)
            mismatched = {k for k in set(exp_flat) & set(got_flat)
                          if exp_flat[k] != got_flat[k]}
            raise ValueError(
                f"checkpoint does not match config: missing={sorted(missing)[:5]} "
                f"extra={sorted(extra)[:5]} shape-mismatch={sorted(mismatched)[:5]}")
    return params


def _flatten_params_shapes(tree, prefix=""):
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten_params_shapes(v, f"{prefix}{k}/"))
        return out
    out[prefix.rstrip("/")] = tuple(tree.shape)
    return out
