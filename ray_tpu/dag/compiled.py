"""CompiledPipeline: bind actors -> compile to a channel chain -> execute.

Reference parity: python/ray/dag/compiled_dag_node.py:805 (CompiledDAG —
bind, experimental_compile, execute returning a ref) re-shaped for this
runtime: stages are existing actors, each edge is one mutable channel
(writer on the producing stage's node, agent-relayed across nodes), and a
stage runs a resident loop task (via the generic ``__rtpu_call__`` actor
entry) instead of per-call task submission.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Optional

from ray_tpu.core.channel import Channel, ChannelClosedError

_OUT_ATTR = "__rtpu_pipe_out__"


def _stage_setup(inst, capacity: int):
    """Runs ON the stage actor: create its output channel locally (a
    channel's writer must live on the writing node) and hand back a
    location-transparent reader for the next stage."""
    ch = Channel(capacity=capacity, num_readers=1)
    setattr(inst, _OUT_ATTR, ch)
    return ch.remote_reader(0)


def _stage_loop(inst, in_reader, method_name: str):
    """Runs ON the stage actor for the pipeline's lifetime: read → method →
    write. Ends (and closes the downstream edge, cascading teardown) when
    the upstream channel closes."""
    out: Channel = getattr(inst, _OUT_ATTR)
    method = getattr(inst, method_name)
    processed = 0
    try:
        while True:
            try:
                value = in_reader.read(timeout=None)
            except ChannelClosedError:
                return processed
            out.write(method(value), timeout=None)
            processed += 1
    finally:
        out.close()
        if hasattr(in_reader, "close"):
            in_reader.close()


def _stage_unlink(inst):
    """Runs ON the stage actor after its loop task has exited (queued
    behind it on the actor's slots): drop the out channel's /dev/shm name.
    Deferred to close() rather than the loop's finally because a
    downstream reader attaches lazily on first read — unlinking at loop
    exit could delete the segment before a late-starting consumer (or the
    driver's result reader) ever opened it."""
    ch = getattr(inst, _OUT_ATTR, None)
    if ch is not None:
        ch.unlink()


class PipelineRef:
    """Result handle for one execute() (the compiled-DAG 'ref'): get()
    blocks for that execution's output, delivered in submission order."""

    def __init__(self, pipe: "CompiledPipeline", index: int):
        self._pipe = pipe
        self._index = index

    def get(self, timeout: Optional[float] = 60.0):
        return self._pipe._result(self._index, timeout)


class CompiledPipeline:
    """A linear actor pipeline compiled onto mutable channels.

    >>> pipe = CompiledPipeline([(a, "prep"), (b, "infer")]).compile()
    >>> ref = pipe.execute(batch)      # write-side, returns immediately
    >>> out = ref.get()                # read-side, in submission order

    The stage actors keep running their loop task until close(); while
    compiled, calls submitted through the pipeline bypass task submission
    entirely (one shm write per hop; agent relay across nodes).
    """

    def __init__(self, stages: list, capacity: int = 8 * 1024 * 1024):
        if not stages:
            raise ValueError("pipeline needs at least one stage")
        self._stages = [(s if isinstance(s, tuple) else (s, "__call__"))
                        for s in stages]
        self._capacity = capacity
        self._input: Optional[Channel] = None
        self._out_reader = None
        self._loop_refs: list = []
        self._lock = threading.Lock()
        # writers serialize on a SEPARATE lock: index assignment and the
        # channel write must be atomic together (or two concurrent
        # execute()s could write in the opposite order of their indices and
        # cross-wire results), but the write may block on backpressure and
        # the drain side (_result) needs _lock to make progress
        self._write_lock = threading.Lock()
        self._submitted = 0
        self._delivered = 0
        self._results: dict[int, Any] = {}
        self._closed = False

    def compile(self) -> "CompiledPipeline":
        import ray_tpu

        self._input = Channel(capacity=self._capacity, num_readers=1)
        prev_reader = self._input.remote_reader(0)
        for actor, method in self._stages:
            out_reader = ray_tpu.get(
                actor.__rtpu_call__.remote(_stage_setup, self._capacity),
                timeout=60.0)
            # resident stage loop: occupies one of the actor's concurrency
            # slots until close()
            self._loop_refs.append(
                actor.__rtpu_call__.remote(_stage_loop, prev_reader, method))
            prev_reader = out_reader
        self._out_reader = prev_reader
        return self

    def execute(self, value) -> PipelineRef:
        if self._input is None:
            raise RuntimeError("pipeline not compiled (call .compile())")
        if self._closed:
            raise RuntimeError("pipeline closed")
        with self._write_lock:
            with self._lock:
                # Bounded in-flight (reference: CompiledDAG
                # max_buffered_results — dag/compiled_dag_node.py raises
                # rather than deadlock): each hop buffers ONE value, so a
                # single-threaded caller submitting past the chain's slot
                # count would block in write() with the drain side never
                # reached. stages+1 is a safe lower bound of the chain's
                # capacity (input slot + one per stage output; relays and
                # in-hand values only add slack).
                limit = len(self._stages) + 1
                if self._submitted - self._delivered >= limit:
                    raise RuntimeError(
                        f"{limit} executions already in flight; get() some "
                        "results before submitting more (each pipeline hop "
                        "buffers one value)")
                idx = self._submitted
                self._submitted += 1
            self._input.write(value, timeout=None)
        return PipelineRef(self, idx)

    def _result(self, index: int, timeout: Optional[float]):
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while index not in self._results:
                if self._delivered > index:
                    raise RuntimeError(
                        f"pipeline result {index} already consumed")
                # single-threaded drain under the lock: deliver in order.
                # The whole drain shares ONE deadline — without it, get()
                # for index N could block (N-delivered+1)*timeout while
                # holding _lock against concurrent execute() callers.
                remaining = None if deadline is None else \
                    max(0.0, deadline - time.monotonic())
                value = self._out_reader.read(timeout=remaining)
                self._results[self._delivered] = value
                self._delivered += 1
            return self._results.pop(index)

    def close(self, timeout: float = 30.0) -> None:
        """Tear down: close the input edge; closure cascades stage by stage
        and each loop task returns its processed count."""
        if self._closed or self._input is None:
            return
        self._closed = True
        import ray_tpu

        self._input.close()
        try:
            ray_tpu.get(self._loop_refs, timeout=timeout)
        except Exception:  # noqa: BLE001 - teardown is best-effort
            pass
        # attach the result reader BEFORE any unlink so values still
        # buffered in the final channel stay readable after close()
        try:
            if hasattr(self._out_reader, "_ensure"):
                self._out_reader._ensure()
        except Exception:  # noqa: BLE001
            pass
        # reclaim every stage's out segment (ordered behind the loop task
        # on each actor's slots, so a hung stage just skips its unlink)
        try:
            ray_tpu.get([a.__rtpu_call__.remote(_stage_unlink)
                         for a, _ in self._stages], timeout=10.0)
        except Exception:  # noqa: BLE001
            pass
        if hasattr(self._out_reader, "close"):
            self._out_reader.close()
        self._input.unlink()
