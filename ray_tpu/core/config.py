"""Config/flag system.

TPU-native analog of the reference's RAY_CONFIG flag table
(/root/reference/src/ray/common/ray_config_def.h, ray_config.h:60-72): every flag
has a typed default, is overridable by the environment variable ``RAY_TPU_<name>``,
and by the ``_system_config`` dict passed to ``ray_tpu.init`` (propagated to all
spawned processes through the environment).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field, fields
from typing import Any

_ENV_PREFIX = "RAY_TPU_"
_SYSTEM_CONFIG_ENV = "RAY_TPU_SYSTEM_CONFIG"


def _coerce(value: str, typ: type) -> Any:
    if typ is bool:
        return value.lower() in ("1", "true", "yes", "on")
    if typ is int:
        return int(value)
    if typ is float:
        return float(value)
    return value


@dataclass
class Config:
    """All runtime flags. Field name == flag name."""

    # --- object store ---
    # Objects at or below this size are returned inline to the owner's
    # in-process memory store (ref: ray_config_def.h max_direct_call_object_size).
    max_inline_object_size: int = 100 * 1024
    # Default shared-memory store capacity per node (bytes).
    object_store_memory: int = 512 * 1024 * 1024
    # Evict-on-full policy headroom fraction.
    object_store_eviction_headroom: float = 0.1
    # Use the native C++ shared-memory store if built; fall back to pure python.
    use_native_object_store: bool = True
    # Spill sealed+unpinned objects to disk instead of evicting them
    # (ref: local_object_manager.h:44 SpillObjects).
    enable_object_spilling: bool = True
    spill_dir: str = ""
    # Pull admission control: max bytes of concurrent inbound object pulls
    # (ref: pull_manager.h:49 bundle admission).
    max_inflight_pull_bytes: int = 256 * 1024 * 1024

    # --- scheduling ---
    # Max worker processes per node agent (0 = num_cpus).
    max_workers_per_node: int = 0
    # Idle worker keep-alive before reaping (seconds).
    idle_worker_ttl_s: float = 300.0
    # Lease request timeout.
    lease_timeout_s: float = 60.0
    # Hybrid scheduling policy: prefer local node until its utilization
    # exceeds this threshold, then pack remote nodes by score
    # (ref: hybrid_scheduling_policy.cc).
    hybrid_threshold: float = 0.5
    # Weight of ICI distance in node scoring (TPU-native addition).
    ici_distance_weight: float = 0.2

    # --- control-plane persistence ---
    # Path for the control plane's durable metadata store (sqlite). Empty =
    # in-memory only (CP restart loses the cluster; ref: redis_store_client).
    cp_store_path: str = ""

    # --- memory / OOM protection (ref: memory_monitor.h:52) ---
    # Kill the newest killable worker when host memory use crosses this
    # fraction; 0 disables the monitor.
    memory_usage_threshold: float = 0.95
    memory_monitor_interval_s: float = 1.0

    # --- fault tolerance ---
    task_max_retries: int = 3
    actor_max_restarts: int = 0
    # Enable lineage-based reconstruction of lost shared-memory objects
    # (ref: object_recovery_manager.h:41).
    enable_object_reconstruction: bool = True
    # Health-check period/timeout (ref: gcs_health_check_manager.h:45).
    health_check_period_s: float = 1.0
    health_check_timeout_s: float = 10.0
    health_check_failure_threshold: int = 5
    # Agent resource-heartbeat period. Each beat scans /proc for system
    # gauges; many-node single-host harnesses (scale tests: 50+ in-process
    # agents) raise this so heartbeat CPU doesn't crowd out the workload.
    agent_heartbeat_interval_s: float = 1.0
    # Graceful drain (ref: node_manager.proto:448 DrainRaylet): how long a
    # DRAINING node may run in-flight leases to completion before the CP
    # finalizes the drain anyway. In-flight work past the deadline is lost
    # (the same as a kill), so size it to the workload's task length.
    drain_deadline_s: float = 30.0

    # --- watchdog ---
    # get()/wait() called with no explicit timeout raise GetTimeoutError
    # after this many seconds. Default 0 = disabled: bare get() blocks
    # indefinitely, matching the reference's ray.get semantics — a
    # legitimate multi-hour driver-side get on a training task must not
    # fail in production. Opt in (RAY_TPU_BLOCKING_WATCHDOG_S) to convert
    # wedges into loud GetTimeoutErrors; the test suite pins it to 300 so
    # a wedge surfaces in minutes (tests/conftest.py).
    blocking_watchdog_s: float = 0.0

    # --- streaming generator returns ---
    # Max streamed items the producer may run AHEAD OF THE CONSUMER's
    # cursor (ref: generator_backpressure_num_objects).
    streaming_backpressure_items: int = 16

    # --- data (streaming executor; ref: resource_manager.py budgets) ---
    # Read tasks stream blocks through ObjectRefGenerators (first block
    # flows downstream before the datasource finishes). Default OFF: an
    # intermittent libarrow fault under the early-exit (take/limit) cancel
    # path is still being chased — see tests/test_data.py
    # test_streaming_read_incremental, which opts in.
    data_streaming_reads: bool = False
    # Per-operator cap on BYTES of input blocks with in-flight transform
    # tasks (a 100 MB block charges 100 MB, not "1 task").
    data_op_inflight_bytes: int = 128 * 1024 * 1024
    # Per-operator cap on bytes buffered in its output queue.
    data_op_output_buffer_bytes: int = 128 * 1024 * 1024

    # --- serve robustness (serve/proxy.py, core/deadline.py) ---
    # Default end-to-end request deadline when the client sends no
    # X-Request-Deadline / X-Request-Timeout-S header and the deployment
    # sets no request_timeout_s. Every internal wait on the request path is
    # bounded by the REMAINING budget ("The Tail at Scale": refuse expired
    # work, never wait past the deadline, cancel on expiry).
    serve_request_timeout_s: float = 60.0
    # Proxy admission control: requests beyond this many concurrently
    # in-flight are shed with a fast 503 + Retry-After instead of queueing.
    proxy_max_inflight: int = 1000

    # --- rpc ---
    rpc_connect_timeout_s: float = 10.0
    # A refused connect means nothing is listening: peers publish their
    # address only after binding, so refusal almost always means the
    # process is gone. Retry refused connects only this long (port-reuse
    # grace), not the full connect budget — otherwise every caller that
    # races a death (the CP's publish fan-out, the submitters' shared
    # flusher) wedges for rpc_connect_timeout_s per dead peer.
    rpc_refused_grace_s: float = 1.0
    rpc_retries: int = 3
    # Deterministic fault injection: "method:prob_req:prob_resp,..."
    # (ref: rpc_chaos.cc, ray_config_def.h:842-849).
    testing_rpc_failure: str = ""

    # --- task events / observability ---
    task_events_buffer_size: int = 10000
    task_events_flush_interval_s: float = 1.0
    # Distributed tracing (observability/tracing.py). Head-based sampling:
    # the root caller rolls tracing_sample_rate once; the decision
    # propagates by carrier presence, so rate 0 / disabled leaves the hot
    # path span-free everywhere.
    tracing_enabled: bool = False
    tracing_sample_rate: float = 1.0
    # finished spans per report_spans RPC (also flushed when the local
    # span stack unwinds and on shutdown)
    trace_flush_batch: int = 256
    # control-plane trace store: evict whole oldest traces past this
    # total span count (bounded ring, ref: GcsTaskManager's bounded sink)
    trace_store_max_spans: int = 50000
    # Critical-path attribution (observability/attribution.py): per-request
    # stage timelines stamped at the proxy/router/engine; SLO-violating
    # requests persist full timelines to the CP exemplar store. Stamping is
    # host-side dict appends (A/B-bounded by `bench_serve.py --slo-ab`).
    slo_attribution_enabled: bool = True
    # CP exemplar store cap: oldest records evict first past this
    slo_exemplar_max_records: int = 512
    # Metrics pipeline (util/metrics.py MetricsFlusher → CP TimeSeriesStore).
    # Every worker/driver/node-agent process runs one background flusher
    # pushing delta snapshots on this period (plus once on clean shutdown).
    metrics_enabled: bool = True
    metrics_flush_interval_s: float = 10.0
    # CP-outage tolerance: delta snapshots that fail to publish are kept
    # (original timestamps) and folded into the next flush instead of
    # dropped. Bounded: past this many unsent payloads the OLDEST drops
    # first. At the default 10s flush period, 32 payloads ≈ 5 minutes of
    # CP outage with zero counter loss.
    metrics_flush_buffer_max: int = 32
    # Same for the trace flusher: spans whose report_spans RPC failed are
    # re-queued at the buffer head, bounded to this many spans.
    trace_flush_buffer_max: int = 4096
    # CP time-series retention: points older than the window are evicted;
    # a series past the point cap is downsampled (every other point of its
    # older half dropped) instead of hard-truncated.
    metrics_retention_s: float = 3600.0
    metrics_max_points_per_series: int = 1024
    # Flight recorder (observability/events.py): structured cluster
    # events batch-flushed to a bounded CP journal. Emit is a host-side
    # dict append + queue push (A/B-bounded by `bench_serve.py
    # --events-ab`); the flusher keeps unsent batches across CP outages,
    # bounded to this many payloads with oldest-first eviction.
    events_enabled: bool = True
    events_flush_interval_s: float = 2.0
    events_flush_buffer_max: int = 64
    # CP journal retention: past the cap, older INFOs downsample first
    # (every other one of the older half drops), then the oldest
    # non-ERROR evicts — ERRORs outlive chatty INFO streams.
    events_max_records: int = 2048

    # --- misc ---
    worker_register_timeout_s: float = 30.0
    # runtime_env["pip"] needs network access; opt in explicitly
    # (RAY_TPU_ALLOW_RUNTIME_ENV_PIP=1).
    allow_runtime_env_pip: bool = False
    # Cached runtime-env eviction (ref: _private/runtime_env/uri_cache.py):
    # LRU over /tmp/ray_tpu_envs, keeping at most max_envs entries; entries
    # used within min_age_s are never evicted (a live worker may hold one).
    runtime_env_cache_max_envs: int = 16
    runtime_env_cache_min_age_s: float = 600.0
    log_dir: str = ""
    # Stream worker stdout/stderr to the driver (ref: _private/log_monitor.py
    # + worker.py log_to_driver).
    log_to_driver: bool = True
    log_monitor_interval_s: float = 0.3

    def __post_init__(self) -> None:
        # env overrides
        for f in fields(self):
            env = os.environ.get(_ENV_PREFIX + f.name.upper())
            if env is not None:
                setattr(self, f.name, _coerce(env, f.type if isinstance(f.type, type) else type(getattr(self, f.name))))
        # _system_config propagated via env (JSON)
        blob = os.environ.get(_SYSTEM_CONFIG_ENV)
        if blob:
            self.apply(json.loads(blob))

    def apply(self, overrides: dict[str, Any] | None) -> None:
        if not overrides:
            return
        for k, v in overrides.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown system config flag: {k}")
            setattr(self, k, v)

    def to_env(self, overrides: dict[str, Any] | None = None) -> dict[str, str]:
        """Serialize overrides for child process environments."""
        merged = dict(overrides or {})
        return {_SYSTEM_CONFIG_ENV: json.dumps(merged)} if merged else {}


def package_parent_path() -> str:
    """Directory containing the ray_tpu package — prepended to PYTHONPATH of
    spawned processes (workers, job drivers) so the framework stays
    importable when a runtime_env or entrypoint changes their cwd."""
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


_config: Config | None = None


def get_config() -> Config:
    global _config
    if _config is None:
        _config = Config()
    return _config


def reset_config() -> None:
    global _config
    _config = None
