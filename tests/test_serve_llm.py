"""LLM serving tests (models the reference's llm serve tests:
python/ray/llm/tests/serve/ — engine correctness, OpenAI API shape,
streaming). Runs tiny-Llama on CPU."""

import json
import time
import urllib.request

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module")
def ray_start_regular(ray_start_module):
    yield ray_start_module



def _tiny_cfg(**kw):
    from ray_tpu.models import llama
    from ray_tpu.serve.llm import LLMConfig

    d = dict(model_config=llama.llama_tiny(vocab_size=512),
             max_batch_size=4, page_size=16, num_pages=64,
             max_prompt_len=64, max_seq_len=128, max_tokens=8)
    d.update(kw)
    return LLMConfig(**d)


def test_paged_decode_matches_dense_forward():
    """Greedy decode through the paged KV cache must reproduce the dense
    forward pass logits step by step."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import llama
    from ray_tpu.serve.llm import kv_cache as kvc

    cfg = llama.llama_tiny(vocab_size=128)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    page_size = 8
    num_pages = 16
    max_pages = 4  # 32 positions

    prompt = np.array([[5, 9, 2, 7, 1]], np.int32)
    plen = prompt.shape[1]

    kv = kvc.init_paged_cache(cfg, num_pages, page_size)
    table = np.zeros((max_pages,), np.int32)
    table[:max_pages] = [3, 4, 5, 6]  # arbitrary non-contiguous pages

    logits_p, kv = kvc.paged_prefill(
        params, kv, jnp.asarray(table), jnp.asarray(prompt),
        jnp.int32(plen), cfg, page_size)

    dense = llama.forward(params, jnp.asarray(prompt), cfg)
    np.testing.assert_allclose(
        np.asarray(logits_p), np.asarray(dense[0, plen - 1]),
        rtol=2e-3, atol=2e-3)

    # three greedy decode steps vs dense forward over the growing sequence
    seq = list(prompt[0])
    page_tables = np.zeros((1, max_pages), np.int32)
    page_tables[0] = table
    seq_lens = jnp.asarray([plen], jnp.int32)
    tok = int(np.argmax(np.asarray(logits_p)))
    for _ in range(3):
        seq.append(tok)
        logits_d, kv, seq_lens = kvc.paged_decode_step(
            params, kv, jnp.asarray(page_tables), seq_lens,
            jnp.asarray([tok], jnp.int32), cfg, page_size)
        dense = llama.forward(params, jnp.asarray([seq], jnp.int32), cfg)
        np.testing.assert_allclose(
            np.asarray(logits_d[0]), np.asarray(dense[0, -1]),
            rtol=2e-3, atol=2e-3)
        tok = int(np.argmax(np.asarray(logits_d[0])))


def test_engine_greedy_matches_reference_loop():
    """The continuous-batching engine (greedy) must emit the same tokens as
    a naive forward-pass generation loop."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import llama
    from ray_tpu.serve.llm import LLMEngine

    cfg = _tiny_cfg(max_tokens=6)
    eng = LLMEngine(cfg, rng_seed=0)
    eng.start()
    try:
        out = eng.generate("abc")
        toks = out["tokens"]
        # reference loop on the same params
        mcfg = eng.model_cfg
        prompt = eng.tokenizer.encode("abc")
        seq = list(prompt)
        expect = []
        for _ in range(len(toks)):
            logits = llama.forward(
                eng.params, jnp.asarray([seq], jnp.int32), mcfg)
            nxt = int(np.argmax(np.asarray(logits[0, -1])))
            expect.append(nxt)
            seq.append(nxt)
        assert toks == expect
    finally:
        eng.shutdown()


def test_chunked_prefill_matches_full_prefill():
    """paged_prefill_chunk over several chunks must build the same KV and
    final logits as one full paged_prefill (chunked prefill correctness)."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import llama
    from ray_tpu.serve.llm import kv_cache as kvc

    cfg = llama.llama_tiny(vocab_size=128)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    page_size = 8
    num_pages = 16
    max_pages = 4

    rng = np.random.default_rng(7)
    plen = 21  # deliberately not a multiple of the chunk
    prompt = rng.integers(1, 128, size=(1, plen)).astype(np.int32)
    table = np.asarray([3, 4, 5, 6], np.int32)

    kv_full = kvc.init_paged_cache(cfg, num_pages, page_size)
    logits_full, kv_full = kvc.paged_prefill(
        params, kv_full, jnp.asarray(table), jnp.asarray(prompt),
        jnp.int32(plen), cfg, page_size)

    kv_c = kvc.init_paged_cache(cfg, num_pages, page_size)
    chunk = 8
    logits_c = None
    for start in range(0, plen, chunk):
        seg = prompt[:, start: start + chunk]
        padded = np.zeros((1, chunk), np.int32)
        padded[:, : seg.shape[1]] = seg
        logits_c, kv_c = kvc.paged_prefill_chunk(
            params, kv_c, jnp.asarray(table), jnp.asarray(padded),
            jnp.int32(start), jnp.int32(plen), cfg, page_size)

    np.testing.assert_allclose(
        np.asarray(logits_c), np.asarray(logits_full), rtol=2e-3, atol=2e-3)
    # the KV pages this slot owns must match too (pool dtype tolerance)
    for key in ("k", "v"):
        np.testing.assert_allclose(
            np.asarray(kv_c[key][:, :, table]),
            np.asarray(kv_full[key][:, :, table]), rtol=2e-3, atol=2e-3)


def test_engine_chunked_prefill_generates_same_tokens():
    """An engine forced into chunked prefill (tiny prefill_chunk) must emit
    exactly the tokens the unchunked engine emits (greedy)."""
    from ray_tpu.serve.llm import LLMEngine

    prompt = "the quick brown fox jumps over the lazy dog"  # 43 byte-tokens
    ref_cfg = _tiny_cfg(max_tokens=6, prefill_chunk=512)
    ref_eng = LLMEngine(ref_cfg, rng_seed=0)
    ref_eng.start()
    try:
        expect = ref_eng.generate(prompt)["tokens"]
    finally:
        ref_eng.shutdown()

    cfg = _tiny_cfg(max_tokens=6, prefill_chunk=16)
    eng = LLMEngine(cfg, rng_seed=0)
    eng.start()
    try:
        # a concurrent short request exercises the decode/chunk interleave
        rid_long = eng.submit(prompt)
        rid_short = eng.submit("abc")
        out_long = eng.result(rid_long, timeout=120.0)
        out_short = eng.result(rid_short, timeout=120.0)
        assert out_long["error"] is None and out_short["error"] is None
        assert out_long["tokens"] == expect
        assert eng.stats["prefills"] >= 2
    finally:
        eng.shutdown()


def test_engine_concurrent_and_paging():
    from ray_tpu.serve.llm import LLMEngine

    cfg = _tiny_cfg(max_batch_size=2, num_pages=32, max_tokens=5)
    eng = LLMEngine(cfg)
    eng.start()
    try:
        ids = [eng.submit(f"req {i}") for i in range(5)]
        outs = [eng.result(r, timeout=120.0) for r in ids]
        assert all(o["error"] is None for o in outs)
        assert all(o["num_generated_tokens"] == 5 for o in outs)
        stats = eng.engine_stats()
        assert stats["active_slots"] == 0
        assert stats["free_pages"] == 31  # all pages recycled (page 0 trash)
    finally:
        eng.shutdown()


@pytest.fixture
def llm_app(ray_start_regular):
    from ray_tpu import serve
    from ray_tpu.serve.llm import build_openai_app

    app = build_openai_app(_tiny_cfg(), route_prefix="/v1")
    serve.run(app, name="llm", route_prefix="/v1")
    proxy = serve.start_http_proxy(port=0)
    base = f"http://127.0.0.1:{proxy.port}"
    yield base
    serve.shutdown()


def _post(url, payload, timeout=120.0):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, r.read()


def test_openai_http_completions(llm_app):
    status, body = _post(f"{llm_app}/v1/completions",
                         {"prompt": "hello", "max_tokens": 4})
    assert status == 200
    out = json.loads(body)
    assert out["object"] == "text_completion"
    assert out["usage"]["completion_tokens"] == 4
    assert isinstance(out["choices"][0]["text"], str)

    status, body = _post(f"{llm_app}/v1/chat/completions",
                         {"messages": [{"role": "user", "content": "hi"}],
                          "max_tokens": 3})
    out = json.loads(body)
    assert out["choices"][0]["message"]["role"] == "assistant"

    with urllib.request.urlopen(f"{llm_app}/v1/models", timeout=30) as r:
        models = json.loads(r.read())
    assert models["data"][0]["id"] == "llama-tiny"


def test_openai_http_streaming(llm_app):
    status, body = _post(
        f"{llm_app}/v1/completions",
        {"prompt": "stream", "max_tokens": 5, "stream": True})
    assert status == 200
    lines = [ln for ln in body.decode().split("\n\n") if ln.startswith("data: ")]
    assert lines[-1] == "data: [DONE]"
    chunks = [json.loads(ln[len("data: "):]) for ln in lines[:-1]]
    assert chunks, "no SSE chunks"
    text = "".join(c["choices"][0]["text"] for c in chunks)
    finishes = [c["choices"][0]["finish_reason"] for c in chunks]
    assert finishes[-1] == "stop"
    assert isinstance(text, str)


def test_slot_reuse_no_kv_corruption():
    """A freed slot's device page table must be invalidated: otherwise later
    decode blocks keep scattering its junk KV into pages reallocated to a
    NEW request, corrupting its completion. Greedy output of a request must
    not depend on an earlier request having used (and freed) its pages."""
    from ray_tpu.models import llama
    from ray_tpu.serve.llm import LLMConfig
    from ray_tpu.serve.llm.engine import LLMEngine

    def make_engine():
        cfg = LLMConfig(
            model_id="t", model_config=llama.llama_tiny(vocab_size=512),
            max_batch_size=2, page_size=16, num_pages=24,
            max_prompt_len=64, max_seq_len=128, max_tokens=24,
            decode_block=4)
        eng = LLMEngine(cfg, rng_seed=7)
        eng.start()
        return eng

    probe = [5, 9, 2] * 8

    eng = make_engine()
    clean = eng.generate(probe, max_tokens=16, temperature=0.0)["tokens"]
    eng.shutdown()

    eng = make_engine()
    # short request grabs slot 0 + pages, finishes fast, slot is freed
    # mid-pipeline while the longer one still decodes
    a = eng.submit([1] * 4, max_tokens=2, temperature=0.0)
    b = eng.submit([2] * 30, max_tokens=20, temperature=0.0)
    eng.result(a, timeout=60)
    eng.result(b, timeout=60)
    # new request reuses the freed slot/pages; its greedy output must match
    # the clean-engine run exactly
    out = eng.generate(probe, max_tokens=16, temperature=0.0)["tokens"]
    eng.shutdown()
    assert out == clean


def test_engine_loads_checkpoint(tmp_path):
    """checkpoint_path round-trip: an engine built from saved params emits
    the same greedy tokens as one holding them in memory (the serving analog
    of weight loading; reference: vLLM model loading)."""
    import jax

    from ray_tpu.models import llama
    from ray_tpu.serve.llm import LLMConfig
    from ray_tpu.serve.llm.engine import LLMEngine

    mc = llama.llama_tiny(vocab_size=512)
    params = llama.init_params(jax.random.PRNGKey(42), mc)
    path = llama.save_params(params, str(tmp_path / "ckpt"))
    assert path.endswith("params.npz")

    base = dict(model_id="t", model_config=mc, max_batch_size=2,
                page_size=16, num_pages=24, max_prompt_len=64,
                max_seq_len=128, max_tokens=16)
    e1 = LLMEngine(LLMConfig(**base), params=params)
    e1.start()
    want = e1.generate([3, 1, 4] * 6, max_tokens=8, temperature=0.0)["tokens"]
    e1.shutdown()

    e2 = LLMEngine(LLMConfig(**base, checkpoint_path=str(tmp_path / "ckpt")))
    e2.start()
    got = e2.generate([3, 1, 4] * 6, max_tokens=8, temperature=0.0)["tokens"]
    e2.shutdown()
    assert got == want

    # config mismatch fails loudly
    import pytest as _pytest
    with _pytest.raises(ValueError, match="does not match"):
        llama.load_params(str(tmp_path / "ckpt"),
                          llama.llama_tiny(vocab_size=300))


def test_cancel_waiting_request_releases_result_waiter():
    """cancel() on a still-WAITING request must set done_event: a result()
    waiter already parked on it would otherwise block for its full
    timeout even though the request is gone."""
    import threading

    from ray_tpu.serve.llm import LLMEngine

    eng = LLMEngine(_tiny_cfg(), rng_seed=0)
    # engine loop deliberately NOT started: the request stays WAITING
    rid = eng.submit("abc")
    out = {}
    waiter = threading.Thread(
        target=lambda: out.update(eng.result(rid, timeout=60)))
    waiter.start()
    time.sleep(0.2)  # let the waiter park on done_event
    t0 = time.monotonic()
    eng.cancel(rid)
    waiter.join(timeout=10)
    assert not waiter.is_alive(), "result() still blocked after cancel()"
    assert time.monotonic() - t0 < 5.0
    assert out["tokens"] == [] and out["error"] is None
    # cancel removed all tracking state (nothing will ever drain it)
    assert eng.drain(rid)["error"] == "unknown request"


def test_engine_sheds_expired_waiting_request():
    """The admission loop drops WAITING requests whose deadline passed —
    no slot, no pages, no prefill — and the result() waiter gets a fast
    'deadline exceeded' error instead of its full timeout."""
    from ray_tpu.core import deadline as request_deadline
    from ray_tpu.serve.llm import LLMEngine

    eng = LLMEngine(_tiny_cfg(), rng_seed=0)
    # engine loop deliberately NOT started: the request stays WAITING
    with request_deadline.scope(time.time() + 0.1):
        rid = eng.submit("abc")
    assert eng._requests[rid].deadline is not None  # captured at submit
    time.sleep(0.15)
    eng._shed_expired_waiting()  # what _admit() runs first each pass
    out = eng.result(rid, timeout=5)
    assert out["error"] == "deadline exceeded"
    assert out["tokens"] == []
    assert eng.stats["shed_expired"] == 1

    # a live deadline rides along without shedding
    with request_deadline.scope(time.time() + 60.0):
        rid2 = eng.submit("abc")
    eng._shed_expired_waiting()
    assert len(eng._waiting) == 1  # still queued, not shed
    eng.cancel(rid2)


def test_decode_block_tier_selection():
    """_select_block's three tiers: admissions blocked (waiting + free
    slots, or a chunked prefill mid-flight) -> 1; slot-starved (waiting,
    no free slots) -> pressure_decode_block; idle -> decode_block."""
    from ray_tpu.serve.llm import LLMEngine

    eng = LLMEngine(_tiny_cfg(decode_block=8, pressure_decode_block=2),
                    rng_seed=0)
    assert eng._select_block() == 8          # idle: full block
    eng._waiting = [object()]
    assert eng._select_block() == 1          # waiting + free slots
    eng.free_slots = []
    assert eng._select_block() == 2          # slot-starved: pressure tier
    eng._waiting = []
    eng._prefilling = [object()]
    assert eng._select_block() == 1          # chunked prefill mid-flight
    eng._prefilling = []
    assert eng._select_block() == 8          # back to idle

    # pressure tier clamps to decode_block (a misconfigured larger value
    # must not out-dispatch the idle tier)
    big = LLMEngine(_tiny_cfg(decode_block=4, pressure_decode_block=16),
                    rng_seed=0)
    big._waiting = [object()]
    big.free_slots = []
    assert big._select_block() == 4

    # spec decode caps the idle tier at spec_draft_len (draft probing
    # happens between blocks; see _select_block docstring)
    spec = LLMEngine(_tiny_cfg(decode_block=8, spec_decode_enabled=True,
                               spec_draft_len=4), rng_seed=0)
    assert spec._select_block() == 4


def test_bucket_width_padding():
    """_bucket_width packs active slots into power-of-two widths with a
    floor of 4, capped at max_batch_size."""
    from ray_tpu.serve.llm import LLMEngine

    eng = LLMEngine(_tiny_cfg(max_batch_size=16, num_pages=96), rng_seed=0)
    assert eng._bucket_width(1) == 4    # floor
    assert eng._bucket_width(4) == 4
    assert eng._bucket_width(5) == 8
    assert eng._bucket_width(9) == 16
    assert eng._bucket_width(16) == 16  # cap == max_batch_size

    small = LLMEngine(_tiny_cfg(max_batch_size=3), rng_seed=0)
    assert small._bucket_width(2) == 3  # cap below the floor
    assert small._bucket_width(3) == 3


def test_engine_serves_without_is_ready_api():
    """Satellite regression: on jax builds without Array.is_ready() the
    engine must fall back to a BOUNDED harvest (pop the oldest block while
    a newer one is in flight), not silently disable eager harvest — and
    outputs stay identical."""
    from ray_tpu.serve.llm import LLMEngine

    want_eng = LLMEngine(_tiny_cfg(max_tokens=16), rng_seed=0)
    want_eng.start()
    try:
        want = want_eng.generate("fallback probe", max_tokens=16,
                                 temperature=0.0)["tokens"]
    finally:
        want_eng.shutdown()

    eng = LLMEngine(_tiny_cfg(max_tokens=16), rng_seed=0)
    eng._is_ready_supported = False  # simulate the probe failing
    assert eng._ready(object()) is False  # never touches the array
    eng.start()
    try:
        rids = [eng.submit("fallback probe", max_tokens=16,
                           temperature=0.0) for _ in range(3)]
        outs = [eng.result(r, timeout=120.0) for r in rids]
        assert all(o["error"] is None for o in outs)
        assert all(o["tokens"] == want for o in outs)
    finally:
        eng.shutdown()
