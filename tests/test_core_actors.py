"""Actor tests: creation, ordering, named actors, failure semantics.

Models the reference's python/ray/tests/test_actor.py coverage.
"""

import time

import pytest

import ray_tpu
from ray_tpu import exceptions


@ray_tpu.remote
class Counter:
    def __init__(self, start=0):
        self.n = start

    def inc(self, by=1):
        self.n += by
        return self.n

    def read(self):
        return self.n


def test_actor_basic(ray_start_regular):
    c = Counter.remote()
    assert ray_tpu.get(c.inc.remote()) == 1
    assert ray_tpu.get(c.inc.remote(5)) == 6
    assert ray_tpu.get(c.read.remote()) == 6


def test_actor_constructor_args(ray_start_regular):
    c = Counter.remote(100)
    assert ray_tpu.get(c.read.remote()) == 100


def test_actor_ordering(ray_start_regular):
    c = Counter.remote()
    refs = [c.inc.remote() for _ in range(20)]
    assert ray_tpu.get(refs[-1]) == 20
    assert ray_tpu.get(refs) == list(range(1, 21))


def test_two_actors_isolated(ray_start_regular):
    a, b = Counter.remote(), Counter.remote(10)
    ray_tpu.get([a.inc.remote(), b.inc.remote()])
    assert ray_tpu.get(a.read.remote()) == 1
    assert ray_tpu.get(b.read.remote()) == 11


def test_actor_method_error(ray_start_regular):
    @ray_tpu.remote
    class Bad:
        def boom(self):
            raise RuntimeError("actor-boom")

        def ok(self):
            return "fine"

    b = Bad.remote()
    with pytest.raises(exceptions.TaskError) as ei:
        ray_tpu.get(b.boom.remote())
    assert "actor-boom" in str(ei.value)
    # actor survives method errors
    assert ray_tpu.get(b.ok.remote()) == "fine"


def test_actor_creation_error(ray_start_regular):
    @ray_tpu.remote
    class FailInit:
        def __init__(self):
            raise RuntimeError("init-boom")

        def m(self):
            return 1

    f = FailInit.remote()
    with pytest.raises(exceptions.TaskError):
        ray_tpu.get(f.m.remote(), timeout=30)


def test_named_actor(ray_start_regular):
    Counter.options(name="counter1").remote(7)
    h = ray_tpu.get_actor("counter1")
    assert ray_tpu.get(h.read.remote()) == 7


def test_kill_actor(ray_start_regular):
    import time as _time

    c = Counter.remote()
    assert ray_tpu.get(c.inc.remote()) == 1
    ray_tpu.kill(c)
    # kill is ASYNC (reference semantics): a call racing the kill RPC may
    # still execute; keep calling until the death lands
    with pytest.raises((exceptions.TaskError, exceptions.ActorDiedError)):
        for _ in range(100):
            ray_tpu.get(c.inc.remote(), timeout=30)
            _time.sleep(0.1)


def test_actor_restart(ray_start_regular):
    @ray_tpu.remote(max_restarts=1, max_task_retries=1)
    class Dying:
        def __init__(self):
            self.n = 0

        def inc(self):
            self.n += 1
            return self.n

        def die(self):
            import os
            os._exit(1)

    d = Dying.remote()
    assert ray_tpu.get(d.inc.remote()) == 1
    # the kill itself must not be retried on the restarted actor
    d.die.options(max_task_retries=0).remote()
    time.sleep(1.0)
    # state reset after restart; max_task_retries lets the call retry
    assert ray_tpu.get(d.inc.remote(), timeout=60) == 1


def test_actor_handle_passing(ray_start_regular):
    @ray_tpu.remote
    def use_actor(h):
        return ray_tpu.get(h.inc.remote())

    c = Counter.remote()
    assert ray_tpu.get(use_actor.remote(c)) == 1
    assert ray_tpu.get(c.read.remote()) == 1


def test_async_actor(ray_start_regular):
    @ray_tpu.remote
    class Async:
        async def slow_echo(self, x):
            import asyncio
            await asyncio.sleep(0.1)
            return x

    a = Async.remote()
    refs = [a.slow_echo.remote(i) for i in range(5)]
    start = time.monotonic()
    assert ray_tpu.get(refs, timeout=30) == list(range(5))
    # concurrent execution: 5 * 0.1s awaited concurrently, not serially
    assert time.monotonic() - start < 3.0


def test_exit_actor(ray_start_regular):
    @ray_tpu.remote
    class Quitter:
        def quit(self):
            ray_tpu.exit_actor()
            return "bye"

        def m(self):
            return 1

    q = Quitter.remote()
    assert ray_tpu.get(q.quit.remote(), timeout=30) == "bye"
    time.sleep(0.5)
    with pytest.raises((exceptions.TaskError, exceptions.ActorDiedError)):
        ray_tpu.get(q.m.remote(), timeout=30)


def test_async_actor_high_concurrency(ray_start_regular):
    """100 in-flight calls on ONE async actor complete concurrently —
    concurrency is bounded by max_concurrency, not the RPC thread pool
    (reply-later execution, ref: fiber.h semantics)."""

    @ray_tpu.remote
    class Async:
        def __init__(self):
            self.peak = 0
            self.cur = 0

        async def hold(self, x):
            import asyncio
            self.cur += 1
            self.peak = max(self.peak, self.cur)
            await asyncio.sleep(0.2)
            self.cur -= 1
            return x

        async def get_peak(self):
            return self.peak

    a = Async.remote()
    start = time.monotonic()
    refs = [a.hold.remote(i) for i in range(100)]
    assert ray_tpu.get(refs, timeout=60) == list(range(100))
    elapsed = time.monotonic() - start
    # serial execution would be >= 20s
    assert elapsed < 10.0, f"not concurrent: {elapsed:.1f}s"
    assert ray_tpu.get(a.get_peak.remote(), timeout=30) >= 50


def test_nested_actor_call_chain_no_deadlock(ray_start_regular):
    """a→b→a re-entrant call chain completes (needs reply-later dispatch +
    max_concurrency >= 2 on the re-entered actor)."""

    @ray_tpu.remote(max_concurrency=2)
    class A:
        def __init__(self):
            self.b = None

        def set_b(self, b):
            self.b = b

        def outer(self):
            return ray_tpu.get(self.b.middle.remote(), timeout=30) + 1

        def inner(self):
            return 100

    @ray_tpu.remote
    class B:
        def __init__(self, a):
            self.a = a

        def middle(self):
            return ray_tpu.get(self.a.inner.remote(), timeout=30) + 10

    a = A.remote()
    b = B.remote(a)
    ray_tpu.get(a.set_b.remote(b), timeout=30)
    assert ray_tpu.get(a.outer.remote(), timeout=60) == 111


def test_concurrency_groups(ray_start_regular):
    """Named concurrency groups (ref: ConcurrencyGroupManager + ray.method):
    each group is an independent bounded pool, so slow calls in one group
    don't starve another; per-call .options(concurrency_group=...) works."""
    import time

    @ray_tpu.remote(concurrency_groups={"io": 2, "compute": 1})
    class Worker:
        def __init__(self):
            self.log = []

        @ray_tpu.method(concurrency_group="io")
        def slow_io(self):
            time.sleep(1.0)
            return "io"

        @ray_tpu.method(concurrency_group="compute")
        def compute(self):
            return "fast"

        def default_group(self):
            return "default"

    w = Worker.remote()
    # fill the io group with 2 slow calls; compute must still answer fast
    slow = [w.slow_io.remote() for _ in range(2)]
    t0 = time.monotonic()
    assert ray_tpu.get(w.compute.remote(), timeout=30) == "fast"
    assert time.monotonic() - t0 < 0.9  # didn't wait behind slow_io
    assert ray_tpu.get(w.default_group.remote(), timeout=30) == "default"
    # per-call group override routes to the io pool
    assert ray_tpu.get(
        w.default_group.options(concurrency_group="io").remote(),
        timeout=30) == "default"
    assert ray_tpu.get(slow, timeout=30) == ["io", "io"]


def test_concurrency_groups_async_actor(ray_start_regular):
    """Group bounds hold for ASYNC actors too: the pool only bounds the
    scheduling thunk, so coroutine concurrency is capped by a loop-side
    semaphore per group."""
    import time

    @ray_tpu.remote(concurrency_groups={"io": 2})
    class AsyncWorker:
        def __init__(self):
            self.active = 0
            self.peak = 0

        @ray_tpu.method(concurrency_group="io")
        async def probe(self):
            import asyncio
            self.active += 1
            self.peak = max(self.peak, self.active)
            await asyncio.sleep(0.2)
            self.active -= 1
            return self.peak

        async def peak_seen(self):
            return self.peak

    w = AsyncWorker.remote()
    ray_tpu.get([w.probe.remote() for _ in range(8)], timeout=60)
    assert ray_tpu.get(w.peak_seen.remote(), timeout=30) <= 2

    # unknown group fails loudly instead of silently serializing
    with pytest.raises(Exception, match="unknown concurrency group"):
        ray_tpu.get(
            w.peak_seen.options(concurrency_group="oi").remote(), timeout=30)
