"""DQN with target network + replay (ref: rllib/algorithms/dqn/dqn.py).

Double-DQN targets, epsilon-greedy exploration annealed over iterations,
replay on the host, the TD update as one jitted step.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.buffer import PrioritizedReplayBuffer, ReplayBuffer
from ray_tpu.rllib.env import make_env


class DQN(Algorithm):
    def setup(self) -> None:
        kw = self.config.train_kwargs
        obs_dim = make_env(self.config.env_spec).observation_dim
        # "prioritized" -> proportional PER with IS weights + TD-error
        # priority updates (ref: dqn.py replay_buffer_config)
        self._prioritized = kw.get("replay_buffer", "uniform") == "prioritized"
        if self._prioritized:
            self._buffer = PrioritizedReplayBuffer(
                kw.get("buffer_size", 50_000), obs_dim,
                seed=self.config.seed, alpha=kw.get("per_alpha", 0.6),
                beta=kw.get("per_beta", 0.4))
        else:
            self._buffer = ReplayBuffer(
                kw.get("buffer_size", 50_000), obs_dim,
                seed=self.config.seed)
        self._batch_size = kw.get("train_batch_size", 128)
        self._updates_per_iter = kw.get("updates_per_iter", 128)
        # hard target copy once per iteration by default: near-online targets
        # (freq ~4) let the bootstrap run away (deadly-triad divergence we
        # observed: Q >> r_max/(1-gamma) on sparse-reward chains)
        self._target_update_freq = kw.get("target_update_freq", 128)
        self._eps0 = kw.get("initial_epsilon", 1.0)
        self._eps1 = kw.get("final_epsilon", 0.05)
        self._eps_iters = kw.get("epsilon_anneal_iters", 20)
        self._learn_start = kw.get("learning_starts", 500)
        self._target = jax.tree.map(jnp.copy, self.params)
        self._opt = optax.adam(self.config.lr)
        self._opt_state = self._opt.init(self.params)

        module, gamma = self.module, self.config.gamma

        def loss_fn(params, target_params, b):
            q = module.forward_inference(params, b["obs"])
            q_sa = jnp.take_along_axis(q, b["actions"][:, None], axis=1)[:, 0]
            # double-DQN: online net picks the argmax, target net scores it
            next_online = module.forward_inference(params, b["next_obs"])
            next_a = jnp.argmax(next_online, axis=1)
            next_target = module.forward_inference(target_params, b["next_obs"])
            next_q = jnp.take_along_axis(next_target, next_a[:, None], axis=1)[:, 0]
            target = b["rewards"] + gamma * (1.0 - b["dones"]) * \
                jax.lax.stop_gradient(next_q)
            td = q_sa - target
            # importance weights correct the prioritized sampling bias
            # (uniform replay passes ones)
            return (b["weights"] * td ** 2).mean(), td

        @jax.jit
        def update(params, target_params, opt_state, b):
            (loss, td), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, target_params, b)
            updates, opt_state = self._opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss, td

        self._update = update

    def _epsilon(self) -> float:
        frac = min(1.0, self._iter / max(1, self._eps_iters))
        return self._eps0 + frac * (self._eps1 - self._eps0)

    def training_step(self) -> dict:
        cfg = self.config
        samples = self.runners.sample(
            self.params, cfg.rollout_steps, explore=False,
            epsilon=self._epsilon())
        for s in samples:
            self._buffer.add_batch(s)
        self._timesteps += cfg.rollout_steps * cfg.num_env_runners

        if len(self._buffer) < self._learn_start:
            return {"loss": None, "epsilon": self._epsilon(),
                    "buffer_size": len(self._buffer)}

        loss = 0.0
        for i in range(self._updates_per_iter):
            b = self._buffer.sample(self._batch_size)
            idx = b.pop("idx", None)
            b.setdefault("weights", np.ones(self._batch_size, np.float32))
            self.params, self._opt_state, loss, td = self._update(
                self.params, self._target, self._opt_state, b)
            if self._prioritized and idx is not None:
                self._buffer.update_priorities(idx, np.asarray(td))
            if (i + 1) % self._target_update_freq == 0:
                self._target = jax.tree.map(jnp.copy, self.params)
        return {"loss": float(loss), "epsilon": self._epsilon(),
                "buffer_size": len(self._buffer)}

    @classmethod
    def get_default_config(cls) -> AlgorithmConfig:
        cfg = AlgorithmConfig(algo_cls=cls)
        cfg.lr = 1e-3
        return cfg


def DQNConfig() -> AlgorithmConfig:
    return DQN.get_default_config()
