"""Core-runtime microbenchmarks (`ray microbenchmark` analog).

Mirrors the workloads of the reference's perf suite
(/root/reference/python/ray/_private/ray_perf.py:95; published numbers in
BASELINE.md "Microbenchmarks") so the runtime's task/actor/object planes are
measured, not guessed. Writes MICROBENCH.json and prints a table with the
reference numbers alongside.

Also hosts the serving-kernel arm (`--paged-kernels`): paged-attention
decode/verify/chunked-prefill latency gather vs pallas (interpret mode
off-TPU — a correctness-path timing record there, the perf claim is
TPU-only) and KV codec MB/s per-page vs batched (`kv_codec.encode_pages`
/ `decode_pages`). Every run MERGES its rows into the --out file by
metric name, so arms recorded at different times coexist.

Usage: python microbench.py [--quick] [--paged-kernels]
       [--out MICROBENCH.json]
"""

from __future__ import annotations

import argparse
import json
import time


# BASELINE.md microbenchmark rows (m4.16xlarge-class, reference 2.49.1)
_REFERENCE = {
    "single_client_get": 9176.7,
    "single_client_put": 4795.1,
    "single_client_put_gbps": 20.35,
    "single_client_tasks_sync": 901.0,
    "single_client_tasks_async": 7418.7,
    "multi_client_tasks_async": 19294.7,
    "actor_calls_1_1_sync": 1826.4,
    "actor_calls_1_1_async": 7925.7,
    "actor_calls_1_n_async": 7563.5,
    "actor_calls_n_n_async": 24808.7,
    "async_actor_calls_1_1_sync": 1374.0,
    "async_actor_calls_1_1_async": 3645.3,
    "async_actor_calls_n_n_async": 21602.2,
    "pg_create_remove_per_s": 751.1,
}


def _rate(n: int, t: float) -> float:
    return n / t if t > 0 else float("inf")


def _wait_worker_quiesce(timeout_s: float = 120.0) -> None:
    """Block until worker processes stop burning CPU (spawn storm over).

    A warm fan-out asks for the full lease breadth, and the agent answers by
    SPAWNING workers — each ~1.7s of import CPU. On a 1-core box those
    imports keep running long after the fan-out's gets return, stealing the
    core from whichever section measures next (observed: 200/s vs 2,100/s
    for the SAME sync-task section depending on spawn-storm timing). Settle
    until aggregate worker CPU is flat for 3 consecutive seconds."""
    import os

    def worker_cpu() -> int:
        tot = 0
        for pid in os.listdir("/proc"):
            if not pid.isdigit():
                continue
            try:
                if "worker_main" in open(f"/proc/{pid}/cmdline").read():
                    parts = open(f"/proc/{pid}/stat").read().split()
                    tot += int(parts[13]) + int(parts[14])
            except OSError:
                continue
        return tot

    deadline = time.monotonic() + timeout_s
    prev = worker_cpu()
    quiet = 0
    while time.monotonic() < deadline and quiet < 3:
        time.sleep(1.0)
        cur = worker_cpu()
        quiet = quiet + 1 if cur - prev <= 2 else 0
        prev = cur


def _timeit(fn, n: int) -> float:
    t0 = time.perf_counter()
    fn()
    return _rate(n, time.perf_counter() - t0)


def run(quick: bool = False) -> dict:
    import numpy as np

    import ray_tpu

    scale = 0.2 if quick else 1.0

    def N(n: int) -> int:
        return max(10, int(n * scale))

    # logical CPUs: every live actor reserves one; sections clean up after
    # themselves but the peak (4 targets + 4 callers + driver tasks) needs
    # headroom. Workload is RPC-bound, not CPU-bound.
    # 2 GiB store: the bandwidth row must measure shm, not disk spill (the
    # reference's default store is 30% of RAM; 512MB would spill mid-bench)
    ray_tpu.init(num_cpus=16, object_store_memory=2 * 1024**3)
    results: dict[str, float] = {}

    # ---- object plane --------------------------------------------------
    small = b"x" * 1024
    n = N(2000)
    ref = ray_tpu.put(small)
    results["single_client_get"] = _timeit(
        lambda: [ray_tpu.get(ref) for _ in range(n)], n)
    results["single_client_put"] = _timeit(
        lambda: [ray_tpu.put(small) for _ in range(n)], n)

    big = np.zeros(1 << 25, np.uint8)  # 32 MiB > inline threshold → shm
    n_big = N(40)
    # 3 passes, report the MEDIAN (r4 recorded a 4x run-to-run swing in
    # this row; the dominant noise was page-fault state of the arena —
    # now pre-touched by the native store — plus host load). Pass 0 also
    # covers the cold path; spread lands in the JSON for the record.
    passes = []
    for _ in range(3):
        t0 = time.perf_counter()
        refs = [ray_tpu.put(big) for _ in range(n_big)]
        passes.append((n_big * big.nbytes / (time.perf_counter() - t0)) / 1e9)
        del refs
        # let refcount-driven deletions/evictions drain so the freed-object
        # cleanup storm doesn't contaminate the next pass / section
        time.sleep(1.0)
    results["single_client_put_gbps"] = sorted(passes)[1]
    results["single_client_put_gbps_passes"] = [round(p, 2) for p in passes]

    # ---- task plane ----------------------------------------------------
    @ray_tpu.remote
    def nop():
        return None

    # Warm fan-out: spawn + register the full worker pool BEFORE measuring.
    # Worker spawn is ~1.7s of CPU each on this box; the rows below measure
    # steady-state task throughput (what the reference's numbers report from
    # its warmed multi-round suite, ray_perf.py), not process creation.
    ray_tpu.get([nop.remote() for _ in range(N(1000))])
    # settle: wait out the spawn storm the fan-out triggered (worker import
    # CPU would otherwise contaminate the next sections), then drain the
    # fan-out's deferred ref releases and let the lease pool quiesce
    _wait_worker_quiesce()
    for _ in range(30):
        ray_tpu.get(nop.remote())
    time.sleep(1.0)
    n = N(500)
    results["single_client_tasks_sync"] = _timeit(
        lambda: [ray_tpu.get(nop.remote()) for _ in range(n)], n)
    n = N(3000)
    results["single_client_tasks_async"] = _timeit(
        lambda: ray_tpu.get([nop.remote() for _ in range(n)]), n)

    # multi client: M submitter actors each firing tasks
    @ray_tpu.remote
    class Client:
        def fire(self, k):
            return ray_tpu.get([nop.remote() for _ in range(k)]) and None

    m = 4
    clients = [Client.remote() for _ in range(m)]
    k = N(500)
    ray_tpu.get([c.fire.remote(50) for c in clients])  # warm
    _wait_worker_quiesce(60.0)
    time.sleep(0.5)
    t0 = time.perf_counter()
    ray_tpu.get([c.fire.remote(k) for c in clients], timeout=300)
    results["multi_client_tasks_async"] = _rate(
        m * k, time.perf_counter() - t0)
    for c in clients:
        ray_tpu.kill(c)
    time.sleep(1.0)  # let kill/reap cleanup drain before the next section

    # ---- actor plane ---------------------------------------------------
    @ray_tpu.remote
    class Sync:
        def m(self):
            return None

    a = Sync.remote()
    ray_tpu.get([a.m.remote() for _ in range(N(300))])  # warm
    for _ in range(30):  # settle (see task-plane warm note)
        ray_tpu.get(a.m.remote())
    time.sleep(0.5)
    n = N(500)
    results["actor_calls_1_1_sync"] = _timeit(
        lambda: [ray_tpu.get(a.m.remote()) for _ in range(n)], n)
    n = N(3000)
    results["actor_calls_1_1_async"] = _timeit(
        lambda: ray_tpu.get([a.m.remote() for _ in range(n)]), n)

    actors = [Sync.remote() for _ in range(4)]
    ray_tpu.get([b.m.remote() for b in actors])
    _wait_worker_quiesce(60.0)  # actor creation spawns pool backfill workers
    n = N(3000)
    t0 = time.perf_counter()
    ray_tpu.get([actors[i % 4].m.remote() for i in range(n)])
    results["actor_calls_1_n_async"] = _rate(n, time.perf_counter() - t0)

    @ray_tpu.remote
    class Caller:
        def __init__(self, target):
            self.t = target

        def drive(self, k):
            return ray_tpu.get([self.t.m.remote() for _ in range(k)]) and None

    callers = [Caller.remote(actors[i]) for i in range(4)]
    k = N(800)
    ray_tpu.get([c.drive.remote(50) for c in callers])
    _wait_worker_quiesce(60.0)
    time.sleep(0.5)
    t0 = time.perf_counter()
    ray_tpu.get([c.drive.remote(k) for c in callers], timeout=300)
    results["actor_calls_n_n_async"] = _rate(4 * k, time.perf_counter() - t0)
    for c in callers:
        ray_tpu.kill(c)
    for b in actors:
        ray_tpu.kill(b)
    ray_tpu.kill(a)
    time.sleep(1.0)  # let kill/reap cleanup drain before the next section

    @ray_tpu.remote
    class Async:
        async def m(self):
            return None

    aa = Async.remote()
    ray_tpu.get([aa.m.remote() for _ in range(N(300))])  # warm
    for _ in range(30):  # settle (see task-plane warm note)
        ray_tpu.get(aa.m.remote())
    time.sleep(0.5)
    n = N(500)
    results["async_actor_calls_1_1_sync"] = _timeit(
        lambda: [ray_tpu.get(aa.m.remote()) for _ in range(n)], n)
    n = N(3000)
    results["async_actor_calls_1_1_async"] = _timeit(
        lambda: ray_tpu.get([aa.m.remote() for _ in range(n)]), n)

    async_actors = [Async.remote() for _ in range(4)]
    ray_tpu.get([b.m.remote() for b in async_actors])
    acallers = [Caller.remote(async_actors[i]) for i in range(4)]
    k = N(800)
    ray_tpu.get([c.drive.remote(50) for c in acallers])
    _wait_worker_quiesce(60.0)
    time.sleep(0.5)
    t0 = time.perf_counter()
    ray_tpu.get([c.drive.remote(k) for c in acallers], timeout=300)
    results["async_actor_calls_n_n_async"] = _rate(
        4 * k, time.perf_counter() - t0)
    for c in acallers:
        ray_tpu.kill(c)
    for b in async_actors:
        ray_tpu.kill(b)
    ray_tpu.kill(aa)
    time.sleep(2.0)  # kill/reap cleanup must not contaminate the PG row

    # ---- placement groups ----------------------------------------------
    n = N(60)
    t0 = time.perf_counter()
    for _ in range(n):
        pg = ray_tpu.placement_group([{"CPU": 1}])
        assert pg.ready(timeout=30)
        ray_tpu.remove_placement_group(pg)
    results["pg_create_remove_per_s"] = _rate(n, time.perf_counter() - t0)

    ray_tpu.shutdown()
    return results


def run_paged_kernels(quick: bool = False) -> dict:
    """Serving-kernel arm: paged-attention backends + KV codec batching.

    Attention rows time the jitted op both ways on this host's backend
    (pallas = interpret mode off-TPU, so treat CPU ratios as a record of
    the correctness path, not the perf claim). Codec rows time the exact
    spill/restore hot loops: per-page encode_page/decode_page vs the
    batched encode_pages/decode_pages the tier now calls."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from ray_tpu.ops import paged_attention as paged_ops
    from ray_tpu.serve.llm import kv_cache, kv_codec

    results: dict[str, float] = {}
    iters = 3 if quick else 10

    def best_ms(fn):
        fn()                                  # compile/warm
        times = []
        for _ in range(iters):
            t0 = time.perf_counter()
            jax.block_until_ready(fn())
            times.append((time.perf_counter() - t0) * 1e3)
        return min(times)

    # shapes: small enough for CPU interpret, real paged geometry
    hkv, n_rep, d, page, mp, b = 4, 2, 64, 16, 8, 8
    h = hkv * n_rep
    key = jax.random.PRNGKey(0)
    k_pages = jax.random.normal(key, (hkv, mp * b + 1, page, d),
                                jnp.float32)
    v_pages = jax.random.normal(key, (hkv, mp * b + 1, page, d),
                                jnp.float32)
    page_tables = jnp.arange(1, mp * b + 1).reshape(b, mp).astype(jnp.int32)
    pos = jnp.full((b,), mp * page - 1, jnp.int32)
    sm = d ** -0.5

    def gather_ref(q, base, limit):
        b_, t_ = q.shape[:2]
        max_len = mp * page
        k_seq = jnp.moveaxis(jnp.take(k_pages, page_tables[:b_], axis=1),
                             0, 3).reshape(b_, max_len, hkv, d)
        v_seq = jnp.moveaxis(jnp.take(v_pages, page_tables[:b_], axis=1),
                             0, 3).reshape(b_, max_len, hkv, d)
        k_full = kv_cache._gqa_expand(k_seq, n_rep)
        v_full = kv_cache._gqa_expand(v_seq, n_rep)
        col = jnp.arange(max_len)
        p_ = base[:, None] + jnp.arange(t_)[None, :]
        valid = (col[None, None, :] <= p_[:, :, None]) \
            & (col[None, None, :] < limit[:, None, None])
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k_full).astype(
            jnp.float32) * sm
        s = jnp.where(valid[:, None], s, -1e30)
        w = jax.nn.softmax(s, axis=-1).astype(q.dtype)
        return jnp.einsum("bhqk,bkhd->bqhd", w, v_full)

    full = jnp.full((b,), mp * page, jnp.int32)
    for name, t_span, b_eff in (("decode", 1, b), ("verify", 4, b),
                                ("chunk", 32, 1)):
        q = jax.random.normal(jax.random.PRNGKey(1),
                              (b_eff, t_span, h, d), jnp.float32)
        base = (pos[:b_eff] - t_span + 1).astype(jnp.int32)
        g = jax.jit(lambda q, base: gather_ref(q, base, full[:b_eff]))
        p = jax.jit(lambda q, base: paged_ops.paged_attention(
            q, k_pages, v_pages, page_tables[:b_eff], base, sm_scale=sm))
        results[f"paged_{name}_gather_ms"] = best_ms(lambda: g(q, base))
        results[f"paged_{name}_pallas_ms"] = best_ms(lambda: p(q, base))

    # ---- codec: per-page loop vs batch entry points ---------------------
    # small-page geometry (the engine's paged layout at test scale; also
    # the regime where per-page python + numpy call overhead is visible —
    # on multi-MB pages zlib dominates both paths equally)
    rng = np.random.default_rng(0)
    n_pages = 16 if quick else 64
    shape = (2, 2, n_pages, 8, 16)                # [L, Hkv, n, page, D]
    k_np = rng.standard_normal(shape, np.float32) * 0.1
    v_np = rng.standard_normal(shape, np.float32) * 0.1
    mb = 2 * k_np.nbytes / 1e6

    def best_mbps(fn):
        times = []
        for _ in range(iters):
            t0 = time.perf_counter()
            fn()
            times.append(time.perf_counter() - t0)
        return mb / min(times)

    for mode in ("lossless", "int8"):
        per_page = lambda: [
            (kv_codec.encode_page(k_np[:, :, i:i + 1], mode),
             kv_codec.encode_page(v_np[:, :, i:i + 1], mode))
            for i in range(n_pages)]
        batch = lambda: kv_codec.encode_pages(k_np, v_np, mode)
        results[f"kv_codec_{mode}_encode_page_mbps"] = best_mbps(per_page)
        results[f"kv_codec_{mode}_encode_batch_mbps"] = best_mbps(batch)
        pages = batch()
        flat = [e for pair in pages for e in pair]
        results[f"kv_codec_{mode}_decode_page_mbps"] = best_mbps(
            lambda: [kv_codec.decode_page(e) for e in flat])
        results[f"kv_codec_{mode}_decode_batch_mbps"] = best_mbps(
            lambda: kv_codec.decode_pages(flat))
    return results


def _merge_rows(out_path: str, rows: list) -> list:
    """Merge new rows into an existing MICROBENCH.json by metric name:
    re-measured metrics are replaced in place, everything else is kept."""
    try:
        with open(out_path) as f:
            old = json.load(f).get("results") or []
    except (OSError, ValueError):
        old = []
    fresh = {r["metric"] for r in rows}
    return [r for r in old if r.get("metric") not in fresh] + rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--paged-kernels", action="store_true",
                    help="run only the paged-attention + KV codec arm")
    ap.add_argument("--out", default="MICROBENCH.json")
    args = ap.parse_args()

    if args.paged_kernels:
        results = run_paged_kernels(quick=args.quick)
    else:
        results = run(quick=args.quick)

    rows = []
    for key, val in results.items():
        if isinstance(val, list):  # per-pass detail (e.g. put_gbps spread)
            rows.append({"metric": key, "value": val, "reference": None,
                         "ratio_vs_reference": None})
            continue
        ref = _REFERENCE.get(key)
        ratio = (val / ref) if ref else None
        rows.append({"metric": key,
                     "value": round(val, 1) if ref else round(val, 3),
                     "reference": ref,
                     "ratio_vs_reference": round(ratio, 3) if ratio else None})
    payload = {"results": _merge_rows(args.out, rows), "ts": time.time(),
               "note": "reference numbers from BASELINE.md (m4.16xlarge, "
                       "2.49.1); this host is much smaller — ratios are "
                       "directional, not apples-to-apples; paged_*_pallas "
                       "rows ran in interpret mode unless the host is a TPU"}
    with open(args.out, "w") as f:
        json.dump(payload, f, indent=1)

    w = max(len(r["metric"]) for r in rows)
    print(f"{'metric'.ljust(w)}  {'ours':>10}  {'reference':>10}  ratio")
    for r in rows:
        if isinstance(r["value"], list):
            print(f"{r['metric'].ljust(w)}  {r['value']}")
            continue
        ref = f"{r['reference']:>10.1f}" if r["reference"] else " " * 10
        ratio = f"{r['ratio_vs_reference']:.2f}x" \
            if r["ratio_vs_reference"] else ""
        print(f"{r['metric'].ljust(w)}  {r['value']:>10.1f}  {ref}  {ratio}")


if __name__ == "__main__":
    main()
