"""Flash attention as a Pallas TPU kernel.

The hot op of the transformer stack (SURVEY.md TPU-native note: pallas for the
ops XLA can't fuse). Streaming-softmax tiling keeps the working set in VMEM and
the (block_q × block_k) score matmuls on the MXU; causal blocks that are fully
masked are skipped. Used by models/llama.py (attn_impl="flash") and as the
per-block kernel of parallel/ring_attention.py on TPU.

Falls back to a fused einsum implementation off-TPU; tests run the kernel in
interpreter mode on CPU (pl.pallas_call(interpret=True)).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30
_STATS_LANES = 128  # stats tiles are [block_q, 128] to satisfy TPU tiling


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
                  sm_scale: float, causal: bool, block_q: int, block_k: int,
                  num_k_blocks: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q_start = qi * block_q
    k_start = ki * block_k
    # causal: the whole k-block is in the future of the whole q-block → skip
    needed = (not causal) or (k_start <= q_start + block_q - 1)

    @pl.when(needed)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # [bq, bk]
        if causal:
            rows = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        m_prev = m_scr[:, 0]  # [bq]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_scr[:, 0] = l_scr[:, 0] * alpha + jnp.sum(p, axis=-1)
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * alpha[:, None] + pv
        m_scr[:, 0] = m_new

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        l = l_scr[:, 0]
        l = jnp.where(l > 0, l, 1.0)
        o_ref[0] = (acc_scr[:] / l[:, None]).astype(o_ref.dtype)


def _flash_bh(q, k, v, *, causal: bool, sm_scale: float, block_q: int,
              block_k: int, interpret: bool):
    """q,k,v: [BH, T, D] → [BH, T, D]."""
    bh, t_q, d = q.shape
    t_k = k.shape[1]
    block_q = min(block_q, t_q)
    block_k = min(block_k, t_k)
    if t_q % block_q or t_k % block_k:
        raise ValueError(f"seq lens ({t_q},{t_k}) must divide blocks "
                         f"({block_q},{block_k})")
    num_q = t_q // block_q
    num_k = t_k // block_k
    grid = (bh, num_q, num_k)
    kernel = functools.partial(
        _flash_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, num_k_blocks=num_k)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, qi, ki: (b, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, qi, ki: (b, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, t_q, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, _STATS_LANES), jnp.float32),  # running max
            pltpu.VMEM((block_q, _STATS_LANES), jnp.float32),  # running sum
            pltpu.VMEM((block_q, d), jnp.float32),             # output acc
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_attention_core(q, k, v, causal, sm_scale, block_q, block_k,
                          interpret):
    b, t, h, d = q.shape
    to_bh = lambda x: x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)
    out = _flash_bh(to_bh(q), to_bh(k), to_bh(v), causal=causal,
                    sm_scale=sm_scale, block_q=block_q, block_k=block_k,
                    interpret=interpret)
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    out = _flash_attention_core(q, k, v, causal, sm_scale, block_q, block_k,
                                interpret)
    return out, (q, k, v, out)


def _flash_bwd(causal, sm_scale, block_q, block_k, interpret, res, g):
    """Blockwise-recompute backward (flash-attention-2 style), pure JAX:
    scans over k/v blocks so peak memory is O(T·block) not O(T²); every op
    is a batched matmul the MXU likes. Recomputes the softmax normalizer
    from scratch (two passes) instead of saving per-row stats — trades a
    forward-shaped matmul for not materializing [T,T] anywhere."""
    q, k, v, out = res
    b, t_q, h, d = q.shape
    t_k = k.shape[1]
    bk = min(block_k, t_k)
    n_blocks = t_k // bk if t_k % bk == 0 else 1
    if t_k % bk:
        bk = t_k

    # Matmuls stay in the inputs' dtype (bf16 on TPU) with fp32 ACCUMULATION
    # via preferred_element_type — an fp32 cast before the einsum would push
    # the whole backward off the bf16 MXU path (4x+ slower on v5e).
    acc32 = dict(preferred_element_type=jnp.float32)
    g32 = g.astype(jnp.float32)
    # delta_i = sum_j P_ij * dP_ij = rowsum(dO * O)  (flash-attn-2 trick)
    delta = jnp.sum(g32 * out.astype(jnp.float32), axis=-1)  # [B,T,H]

    # pass 1: softmax stats (m, l) per q row, streaming over k blocks
    def stats_body(carry, kb):
        m_prev, l_prev = carry
        k_blk, start = kb
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk, **acc32) * sm_scale
        if causal:
            rows = jnp.arange(t_q)[:, None]
            cols = start + jnp.arange(bk)[None, :]
            s = jnp.where(rows >= cols, s, _NEG_INF)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        l_new = l_prev * jnp.exp(m_prev - m_new) + \
            jnp.sum(jnp.exp(s - m_new[..., None]), axis=-1)
        return (m_new, l_new), None

    starts = jnp.arange(n_blocks) * bk
    k_blocks = k.reshape(b, n_blocks, bk, h, d).transpose(1, 0, 2, 3, 4)
    v_blocks = v.reshape(b, n_blocks, bk, h, d).transpose(1, 0, 2, 3, 4)
    m0 = jnp.full((b, h, t_q), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, t_q), jnp.float32)
    (m, l), _ = jax.lax.scan(stats_body, (m0, l0), (k_blocks, starts))
    l = jnp.where(l > 0, l, 1.0)

    # pass 2: accumulate dq; emit dk/dv per block
    def grad_body(dq_acc, kb):
        k_blk, v_blk, start = kb
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk, **acc32) * sm_scale
        if causal:
            rows = jnp.arange(t_q)[:, None]
            cols = start + jnp.arange(bk)[None, :]
            s = jnp.where(rows >= cols, s, _NEG_INF)
        p = jnp.exp(s - m[..., None]) / l[..., None]          # [B,H,Tq,bk]
        dp = jnp.einsum("bqhd,bkhd->bhqk", g, v_blk, **acc32)
        ds = p * (dp - delta.transpose(0, 2, 1)[..., None]) * sm_scale
        # cast the [T, bk] factors down to the input dtype for the second-
        # stage matmuls (standard flash-attention practice; accumulation
        # stays fp32)
        p_lo = p.astype(q.dtype)
        ds_lo = ds.astype(q.dtype)
        dq_acc = dq_acc + jnp.einsum("bhqk,bkhd->bqhd", ds_lo, k_blk, **acc32)
        dk_blk = jnp.einsum("bhqk,bqhd->bkhd", ds_lo, q, **acc32)
        dv_blk = jnp.einsum("bhqk,bqhd->bkhd", p_lo, g, **acc32)
        return dq_acc, (dk_blk, dv_blk)

    dq0 = jnp.zeros((b, t_q, h, d), jnp.float32)
    dq, (dk_blocks, dv_blocks) = jax.lax.scan(
        grad_body, dq0, (k_blocks, v_blocks, starts))
    dk = dk_blocks.transpose(1, 0, 2, 3, 4).reshape(b, t_k, h, d)
    dv = dv_blocks.transpose(1, 0, 2, 3, 4).reshape(b, t_k, h, d)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash_attention_core.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, *, causal: bool = True, sm_scale: float | None = None,
                    block_q: int = 256, block_k: int = 256,
                    interpret: bool | None = None):
    """q,k,v: [B, T, H, D] (same H — expand GQA before calling).
    Differentiable: forward is the Pallas kernel, backward a blockwise
    recompute (no [T,T] materialization)."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash_attention_core(q, k, v, causal, sm_scale, block_q, block_k,
                                 interpret)


def reference_attention(q, k, v, *, causal: bool = True,
                        sm_scale: float | None = None):
    """Fused-einsum fallback (XLA fuses softmax into the matmuls well enough
    off-TPU)."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * sm_scale
    if causal:
        t_q, t_k = q.shape[1], k.shape[1]
        mask = jnp.arange(t_q)[:, None] >= jnp.arange(t_k)[None, :]
        logits = jnp.where(mask[None, None], logits, _NEG_INF)
    p = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)
