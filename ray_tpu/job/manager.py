"""Job manager: supervised driver subprocesses.

TPU-native analog of the reference's job submission stack
(/root/reference/python/ray/dashboard/modules/job/job_manager.py +
job_supervisor.py — the driver runs as a subprocess under a supervisor
actor; status and logs stream back through the cluster):

- `JobSubmissionClient.submit(entrypoint)` spawns a DETACHED `_JobSupervisor`
  actor; the supervisor execs the entrypoint with `RAY_TPU_ADDRESS` set so
  `ray_tpu.init()` inside the script joins this cluster.
- Status lives in the control-plane KV (`job:<id>` keys) — queryable from
  any client, surviving the submitting process (and CP restarts when the CP
  runs with a persistent store).
- Logs are captured to a file and served back through the supervisor.
"""

from __future__ import annotations

import enum
import json
import os
import time
import uuid
from typing import Optional

import ray_tpu


class JobStatus(str, enum.Enum):
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"


def _kv_key(job_id: str) -> str:
    return f"job:{job_id}"


def _kv_put(payload: dict) -> None:
    from ray_tpu.core import api
    rt = api._get_runtime()
    rt.cp_client.call_with_retry(
        "kv_put", {"key": _kv_key(payload["job_id"]),
                   "value": json.dumps(payload).encode()}, timeout=10.0)


def _kv_get(job_id: str) -> Optional[dict]:
    from ray_tpu.core import api
    rt = api._get_runtime()
    raw = rt.cp_client.call_with_retry(
        "kv_get", {"key": _kv_key(job_id)}, timeout=10.0)
    return json.loads(raw) if raw else None


@ray_tpu.remote
class _JobSupervisor:
    """Runs ONE job's entrypoint as a subprocess (reference
    job_supervisor.py). Detached so it outlives the submitting client."""

    def __init__(self, job_id: str, entrypoint: str, cluster_address: str,
                 env_vars: Optional[dict] = None,
                 working_dir: Optional[str] = None):
        import subprocess
        import threading

        self.job_id = job_id
        self.entrypoint = entrypoint
        log_dir = os.path.join("/tmp/ray_tpu_jobs", job_id)
        os.makedirs(log_dir, exist_ok=True)
        self.log_path = os.path.join(log_dir, "driver.log")

        env = dict(os.environ)
        env["RAY_TPU_ADDRESS"] = cluster_address
        env["RAY_TPU_JOB_ID"] = job_id
        # make the framework importable from anywhere (it may be running
        # from a source tree rather than site-packages)
        from ray_tpu.core.config import package_parent_path
        env["PYTHONPATH"] = (package_parent_path() + os.pathsep
                             + env.get("PYTHONPATH", ""))
        env.update(env_vars or {})

        self._record(JobStatus.RUNNING, start_time=time.time())
        logf = open(self.log_path, "ab")
        self._proc = subprocess.Popen(
            entrypoint, shell=True, env=env,
            cwd=working_dir or os.getcwd(),
            stdout=logf, stderr=subprocess.STDOUT)
        self._waiter = threading.Thread(target=self._wait, daemon=True)
        self._waiter.start()

    def _record(self, status: JobStatus, **extra) -> None:
        cur = _kv_get(self.job_id) or {"job_id": self.job_id}
        cur.update({"status": status.value, "entrypoint": self.entrypoint,
                    "log_path": self.log_path, **extra})
        _kv_put(cur)

    def _wait(self) -> None:
        rc = self._proc.wait()
        self._record(
            JobStatus.SUCCEEDED if rc == 0 else JobStatus.FAILED,
            end_time=time.time(), return_code=rc)

    def status(self) -> str:
        rec = _kv_get(self.job_id)
        return rec["status"] if rec else JobStatus.PENDING.value

    def logs(self, tail: int = 1000) -> str:
        try:
            with open(self.log_path, "r", errors="replace") as f:
                return "".join(f.readlines()[-tail:])
        except OSError:
            return ""

    def stop(self) -> bool:
        if self._proc.poll() is None:
            self._proc.terminate()
            try:
                self._proc.wait(timeout=10)
            except Exception:  # noqa: BLE001
                self._proc.kill()
            self._record(JobStatus.STOPPED, end_time=time.time())
            return True
        return False


class JobSubmissionClient:
    """Submit + query jobs (reference: job SDK sdk.py). Requires a connected
    runtime (`ray_tpu.init(address=...)` or in-process head)."""

    def __init__(self, address: Optional[str] = None):
        if not ray_tpu.is_initialized():
            ray_tpu.init(address=address)
        from ray_tpu.core import api
        rt = api._get_runtime()
        self._cluster_address = f"{rt.cp_addr[0]}:{rt.cp_addr[1]}"

    def submit_job(self, *, entrypoint: str,
                   submission_id: Optional[str] = None,
                   env_vars: Optional[dict] = None,
                   working_dir: Optional[str] = None) -> str:
        job_id = submission_id or f"job_{uuid.uuid4().hex[:10]}"
        _kv_put({"job_id": job_id, "status": JobStatus.PENDING.value,
                 "entrypoint": entrypoint, "submit_time": time.time()})
        sup = _JobSupervisor.options(
            name=f"_job_supervisor_{job_id}", lifetime="detached").remote(
            job_id, entrypoint, self._cluster_address, env_vars, working_dir)
        # touch the supervisor so scheduling errors surface here
        ray_tpu.get(sup.status.remote(), timeout=60.0)
        return job_id

    def get_job_status(self, job_id: str) -> JobStatus:
        rec = _kv_get(job_id)
        if rec is None:
            raise ValueError(f"unknown job {job_id}")
        return JobStatus(rec["status"])

    def get_job_info(self, job_id: str) -> dict:
        rec = _kv_get(job_id)
        if rec is None:
            raise ValueError(f"unknown job {job_id}")
        return rec

    def get_job_logs(self, job_id: str, tail: int = 1000) -> str:
        try:
            sup = ray_tpu.get_actor(f"_job_supervisor_{job_id}", timeout=5.0)
            return ray_tpu.get(sup.logs.remote(tail), timeout=30.0)
        except Exception:  # noqa: BLE001 - supervisor gone: read the file
            rec = _kv_get(job_id)
            if rec and rec.get("log_path") and os.path.exists(rec["log_path"]):
                with open(rec["log_path"], "r", errors="replace") as f:
                    return "".join(f.readlines()[-tail:])
            return ""

    def stop_job(self, job_id: str) -> bool:
        sup = ray_tpu.get_actor(f"_job_supervisor_{job_id}", timeout=5.0)
        return ray_tpu.get(sup.stop.remote(), timeout=30.0)

    def list_jobs(self) -> list[dict]:
        from ray_tpu.core import api
        rt = api._get_runtime()
        keys = rt.cp_client.call_with_retry(
            "kv_keys", {"prefix": "job:"}, timeout=10.0) or []
        out = []
        for k in keys:
            raw = rt.cp_client.call_with_retry(
                "kv_get", {"key": k}, timeout=10.0)
            if raw:
                out.append(json.loads(raw))
        return out

    def wait_until_finished(self, job_id: str, timeout: float = 300.0) -> JobStatus:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            st = self.get_job_status(job_id)
            if st in (JobStatus.SUCCEEDED, JobStatus.FAILED, JobStatus.STOPPED):
                return st
            time.sleep(0.2)
        raise TimeoutError(f"job {job_id} still {st} after {timeout}s")
