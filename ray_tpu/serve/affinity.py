"""Prefix-affinity ingress helpers (ISSUE 10).

The HTTP proxy computes the prompt's leading page-chain digests ONCE per
request — the same blake2b-128 hash chain the engine's prefix index uses
(serve/llm/kv_cache.py `_chain_digest`) over the same tokenization — and
hands them to the router (`choose()` scores replicas by longest resident
match) AND to the chosen replica (which reuses them for its tier restore
instead of re-hashing, after a page-0 verification).

This module must stay importable in the proxy process: hashlib + numpy
only, no jax. The digest chain is duplicated from kv_cache rather than
imported because kv_cache pulls in jax at module scope; the byte-for-byte
equivalence is pinned by tests/test_affinity_routing.py.

The replica side carries the digests request-scoped through a contextvar
(same pattern as serve/multiplex.py's multiplexed model id): the replica
pops `_prefix_digests` from kwargs, sets the contextvar, and the engine
submit path reads it back.
"""

from __future__ import annotations

import contextvars
import hashlib
import threading
from typing import Optional

import numpy as np

# request-scoped ingress digests on the replica (serve/replica.py sets it
# before dispatching into user code; copy_context() carries it into the
# executor thread)
_current_digests: contextvars.ContextVar[Optional[tuple]] = \
    contextvars.ContextVar("ray_tpu_prefix_digests", default=None)

# proxy-side tokenizer cache: one tokenizer per spec string, shared by
# every request (HF tokenizers are expensive to construct). Bounded by
# the number of distinct tokenizer specs the app serves.
_tok_cache: dict = {}
_tok_lock = threading.Lock()


def _set_request_prefix_digests(digests: Optional[list]) -> None:
    _current_digests.set(tuple(digests) if digests else None)


def get_request_prefix_digests() -> Optional[list]:
    cur = _current_digests.get()
    return list(cur) if cur else None


def _chain_digest(parent: bytes, chunk) -> bytes:
    # MUST mirror kv_cache._chain_digest exactly: equal digests are the
    # contract that lets the router match against replica-resident chains
    return hashlib.blake2b(
        parent + np.asarray(chunk, np.int32).tobytes(),
        digest_size=16).digest()


def _get_tokenizer(spec: str):
    with _tok_lock:
        tok = _tok_cache.get(spec)
    if tok is None:
        from ray_tpu.serve.llm.tokenizer import get_tokenizer
        tok = get_tokenizer(spec)
        with _tok_lock:
            tok = _tok_cache.setdefault(spec, tok)
    return tok


def prompt_from_payload(path: str, payload) -> Optional[str]:
    """The prompt string the LLM deployment will tokenize for this HTTP
    request, or None when the route doesn't submit to the engine."""
    if not isinstance(payload, dict):
        return None
    path = "/" + str(path).strip("/")
    if path.endswith("/chat/completions"):
        from ray_tpu.serve.llm.llm_server import _chat_prompt
        return _chat_prompt(payload.get("messages", []))
    if path.endswith("/completions"):
        prompt = payload.get("prompt", "")
        if isinstance(prompt, list):
            prompt = prompt[0] if prompt else ""
        return prompt if isinstance(prompt, str) else None
    return None


def digests_for_http(subpath: str, payload, meta: dict,
                     max_digests: int) -> Optional[list]:
    """Proxy entry point: ingress digests for one HTTP request, or None
    (non-LLM route, short prompt, or any failure — all mean pow-2)."""
    prompt = prompt_from_payload(subpath, payload)
    if prompt is None:
        return None
    return compute_prefix_digests(prompt, meta, max_digests)


def prompt_tokens_for_http(subpath: str, payload, meta: dict) -> int:
    """Tokenized (and max_prompt_len-capped) prompt length for one HTTP
    request under the deployment's affinity ``meta`` — the number the
    disagg threshold decision (ISSUE 16) compares against. 0 on non-LLM
    routes or any failure (0 never crosses a positive threshold, so
    failures degrade to colocated serving)."""
    try:
        prompt = prompt_from_payload(subpath, payload)
        if prompt is None:
            return 0
        tok = _get_tokenizer(str(meta["tokenizer"]))
        toks = tok.encode(prompt)
        max_len = int(meta.get("max_prompt_len") or 0)
        if max_len > 0:
            toks = toks[:max_len]
        return len(toks)
    except Exception:  # noqa: BLE001 — sizing is advisory, same degrade
        # contract as the digests above
        return 0


def compute_prefix_digests(prompt: str, meta: dict,
                           max_digests: int) -> Optional[list]:
    """Leading page-chain digests (hex) for ``prompt`` under the
    deployment's affinity ``meta`` ({tokenizer, page_size,
    max_prompt_len}). Mirrors the engine exactly: same tokenization, same
    max_prompt_len truncation, and the same (len-1)//page_size full-page
    limit as match_prefix (at least one suffix token always remains to
    prefill). Returns None when the prompt has no full page — routing
    then stays plain pow-2."""
    try:
        page_size = int(meta["page_size"])
        tok = _get_tokenizer(str(meta["tokenizer"]))
        toks = tok.encode(prompt)
        max_len = int(meta.get("max_prompt_len") or 0)
        if max_len > 0:
            toks = toks[:max_len]
        limit = (len(toks) - 1) // page_size
        if max_digests > 0:
            limit = min(limit, max_digests)
        if limit <= 0:
            return None
        digest = b""
        out = []
        for i in range(limit):
            digest = _chain_digest(
                digest, toks[i * page_size:(i + 1) * page_size])
            out.append(digest.hex())
        return out
    except Exception:  # noqa: BLE001 — affinity is an optimization; a
        # digest failure must degrade to pow-2 routing, never 500 the
        # request
        return None
