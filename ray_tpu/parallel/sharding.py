"""Sharding strategies: DP / FSDP / TP as sharding-spec builders.

This replaces the reference's per-strategy wrapper machinery
(/root/reference/python/ray/train/torch/train_loop_utils.py:153 prepare_model
→ DDP; :171-185 FSDP passthrough; vLLM tensor_parallel_size delegation) with
in-framework sharding rules (SURVEY.md §2.3): parameters and optimizer state
carry `jax.sharding.NamedSharding`s over the mesh; XLA inserts the collectives.

Two APIs:
- logical-axis rules (flax-style): modules annotate params with logical axis
  names; `logical_to_shardings` maps them onto mesh axes by rule table.
- shape-driven FSDP: `infer_fsdp_sharding` shards the largest divisible dim of
  every array over the fsdp axis — works for any pytree of params with zero
  model annotations (the analog of torch FSDP's parameter flattening, but
  static and compiler-visible).
"""

from __future__ import annotations

import re
from typing import Any, Callable

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # shard_map moved out of jax.experimental in newer releases
    from jax.experimental.shard_map import shard_map as _shard_map
    _SHARD_MAP_CHECK_KW = "check_rep"
except ImportError:  # pragma: no cover - newer jax
    from jax import shard_map as _shard_map
    _SHARD_MAP_CHECK_KW = "check_vma"


def shard_map_compat(fn, *, mesh, in_specs, out_specs, check=False):
    """``shard_map`` across jax versions: the replication-check kwarg was
    renamed ``check_rep`` → ``check_vma`` when shard_map left
    jax.experimental. Callers pass ``check=``; we translate to whatever
    this jax spells it."""
    return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs,
                      **{_SHARD_MAP_CHECK_KW: check})

# default logical-axis rule table (megatron-style TP + fsdp weight sharding)
DEFAULT_RULES: tuple[tuple[str, str | None], ...] = (
    ("batch", "data"),
    ("fsdp_batch", ("replica", "data", "fsdp")),
    ("sequence", "context"),
    ("embed", "fsdp"),          # weight dim sharded by fsdp (zero-3 style)
    ("mlp", "tensor"),          # ffn hidden dim -> tensor parallel
    ("heads", "tensor"),        # attention heads -> tensor parallel
    ("kv_heads", "tensor"),
    ("head_dim", None),
    ("vocab", "tensor"),
    ("expert", "expert"),
    ("layers", None),
    ("stage", "pipeline"),
)


def rules_dict(extra: dict[str, Any] | None = None) -> dict[str, Any]:
    d = dict(DEFAULT_RULES)
    if extra:
        d.update(extra)
    return d


def spec_from_logical(logical_axes: tuple[str | None, ...],
                      rules: dict[str, Any], mesh: Mesh) -> P:
    """Map ('embed','mlp') → PartitionSpec('fsdp','tensor'), dropping mesh axes
    of size 1 (so the same model code runs on any mesh)."""
    out = []
    for ax in logical_axes:
        mapped = rules.get(ax) if ax is not None else None
        if mapped is None:
            out.append(None)
            continue
        if isinstance(mapped, str):
            mapped_axes = (mapped,)
        else:
            mapped_axes = tuple(mapped)
        mapped_axes = tuple(a for a in mapped_axes
                            if a in mesh.axis_names and mesh.shape[a] > 1)
        if not mapped_axes:
            out.append(None)
        elif len(mapped_axes) == 1:
            out.append(mapped_axes[0])
        else:
            out.append(mapped_axes)
    while out and out[-1] is None:
        out.pop()
    return P(*out)


def logical_to_shardings(logical_tree, mesh: Mesh,
                         rules: dict[str, Any] | None = None):
    """Tree of logical-axis tuples → tree of NamedShardings."""
    rules = rules or rules_dict()
    return jax.tree.map(
        lambda axes: NamedSharding(mesh, spec_from_logical(tuple(axes), rules, mesh)),
        logical_tree, is_leaf=lambda x: isinstance(x, tuple))


def infer_fsdp_sharding(params_shapes, mesh: Mesh, axis: str = "fsdp",
                        min_bytes: int = 2 ** 12):
    """Shape-driven FSDP: for each array, shard the largest dim divisible by
    the fsdp axis size; replicate small arrays (the in-framework equivalent of
    the reference's delegated FSDP/ZeRO, SURVEY.md §2.3 row 2)."""
    if axis not in mesh.axis_names or mesh.shape[axis] == 1:
        return jax.tree.map(lambda _: NamedSharding(mesh, P()), params_shapes)
    n = mesh.shape[axis]

    def one(leaf):
        shape = getattr(leaf, "shape", None)
        if shape is None or not shape:
            return NamedSharding(mesh, P())
        size = int(np.prod(shape)) * getattr(leaf, "dtype", np.dtype("f4")).itemsize
        if size < min_bytes:
            return NamedSharding(mesh, P())
        # largest dim divisible by n wins; ties -> first
        best = -1
        best_dim = -1
        for i, d in enumerate(shape):
            if d % n == 0 and d > best_dim:
                best, best_dim = i, d
        if best < 0:
            return NamedSharding(mesh, P())
        spec = [None] * len(shape)
        spec[best] = axis
        del spec[best + 1:]  # trailing Nones are implicit
        return NamedSharding(mesh, P(*spec))

    return jax.tree.map(one, params_shapes)


def _path_name(path) -> str:
    """Pytree key path → a slash-joined name regex rules match against
    (dict keys and sequence indices both render: ``layers/attn/wq``)."""
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        else:
            parts.append(str(k))
    return "/".join(parts)


def match_partition_rules(rules, params):
    """Regex partition rules → tree of PartitionSpecs (the T5X/EasyLM
    idiom). ``rules`` is an ordered sequence of ``(pattern, spec)``; the
    FIRST pattern that ``re.search``-matches a leaf's slash-joined tree
    path wins. Scalars always get ``P()`` (nothing to shard); every
    non-scalar leaf must match some rule — a silent replicate-by-default
    hides typos in the rule table, so an unmatched leaf raises.

    Shared by train (``spmd.state_shardings(partition_rules=...)``) and
    serve (the TP engine's weight shardings): one implementation, one
    set of semantics for how a param name selects its layout."""
    rules = tuple((pat, spec if isinstance(spec, P) else P(*spec))
                  for pat, spec in rules)

    def get_spec(path, leaf):
        name = _path_name(path)
        if not getattr(leaf, "shape", ()):
            return P()
        for pattern, spec in rules:
            if re.search(pattern, name):
                return spec
        raise ValueError(f"partition rule not found for param: {name}")

    return jax.tree_util.tree_map_with_path(get_spec, params)


def prune_spec(spec: P, mesh: Mesh) -> P:
    """Drop mesh axes of size 1 (or absent) from a PartitionSpec, so one
    rule table serves any mesh — the regex-rule twin of the dropping
    ``spec_from_logical`` does for logical-axis rules."""
    out = []
    for entry in spec:
        if entry is None:
            out.append(None)
            continue
        axes = (entry,) if isinstance(entry, str) else tuple(entry)
        axes = tuple(a for a in axes
                     if a in mesh.axis_names and mesh.shape[a] > 1)
        if not axes:
            out.append(None)
        elif len(axes) == 1:
            out.append(axes[0])
        else:
            out.append(axes)
    while out and out[-1] is None:
        out.pop()
    return P(*out)


def rule_shardings(rules, params, mesh: Mesh):
    """``match_partition_rules`` + mesh application in one call: tree of
    params (or ShapeDtypeStructs) → tree of NamedShardings."""
    specs = match_partition_rules(rules, params)
    return jax.tree.map(
        lambda s: NamedSharding(mesh, prune_spec(s, mesh)),
        specs, is_leaf=lambda x: isinstance(x, P))


def batch_sharding(mesh: Mesh, *, extra_dims: int = 0) -> NamedSharding:
    """Inputs sharded over every data-parallel axis on dim 0."""
    dp_axes = tuple(a for a in ("replica", "data", "fsdp")
                    if a in mesh.axis_names and mesh.shape[a] > 1)
    spec = (dp_axes if dp_axes else None,) + (None,) * extra_dims
    return NamedSharding(mesh, P(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_init(init_fn: Callable, mesh: Mesh, shardings) -> Callable:
    """Jit an init function with output shardings so parameters are created
    directly sharded (never materialized replicated — the ZeRO-init analog)."""
    return jax.jit(init_fn, out_shardings=shardings)


def num_dp_shards(mesh: Mesh) -> int:
    n = 1
    for a in ("replica", "data", "fsdp"):
        if a in mesh.axis_names:
            n *= mesh.shape[a]
    return n
