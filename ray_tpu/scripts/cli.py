"""ray-tpu CLI: start / stop / status / submit / logs / jobs /
microbenchmark / timeline.

TPU-native analog of the reference's CLI surface
(/root/reference/python/ray/scripts/scripts.py — `ray start/stop/status/
microbenchmark/timeline`; dashboard/modules/job/cli.py — `ray job submit`).

Usage:
    python -m ray_tpu start --head [--port 6380] [--num-cpus 8] [--store-path p]
    python -m ray_tpu start --address host:port      # join as a worker node
    python -m ray_tpu status [--address host:port]
    python -m ray_tpu drain NODE_ID [--no-wait]    # graceful node drain
    python -m ray_tpu submit [--address ...] -- python my_script.py
    python -m ray_tpu jobs [--address ...]
    python -m ray_tpu logs JOB_ID [--address ...]
    python -m ray_tpu stop
    python -m ray_tpu microbenchmark
    python -m ray_tpu timeline --out trace.json
    python -m ray_tpu metrics [NAME] [--tags k=v] [--since TS] [--watch]
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time

_STATE_DIR = os.path.expanduser("~/.ray_tpu")
_ADDR_FILE = os.path.join(_STATE_DIR, "address")
_PID_FILE = os.path.join(_STATE_DIR, "head.pid")


def _write_state(address: str, pid: int) -> None:
    os.makedirs(_STATE_DIR, exist_ok=True)
    with open(_ADDR_FILE, "w") as f:
        f.write(address)
    with open(_PID_FILE, "w") as f:
        f.write(str(pid))


def _read_address(cli_value: str | None) -> str:
    if cli_value:
        return cli_value
    env = os.environ.get("RAY_TPU_ADDRESS")
    if env:
        return env
    if os.path.exists(_ADDR_FILE):
        with open(_ADDR_FILE) as f:
            return f.read().strip()
    raise SystemExit("no cluster address: pass --address, set "
                     "RAY_TPU_ADDRESS, or `ray-tpu start --head` first")


# ---- head/worker node daemons ---------------------------------------------

def _run_head_daemon(args) -> None:
    """The long-lived head process (GCS+raylet analog in-proc)."""
    from ray_tpu.core.control_plane import ControlPlane
    from ray_tpu.core.node_agent import NodeAgent

    cp = ControlPlane(port=args.port, store_path=args.store_path or None)
    res = {"CPU": float(args.num_cpus or (os.cpu_count() or 1))}
    agent = NodeAgent(cp.addr, resources=res)
    addr = f"{cp.addr[0]}:{cp.addr[1]}"
    dashboard = None
    if getattr(args, "dashboard_port", -1) >= 0:
        import ray_tpu
        ray_tpu.init(address=addr)
        from ray_tpu.dashboard import start_dashboard
        dashboard = start_dashboard(port=args.dashboard_port)
        print(f"dashboard at http://127.0.0.1:{dashboard.port}", flush=True)
    print(f"ray_tpu head up at {addr}", flush=True)
    stop = []
    signal.signal(signal.SIGTERM, lambda *_: stop.append(1))
    while not stop:
        time.sleep(0.5)
    if dashboard is not None:
        dashboard.stop()
    agent.stop()
    cp.stop()


def _parse_labels(spec: str | None) -> dict:
    out = {}
    for item in filter(None, (spec or "").split(",")):
        k, _, v = item.partition("=")
        out[k] = v
    return out


def _run_node_daemon(args) -> None:
    """A long-lived worker-node agent joining an existing cluster."""
    from ray_tpu.core.node_agent import NodeAgent

    host, port = _read_address(args.address).rsplit(":", 1)
    res = {"CPU": float(args.num_cpus or (os.cpu_count() or 1))}
    agent = NodeAgent((host, int(port)), resources=res,
                      labels=_parse_labels(getattr(args, "labels", None)))
    print(f"ray_tpu node joined {host}:{port} as {agent.node_id.hex()[:8]}",
          flush=True)
    stop = []
    signal.signal(signal.SIGTERM, lambda *_: stop.append(1))
    while not stop:
        time.sleep(0.5)
    agent.stop()


def cmd_start(args) -> None:
    if args.block:
        if args.head:
            _run_head_daemon(args)
        else:
            _run_node_daemon(args)
        return
    # detach: re-exec ourselves with --block in a daemonized subprocess
    cmd = [sys.executable, "-m", "ray_tpu", "start", "--block"]
    if args.head:
        cmd += ["--head", "--port", str(args.port),
                "--dashboard-port", str(args.dashboard_port)]
        if args.store_path:
            cmd += ["--store-path", args.store_path]
    else:
        cmd += ["--address", _read_address(args.address)]
        if args.labels:
            cmd += ["--labels", args.labels]
    if args.num_cpus:
        cmd += ["--num-cpus", str(args.num_cpus)]
    os.makedirs(_STATE_DIR, exist_ok=True)
    log = open(os.path.join(_STATE_DIR, "head.log" if args.head
                            else f"node-{os.getpid()}.log"), "ab")
    proc = subprocess.Popen(cmd, stdout=log, stderr=subprocess.STDOUT,
                            start_new_session=True)
    if args.head:
        address = f"127.0.0.1:{args.port}"
        _write_state(address, proc.pid)
        # wait for the control plane to accept connections
        from ray_tpu.core.rpc import RpcClient
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            try:
                RpcClient(("127.0.0.1", args.port), name="probe").call(
                    "ping", None, timeout=2.0)
                print(f"started head at {address} (pid {proc.pid})")
                print(f"connect with: ray_tpu.init(address='{address}')")
                return
            except Exception:  # noqa: BLE001
                time.sleep(0.2)
        raise SystemExit("head failed to start; see ~/.ray_tpu/head.log")
    print(f"started worker node (pid {proc.pid})")


def cmd_stop(args) -> None:
    stopped = False
    if os.path.exists(_PID_FILE):
        with open(_PID_FILE) as f:
            pid = int(f.read().strip())
        # the head was started with start_new_session=True, so its process
        # group holds exactly this cluster (head + its spawned workers);
        # killing the group never touches other clusters on the machine
        def _signal(sig):
            try:
                os.killpg(pid, sig)
            except (ProcessLookupError, PermissionError):
                try:
                    os.kill(pid, sig)
                except ProcessLookupError:
                    raise
        try:
            _signal(signal.SIGTERM)
            stopped = True
            # wait for exit so a follow-up `start` can rebind the ports
            deadline = time.time() + 10.0
            while time.time() < deadline:
                try:
                    os.kill(pid, 0)
                except ProcessLookupError:
                    break
                time.sleep(0.1)
            else:
                try:
                    _signal(signal.SIGKILL)
                except ProcessLookupError:
                    pass
            print(f"stopped head (pid {pid})")
        except ProcessLookupError:
            pass
        os.remove(_PID_FILE)
    if os.path.exists(_ADDR_FILE):
        os.remove(_ADDR_FILE)
    if getattr(args, "force", False):
        # explicit opt-in only: this reaps EVERY ray_tpu worker on the
        # machine, including other live clusters'
        subprocess.run(["pkill", "-f", "ray_tpu.core.worker_main"],
                       check=False)
        print("killed all ray_tpu workers on this machine (--force)")
    elif not stopped:
        print("no head pidfile; nothing stopped (use --force to reap "
              "stray workers)")


def cmd_status(args) -> None:
    import ray_tpu
    ray_tpu.init(address=_read_address(args.address))
    from ray_tpu.util import state

    nodes = ray_tpu.nodes()
    print(f"nodes: {len(nodes)}")
    for n in nodes:
        # the CP-side state machine (ALIVE/DRAINING/DRAINED/DEAD); older
        # CPs only report the alive bit
        st = n.get("state") or ("ALIVE" if n["alive"] else "DEAD")
        progress = ""
        if st == "DRAINING" and n.get("draining_since"):
            from ray_tpu.core.config import get_config
            elapsed = time.time() - n["draining_since"]
            progress = (f" (draining {elapsed:.0f}s/"
                        f"{get_config().drain_deadline_s:.0f}s)")
        print(f"  {n['node_id'].hex()[:8]} {st}{progress} at {n['addr']} "
              f"resources={n['resources']} available={n['available']}")
    actors = state.list_actors()
    by_state: dict[str, int] = {}
    for a in actors:
        by_state[a["state"]] = by_state.get(a["state"], 0) + 1
    print(f"actors: {by_state or 0}")
    pgs = state.list_placement_groups()
    print(f"placement groups: {len(pgs)}")

    # serve prefix-affinity routing (ISSUE 10): router counters from the
    # CP time-series store; silent until a router has reported
    def _counter_total(name: str):
        try:
            res = state.query_metrics(name)
            if not res or not res.get("series"):
                return None
            return sum(s["points"][-1][1] for s in res["series"])
        except Exception:  # noqa: BLE001 — metrics are best-effort
            return None

    hits = _counter_total("ray_tpu_serve_router_affinity_hits_total")
    if hits is not None:
        spill = _counter_total(
            "ray_tpu_serve_router_affinity_spillovers_total") or 0
        stale = _counter_total(
            "ray_tpu_serve_router_affinity_stale_fallbacks_total") or 0
        print(f"serve affinity: hits={hits:.0f} spillovers={spill:.0f} "
              f"stale_fallbacks={stale:.0f}")
    ray_tpu.shutdown()


def cmd_drain(args) -> None:
    """Gracefully drain a node instead of killing it: stop new leases, let
    in-flight work finish, migrate primary objects, then deregister."""
    import ray_tpu
    from ray_tpu.util import state

    ray_tpu.init(address=_read_address(args.address))
    try:
        out = state.drain_node(args.node_id, wait=not args.no_wait,
                               reason="ray-tpu drain CLI")
    except ValueError as e:
        raise SystemExit(str(e))
    print(f"drain {args.node_id}: state={out.get('state')}")
    ray_tpu.shutdown()
    if not out.get("ok"):
        raise SystemExit(out.get("error") or "drain failed")


def cmd_submit(args) -> None:
    import ray_tpu
    from ray_tpu.job import JobSubmissionClient

    ray_tpu.init(address=_read_address(args.address))
    client = JobSubmissionClient()
    entrypoint = " ".join(args.entrypoint)
    job_id = client.submit_job(entrypoint=entrypoint,
                               working_dir=args.working_dir)
    print(f"submitted {job_id}: {entrypoint}")
    if args.no_wait:
        return
    status = client.wait_until_finished(job_id, timeout=args.timeout)
    print(f"status: {status.value}")
    print("---- logs ----")
    print(client.get_job_logs(job_id))
    if status.value != "SUCCEEDED":
        raise SystemExit(1)


def cmd_jobs(args) -> None:
    import ray_tpu
    from ray_tpu.job import JobSubmissionClient

    ray_tpu.init(address=_read_address(args.address))
    for rec in JobSubmissionClient().list_jobs():
        print(json.dumps(rec))


def cmd_logs(args) -> None:
    import ray_tpu
    from ray_tpu.job import JobSubmissionClient

    ray_tpu.init(address=_read_address(args.address))
    print(JobSubmissionClient().get_job_logs(args.job_id, tail=args.tail))


def cmd_microbenchmark(args) -> None:
    import runpy
    sys.argv = ["microbench.py"] + (["--quick"] if args.quick else [])
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), "microbench.py")
    runpy.run_path(path, run_name="__main__")


def cmd_timeline(args) -> None:
    import ray_tpu
    from ray_tpu.util import state

    ray_tpu.init(address=_read_address(args.address))
    out = args.out or f"timeline-{int(time.time())}.json"
    state.timeline(filename=out)
    print(f"wrote chrome trace to {out} (open in chrome://tracing)")


def cmd_trace(args) -> None:
    import ray_tpu
    from ray_tpu.util import state

    ray_tpu.init(address=_read_address(args.address))
    if not args.trace_id:
        # no id: list what the trace store holds
        for meta in state.list_traces(limit=args.limit):
            print(json.dumps(meta))
        return
    if args.out:
        state.trace_timeline(args.trace_id, filename=args.out,
                             fmt=args.format)
        hint = (" (open in chrome://tracing)" if args.format == "chrome"
                else "")
        print(f"wrote {args.format} trace to {args.out}{hint}")
    else:
        print(state.trace_timeline(args.trace_id, fmt=args.format))


def cmd_profile(args) -> None:
    import ray_tpu
    from ray_tpu.util import state

    ray_tpu.init(address=_read_address(args.address))
    if args.list:
        for art in state.list_profile_artifacts():
            print(json.dumps(art))
        return
    if args.memory:
        out = state.save_device_memory_profile(node_id=args.node,
                                               path=args.logdir)
        print(json.dumps(out, indent=2))
        return
    print(f"capturing XPlane trace for {args.duration:g}s "
          f"({'node ' + args.node if args.node else 'all nodes'})…",
          file=sys.stderr)
    out = state.capture_xprof(node_id=args.node, duration=args.duration,
                              logdir=args.logdir)
    arts = out.get("artifacts") or []
    for art in arts:
        print(json.dumps(art))
    if arts:
        print(f"{len(arts)} capture(s); inspect with "
              f"`tensorboard --logdir {arts[0]['logdir']}` (Profile tab)",
              file=sys.stderr)
    else:
        print("no captures produced:", file=sys.stderr)
        print(json.dumps(out, indent=2), file=sys.stderr)
        raise SystemExit(1)


def cmd_kvtier(args) -> None:
    import ray_tpu
    from ray_tpu.util import state

    ray_tpu.init(address=_read_address(args.address))
    if args.gc:
        out = state.kv_tier_gc()
        print(f"gc dropped {out.get('dropped', 0)} expired entries",
              file=sys.stderr)
    res = state.list_kv_tier()
    entries = res.get("entries") or []
    if args.json:
        print(json.dumps(res, indent=2))
        return
    # per-entry rows, then totals per tier/node + CP hit counters
    by_tier: dict[str, dict] = {}
    by_node: dict[str, int] = {}
    for e in entries:
        t = by_tier.setdefault(e.get("tier", "?"),
                               {"entries": 0, "bytes": 0, "raw": 0})
        t["entries"] += 1
        t["bytes"] += int(e.get("nbytes") or 0)
        # pre-codec size; raw-format entries (codec "none", pre-codec
        # publishers) carry no "raw" field — stored == raw there
        t["raw"] += int(e.get("raw") or e.get("nbytes") or 0)
        node = (e.get("node") or "?")[:8]
        by_node[node] = by_node.get(node, 0) + 1
        print(json.dumps({
            "digest": (e.get("digest") or "")[:16],
            "tier": e.get("tier"), "node": node,
            "owner": (e.get("owner") or "")[:8],
            "tokens": e.get("tokens"), "nbytes": e.get("nbytes"),
            "raw": e.get("raw"),
            "age_s": round(time.time() - e["ts"], 1)
            if e.get("ts") else None}))
    print(f"# {len(entries)} indexed pages", file=sys.stderr)
    for tier, agg in sorted(by_tier.items()):
        ratio = (agg["raw"] / agg["bytes"]) if agg["bytes"] else 0.0
        print(f"#   tier={tier}: {agg['entries']} entries "
              f"{agg['bytes']} bytes stored / {agg['raw']} raw "
              f"(codec ratio {ratio:.2f}x => holds {ratio:.2f}x the "
              f"prefix tokens per byte cap)", file=sys.stderr)
    for node, n in sorted(by_node.items()):
        print(f"#   node={node}: {n} entries", file=sys.stderr)
    c = res.get("counters") or {}
    print(f"# match_calls={c.get('match_calls', 0)} "
          f"hits={c.get('hits', 0)} misses={c.get('misses', 0)} "
          f"hit_pages={c.get('hit_pages', 0)}", file=sys.stderr)


def cmd_slo(args) -> None:
    """Tail-latency attribution (ISSUE 12): per-stage breakdown table,
    exemplar listing, one-exemplar waterfall, per-replica skew."""
    import ray_tpu
    from ray_tpu.util import state

    ray_tpu.init(address=_read_address(args.address))

    if args.exemplar:
        rec = state.get_slo_exemplar(args.exemplar)
        if rec is None:
            print(f"no exemplar matching {args.exemplar!r}", file=sys.stderr)
            raise SystemExit(1)
        if args.json:
            print(json.dumps(rec, indent=2))
            return
        from ray_tpu.observability import attribution, tracing
        spans = attribution.stages_to_spans(rec)
        if args.out:
            with open(args.out, "w") as f:
                json.dump(tracing.to_chrome_trace(spans), f)
            print(f"chrome trace written to {args.out} "
                  f"(load in chrome://tracing or Perfetto)", file=sys.stderr)
            return
        _print_exemplar_waterfall(rec, spans)
        return

    if args.exemplars:
        rows = state.list_slo_exemplars(limit=args.limit, kind=args.kind)
        if args.json:
            print(json.dumps(rows, indent=2))
            return
        for r in rows:
            print(json.dumps(r))
        print(f"# {len(rows)} exemplar(s); `ray-tpu slo --exemplar <id>` "
              f"renders one waterfall", file=sys.stderr)
        return

    report = state.slo_report(deployment=args.deployment)
    if args.json:
        print(json.dumps(report, indent=2))
        return
    print(f"# {report.get('count', 0)} exemplar(s), "
          f"{report.get('violations', 0)} SLO violation(s)",
          file=sys.stderr)
    stage_ms = report.get("stage_ms") or {}
    if stage_ms:
        print(f"{'stage':<10} {'p50_ms':>10} {'p95_ms':>10} "
              f"{'p99_ms':>10} {'count':>7}")
        for stage, row in stage_ms.items():
            print(f"{stage:<10} {row['p50']:>10.2f} {row['p95']:>10.2f} "
                  f"{row['p99']:>10.2f} {row['count']:>7}")
    dom = report.get("dominant_stage") or {}
    if dom:
        ranked = sorted(dom.items(), key=lambda kv: kv[1], reverse=True)
        print("# dominant stage of tail requests: "
              + ", ".join(f"{s}={n}" for s, n in ranked), file=sys.stderr)
    if args.replica_skew or not stage_ms:
        skew = report.get("replica_skew") or {}
        if skew:
            print(f"{'replica':<14} {'count':>6} {'qwait_p50':>10} "
                  f"{'qwait_p95':>10} {'hit_share':>10} {'prefilled':>10}")
            for rep, row in sorted(skew.items()):
                print(f"{rep:<14} {row['count']:>6} "
                      f"{row['queue_wait_p50_ms']:>10.2f} "
                      f"{row['queue_wait_p95_ms']:>10.2f} "
                      f"{row['affinity_hit_share']:>10.2f} "
                      f"{row['prefilled_tokens']:>10}")


def _print_exemplar_waterfall(rec: dict, spans: list) -> None:
    """Text waterfall of one exemplar's stage timeline (the PR 1 trace
    span shapes, so the bar math matches `ray-tpu trace`)."""
    stages = [s for s in spans if s.get("parent_id")]
    if not stages:
        print("(no stages recorded)", file=sys.stderr)
        return
    t_min = min(s["start"] for s in stages)
    t_max = max(s["end"] for s in stages)
    span_total = max(t_max - t_min, 1e-9)
    width = 40
    head = (f"request {rec.get('request_id')} kind={rec.get('kind')} "
            f"violated={','.join(rec.get('violated') or []) or '-'} "
            f"replica={rec.get('replica') or '-'} "
            f"ttft_ms={rec.get('ttft_ms')} e2e_ms={rec.get('e2e_ms')}")
    print(f"# {head}", file=sys.stderr)
    for s in stages:
        off = int((s["start"] - t_min) / span_total * width)
        ln = max(1, int((s["end"] - s["start"]) / span_total * width))
        bar = " " * off + "█" * min(ln, width - off)
        dur_ms = (s["end"] - s["start"]) * 1e3
        attrs = s.get("attrs") or {}
        note = " ".join(f"{k}={v}" for k, v in attrs.items())
        print(f"{s['name'][6:]:<10} |{bar:<{width}}| "
              f"{dur_ms:>9.2f} ms  {note}")


def _fmt_hms(ts: float) -> str:
    import datetime
    return datetime.datetime.fromtimestamp(
        float(ts or 0.0)).strftime("%H:%M:%S.%f")[:-3]


def _fmt_event_line(ev: dict) -> str:
    ent = " ".join(f"{k}={ev[k]}" for k in
                   ("node", "deployment", "replica", "request_id")
                   if ev.get(k))
    attrs = ev.get("attrs") or {}
    note = " ".join(f"{k}={v}" for k, v in attrs.items())
    reason = ev.get("reason") or ""
    tail = " | ".join(x for x in (ent, reason, note) if x)
    return (f"{_fmt_hms(ev.get('ts'))} {ev.get('severity', 'INFO'):<7} "
            f"{ev.get('kind', '?'):<20} {tail}")


def cmd_events(args) -> None:
    """Flight recorder (ISSUE 19): tail the cluster event journal, or
    render one postmortem incident timeline joining events + metric
    spikes + SLO exemplars."""
    import ray_tpu
    from ray_tpu.util import state

    ray_tpu.init(address=_read_address(args.address))

    if args.postmortem is not None:
        pm = state.events_postmortem(window_s=args.postmortem)
        if args.json:
            print(json.dumps(pm, indent=2))
            return
        items = pm.get("items") or []
        print(f"# postmortem window {pm.get('window_s')}s "
              f"({_fmt_hms(pm.get('since'))} → {_fmt_hms(pm.get('until'))})"
              f", {len(items)} item(s)", file=sys.stderr)
        for it in items:
            typ = it.get("type")
            if typ == "event":
                print("EV  " + _fmt_event_line(it))
            elif typ == "exemplar":
                print(f"SLO {_fmt_hms(it.get('ts'))} VIOLATION "
                      f"request_id={it.get('request_id')} "
                      f"deployment={it.get('deployment') or '-'} "
                      f"violated={','.join(it.get('violated') or [])} "
                      f"ttft_ms={it.get('ttft_ms')} "
                      f"e2e_ms={it.get('e2e_ms')}")
            elif typ == "metric":
                tags = ",".join(it.get("tags") or [])
                print(f"MET {_fmt_hms(it.get('ts'))} peak    "
                      f"{it.get('name')}"
                      f"{('{' + tags + '}') if tags else ''} "
                      f"first={it.get('first')} peak={it.get('peak')} "
                      f"last={it.get('last')} "
                      f"points={it.get('points')} "
                      f"source={it.get('source')}")
        return

    since = (time.time() - args.since) if args.since else None
    rows = state.list_events(kind=args.kind, severity=args.severity,
                             entity=args.entity, since=since,
                             limit=args.tail)
    if args.json:
        print(json.dumps(rows, indent=2))
        return
    for ev in reversed(rows):  # store answers newest first; print in order
        print(_fmt_event_line(ev))
    print(f"# {len(rows)} event(s); `ray-tpu events --postmortem 300` "
          f"joins the last 5 minutes against metrics + SLO exemplars",
          file=sys.stderr)


def _parse_tags(spec: str | None) -> dict | None:
    tags = _parse_labels(spec)
    return tags or None


def cmd_metrics(args) -> None:
    import ray_tpu
    from ray_tpu.util import state

    ray_tpu.init(address=_read_address(args.address))
    if not args.name:
        # no name: catalogue of stored series
        for row in state.list_metric_series(prefix=args.prefix):
            print(json.dumps(row))
        return

    def show():
        res = state.query_metrics(args.name, tags=_parse_tags(args.tags),
                                  since=args.since, until=args.until)
        if res is None:
            print(f"no stored metric named {args.name!r}", file=sys.stderr)
            return
        for ser in res["series"]:
            tags = dict(zip(res["tag_keys"], ser["tags"]))
            print(f"# source={ser['source']} tags={tags}")
            for ts, val in ser["points"][-args.limit:]:
                print(json.dumps({"ts": ts, "value": val}))
        if res.get("merged"):
            from ray_tpu.util.metrics import percentiles_from_buckets
            qs = percentiles_from_buckets(res["boundaries"],
                                          res["merged"]["buckets"])
            print(f"# merged count={res['merged']['count']} "
                  f"sum={res['merged']['sum']:.6g} "
                  + " ".join(f"p{round(q * 100)}="
                             f"{'n/a' if v is None else format(v, '.6g')}"
                             for q, v in qs.items()))

    show()
    while args.watch:
        time.sleep(args.interval)
        print("---")
        show()


def cmd_lint(args) -> None:
    from ray_tpu.analysis.cli import lint

    rc = lint(paths=args.paths or None, json_out=args.json,
              write_baseline=args.baseline,
              baseline_file=args.baseline_file,
              include_tests=args.tests)
    raise SystemExit(rc)


def main(argv=None) -> None:
    p = argparse.ArgumentParser(prog="ray-tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("start", help="start a head or worker node")
    sp.add_argument("--head", action="store_true")
    sp.add_argument("--address", default=None)
    sp.add_argument("--port", type=int, default=6380)
    sp.add_argument("--num-cpus", type=float, default=None)
    sp.add_argument("--store-path", default=None,
                    help="sqlite path for control-plane fault tolerance")
    sp.add_argument("--dashboard-port", type=int, default=8265,
                    help="-1 disables the dashboard")
    sp.add_argument("--labels", default=None,
                    help="node labels, k=v[,k2=v2] (worker nodes)")
    sp.add_argument("--block", action="store_true",
                    help="run in the foreground")
    sp.set_defaults(fn=cmd_start)

    sp = sub.add_parser("stop", help="stop the local head + workers")
    sp.add_argument("--force", action="store_true",
                    help="also pkill every ray_tpu worker on this machine")
    sp.set_defaults(fn=cmd_stop)

    sp = sub.add_parser("status", help="cluster summary")
    sp.add_argument("--address", default=None)
    sp.set_defaults(fn=cmd_status)

    sp = sub.add_parser(
        "drain", help="gracefully drain a node (in-flight work finishes, "
                      "objects migrate) instead of killing it")
    sp.add_argument("node_id", help="node id (hex prefix ok)")
    sp.add_argument("--address", default=None)
    sp.add_argument("--no-wait", action="store_true",
                    help="request the drain and return immediately")
    sp.set_defaults(fn=cmd_drain)

    sp = sub.add_parser("submit", help="run an entrypoint as a managed job")
    sp.add_argument("--address", default=None)
    sp.add_argument("--working-dir", default=None)
    sp.add_argument("--no-wait", action="store_true")
    sp.add_argument("--timeout", type=float, default=3600.0)
    sp.add_argument("entrypoint", nargs=argparse.REMAINDER,
                    help="-- python my_script.py ...")
    sp.set_defaults(fn=cmd_submit)

    sp = sub.add_parser("jobs", help="list jobs")
    sp.add_argument("--address", default=None)
    sp.set_defaults(fn=cmd_jobs)

    sp = sub.add_parser("logs", help="print a job's driver log")
    sp.add_argument("job_id")
    sp.add_argument("--address", default=None)
    sp.add_argument("--tail", type=int, default=1000)
    sp.set_defaults(fn=cmd_logs)

    sp = sub.add_parser("microbenchmark", help="run core microbenchmarks")
    sp.add_argument("--quick", action="store_true")
    sp.set_defaults(fn=cmd_microbenchmark)

    sp = sub.add_parser("timeline", help="dump a chrome trace of task events")
    sp.add_argument("--address", default=None)
    sp.add_argument("--out", default=None)
    sp.set_defaults(fn=cmd_timeline)

    sp = sub.add_parser(
        "trace", help="list traces, or export one by id (chrome/otlp json)")
    sp.add_argument("trace_id", nargs="?", default=None,
                    help="trace id (prefix ok); omit to list traces")
    sp.add_argument("--address", default=None)
    sp.add_argument("--out", default=None,
                    help="output file (default: print to stdout)")
    sp.add_argument("--format", choices=("chrome", "otlp"), default="chrome")
    sp.add_argument("--limit", type=int, default=50,
                    help="max traces when listing")
    sp.set_defaults(fn=cmd_trace)

    sp = sub.add_parser(
        "metrics", help="list stored metric series, or query one by name")
    sp.add_argument("name", nargs="?", default=None,
                    help="metric name; omit to list the series catalogue")
    sp.add_argument("--address", default=None)
    sp.add_argument("--prefix", default="",
                    help="name prefix filter when listing")
    sp.add_argument("--tags", default=None,
                    help="tag filter, k=v[,k2=v2]")
    sp.add_argument("--since", type=float, default=None,
                    help="epoch-seconds lower bound")
    sp.add_argument("--until", type=float, default=None,
                    help="epoch-seconds upper bound")
    sp.add_argument("--limit", type=int, default=20,
                    help="max points printed per series")
    sp.add_argument("--watch", action="store_true",
                    help="re-query every --interval seconds")
    sp.add_argument("--interval", type=float, default=5.0)
    sp.set_defaults(fn=cmd_metrics)

    sp = sub.add_parser(
        "profile",
        help="capture an on-demand XPlane (jax.profiler) trace cluster-wide")
    sp.add_argument("--address", default=None)
    sp.add_argument("--node", default=None,
                    help="node id (hex prefix ok); default: all alive nodes")
    sp.add_argument("--duration", type=float, default=3.0,
                    help="capture window in seconds")
    sp.add_argument("--logdir", default=None,
                    help="trace output dir on the worker host "
                         "(default: /tmp/ray_tpu_xprof/<ts>-<pid>); "
                         "with --memory, the pprof output path")
    sp.add_argument("--memory", action="store_true",
                    help="dump device (HBM) memory profiles instead of "
                         "a time trace")
    sp.add_argument("--list", action="store_true",
                    help="list registered capture artifacts and exit")
    sp.set_defaults(fn=cmd_profile)

    sp = sub.add_parser(
        "kvtier",
        help="list the cluster tiered-KV index (spilled prefix pages)")
    sp.add_argument("--address", default=None)
    sp.add_argument("--gc", action="store_true",
                    help="drop expired index entries before listing")
    sp.add_argument("--json", action="store_true",
                    help="print the raw index document instead of rows")
    sp.set_defaults(fn=cmd_kvtier)

    sp = sub.add_parser(
        "slo",
        help="tail-latency attribution: per-stage breakdown, SLO "
             "exemplars, per-replica skew (observability/attribution.py)")
    sp.add_argument("--address", default=None)
    sp.add_argument("--deployment", default=None,
                    help="restrict the breakdown to one deployment")
    sp.add_argument("--exemplars", action="store_true",
                    help="list stored exemplar summaries (newest first)")
    sp.add_argument("--exemplar", default=None, metavar="REQUEST_ID",
                    help="render one exemplar's stage waterfall "
                         "(X-Request-Id, prefix ok)")
    sp.add_argument("--kind", default=None,
                    choices=("violation", "baseline"),
                    help="filter --exemplars by kind")
    sp.add_argument("--limit", type=int, default=50)
    sp.add_argument("--replica-skew", action="store_true",
                    help="also print the per-replica skew table")
    sp.add_argument("--out", default=None,
                    help="with --exemplar: write a chrome-trace JSON "
                         "instead of the text waterfall")
    sp.add_argument("--json", action="store_true")
    sp.set_defaults(fn=cmd_slo)

    sp = sub.add_parser(
        "events",
        help="flight recorder: tail the cluster event journal / render "
             "a postmortem timeline (observability/events.py)")
    sp.add_argument("--address", default=None)
    sp.add_argument("--tail", type=int, default=50, metavar="N",
                    help="show the last N matching events (default 50)")
    sp.add_argument("--since", type=float, default=None, metavar="SECONDS",
                    help="only events from the last SECONDS")
    sp.add_argument("--kind", default=None,
                    help="filter by event kind (e.g. replica_death)")
    sp.add_argument("--entity", default=None,
                    help="substring match over node/deployment/replica/"
                         "request id")
    sp.add_argument("--severity", default=None,
                    choices=("INFO", "WARNING", "ERROR"),
                    help="minimum severity (WARNING hides INFO)")
    sp.add_argument("--postmortem", type=float, default=None,
                    metavar="WINDOW_S",
                    help="render one ordered incident timeline for the "
                         "trailing window: events + metric spikes + SLO "
                         "exemplars")
    sp.add_argument("--json", action="store_true")
    sp.set_defaults(fn=cmd_events)

    sp = sub.add_parser(
        "lint",
        help="run graftlint (AST concurrency/JAX-hygiene passes) against "
             "the committed findings baseline")
    sp.add_argument("paths", nargs="*",
                    help="files/dirs to analyze (default: the ray_tpu "
                         "package)")
    sp.add_argument("--json", action="store_true",
                    help="machine-readable findings document on stdout")
    sp.add_argument("--baseline", action="store_true",
                    help="regenerate GRAFTLINT_BASELINE.json from this "
                         "run (keeps surviving justifications)")
    sp.add_argument("--baseline-file", default=None,
                    help="alternate baseline path (default: repo root)")
    sp.add_argument("--tests", action="store_true",
                    help="also run tests-scoped passes (tier1-marks)")
    sp.set_defaults(fn=cmd_lint)

    args = p.parse_args(argv)
    if args.cmd == "submit" and args.entrypoint \
            and args.entrypoint[0] == "--":
        args.entrypoint = args.entrypoint[1:]
    args.fn(args)


if __name__ == "__main__":
    main()
