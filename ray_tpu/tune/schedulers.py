"""Trial schedulers: FIFO, ASHA, median stopping, PBT.

TPU-native analog of the reference's schedulers
(/root/reference/python/ray/tune/schedulers/ — async_hyperband.py
AsyncHyperBandScheduler/ASHA, median_stopping_rule.py, pbt.py). The
controller calls `on_result` on every report and honors the returned
decision.
"""

from __future__ import annotations

import math
import random
from typing import Optional

CONTINUE = "CONTINUE"
STOP = "STOP"


class TrialScheduler:
    def on_result(self, trial, metrics: dict) -> str:
        return CONTINUE

    def on_complete(self, trial, metrics: Optional[dict]) -> None:
        pass


class FIFOScheduler(TrialScheduler):
    pass


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA (reference async_hyperband.py): successive-halving brackets with
    asynchronous promotion — a trial stops at a rung if its result is not in
    the top 1/reduction_factor of completed results at that rung."""

    def __init__(self, *, metric: str, mode: str = "max",
                 time_attr: str = "training_iteration",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: float = 3.0):
        assert mode in ("max", "min")
        self._metric = metric
        self._mode = mode
        self._time_attr = time_attr
        self._max_t = max_t
        self._grace = grace_period
        self._rf = reduction_factor
        # rung milestones: grace * rf^k up to max_t
        self._rungs: list[float] = []
        t = grace_period
        while t < max_t:
            self._rungs.append(t)
            t *= reduction_factor
        self._rungs.append(max_t)
        self._recorded: dict[float, list[float]] = {r: [] for r in self._rungs}
        self._trial_rung: dict[str, int] = {}
        self._last_recorded: dict[str, tuple[float, float]] = {}

    def _value(self, metrics) -> float:
        v = metrics[self._metric]
        return v if self._mode == "max" else -v

    def _cutoff(self, milestone: float) -> float | None:
        recorded = self._recorded[milestone]
        if len(recorded) < self._rf:
            return None
        cutoff_idx = max(0, int(len(recorded) / self._rf) - 1)
        return sorted(recorded, reverse=True)[cutoff_idx]

    def on_result(self, trial, metrics: dict) -> str:
        t = metrics.get(self._time_attr)
        if t is None or self._metric not in metrics:
            return CONTINUE
        if t >= self._max_t:
            return STOP
        rung_idx = self._trial_rung.get(trial.trial_id, 0)
        if rung_idx >= len(self._rungs):
            return STOP
        milestone = self._rungs[rung_idx]
        if t >= milestone:
            value = self._value(metrics)
            self._recorded[milestone].append(value)
            self._trial_rung[trial.trial_id] = rung_idx + 1
            self._last_recorded[trial.trial_id] = (milestone, value)
            cutoff = self._cutoff(milestone)
            if cutoff is not None and value < cutoff:
                return STOP
            return CONTINUE
        # Retroactive cut (determinism under concurrency): a trial that
        # recorded at its last rung BEFORE its peers may only later fall
        # below the rung's top-1/rf cutoff — re-check against the rung's
        # CURRENT population every report so the decision doesn't depend on
        # which trial happened to report first.
        last = self._last_recorded.get(trial.trial_id)
        if last is not None:
            last_milestone, last_value = last
            cutoff = self._cutoff(last_milestone)
            if cutoff is not None and last_value < cutoff:
                return STOP
        return CONTINUE


ASHAScheduler = AsyncHyperBandScheduler


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose best result is worse than the median of running
    averages (reference median_stopping_rule.py)."""

    def __init__(self, *, metric: str, mode: str = "max",
                 time_attr: str = "training_iteration",
                 grace_period: int = 1, min_samples_required: int = 3):
        self._metric = metric
        self._mode = mode
        self._time_attr = time_attr
        self._grace = grace_period
        self._min_samples = min_samples_required
        self._history: dict[str, list[float]] = {}

    def _value(self, metrics) -> float:
        v = metrics[self._metric]
        return v if self._mode == "max" else -v

    def on_result(self, trial, metrics: dict) -> str:
        if self._metric not in metrics:
            return CONTINUE
        hist = self._history.setdefault(trial.trial_id, [])
        hist.append(self._value(metrics))
        t = metrics.get(self._time_attr, len(hist))
        if t < self._grace or len(self._history) < self._min_samples:
            return CONTINUE
        means = [sum(h) / len(h) for h in self._history.values() if h]
        means_sorted = sorted(means)
        median = means_sorted[len(means_sorted) // 2]
        if max(hist) < median:
            return STOP
        return CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference pbt.py): at each perturbation interval, bottom-quantile
    trials exploit (copy hyperparams + checkpoint of) a top-quantile trial
    and explore (perturb) the copied hyperparams. The controller applies the
    returned mutation via trial restart."""

    def __init__(self, *, metric: str, mode: str = "max",
                 time_attr: str = "training_iteration",
                 perturbation_interval: int = 5,
                 hyperparam_mutations: Optional[dict] = None,
                 quantile_fraction: float = 0.25,
                 seed: Optional[int] = None):
        self._metric = metric
        self._mode = mode
        self._time_attr = time_attr
        self._interval = perturbation_interval
        self._mutations = hyperparam_mutations or {}
        self._quantile = quantile_fraction
        self._scores: dict[str, float] = {}
        self._last_perturb: dict[str, float] = {}
        self._rng = random.Random(seed)
        self.exploit_requests: dict[str, dict] = {}  # trial_id -> new config

    def on_exploit(self, trial_id: str) -> None:
        """Called by the tuner when an exploit/restart is applied."""

    def _value(self, metrics) -> float:
        v = metrics[self._metric]
        return v if self._mode == "max" else -v

    def on_result(self, trial, metrics: dict) -> str:
        if self._metric not in metrics:
            return CONTINUE
        self._scores[trial.trial_id] = self._value(metrics)
        t = metrics.get(self._time_attr, 0)
        last = self._last_perturb.get(trial.trial_id, 0)
        if t - last < self._interval or len(self._scores) < 2:
            return CONTINUE
        self._last_perturb[trial.trial_id] = t
        ranked = sorted(self._scores.items(), key=lambda kv: kv[1])
        n = len(ranked)
        k = max(1, int(n * self._quantile))
        bottom = [tid for tid, _ in ranked[:k]]
        top = [tid for tid, _ in ranked[-k:]]
        if trial.trial_id in bottom and top:
            donor_id = self._rng.choice(top)
            self.exploit_requests[trial.trial_id] = {"donor": donor_id,
                                                     "explore": True}
        return CONTINUE

    def mutate_config(self, config: dict) -> dict:
        out = dict(config)
        for key, spec in self._mutations.items():
            if key not in out:
                continue
            if isinstance(spec, list):
                out[key] = self._rng.choice(spec)
            elif callable(spec):
                out[key] = spec()
            else:  # perturb numerically
                factor = self._rng.choice([0.8, 1.2])
                out[key] = out[key] * factor
        return out


class HyperBandForBOHB(AsyncHyperBandScheduler):
    """BOHB's bracket half (reference schedulers/hb_bohb.py): ASHA-style
    rung pruning that additionally FEEDS every rung result to the paired
    model-based searcher, so new suggestions are drawn from the TPE model
    of the deepest rung with enough observations (the BOHB coupling;
    pair with ``BOHBSearcher`` via ``create_bohb``)."""

    def __init__(self, *, searcher=None, **kw):
        super().__init__(**kw)
        self._searcher = searcher

    def on_result(self, trial, metrics: dict) -> str:
        if self._searcher is not None and self._metric in metrics:
            t = metrics.get(self._time_attr, 0)
            rung_idx = self._trial_rung.get(trial.trial_id, 0)
            # feed the model only at RUNG CROSSINGS (the milestones ASHA
            # prunes at), not every report: a handful of fidelity buckets,
            # one observation per trial per rung
            if rung_idx < len(self._rungs) and t >= self._rungs[rung_idx]:
                self._searcher.observe_rung(
                    getattr(trial, "config", {}) or {},
                    metrics[self._metric], self._rungs[rung_idx])
        return super().on_result(trial, metrics)


class PB2(PopulationBasedTraining):
    """PB2 (reference schedulers/pb2.py): PBT whose EXPLORE step picks new
    hyperparameters with a GP-UCB bandit fit on observed
    (hyperparams -> score improvement) data, instead of random *0.8/*1.2
    perturbation — far more sample-efficient at small population sizes.
    The GP is a small RBF-kernel regressor on normalized numeric
    hyperparams; categorical mutations fall back to PBT's choice."""

    def __init__(self, *, hyperparam_bounds: Optional[dict] = None, **kw):
        super().__init__(**kw)
        self._bounds = hyperparam_bounds or {}
        self._observations: list[tuple[dict, float]] = []  # (cfg, d_score)
        self._prev_score: dict[str, float] = {}

    def on_result(self, trial, metrics: dict) -> str:
        if self._metric in metrics:
            cur = self._value(metrics)
            prev = self._prev_score.get(trial.trial_id)
            if prev is not None:
                cfg = {k: (getattr(trial, "config", {}) or {}).get(k)
                       for k in self._bounds}
                if all(isinstance(v, (int, float)) for v in cfg.values()):
                    self._observations.append((cfg, cur - prev))
                    self._observations = self._observations[-128:]
            self._prev_score[trial.trial_id] = cur
        return super().on_result(trial, metrics)

    # -- tiny GP-UCB over normalized hyperparams ------------------------
    def _normalize(self, cfg: dict):
        import numpy as np
        x = []
        for k, (lo, hi) in self._bounds.items():
            v = float(cfg.get(k, lo))
            x.append((v - lo) / max(hi - lo, 1e-12))
        return np.asarray(x)

    def _gp_ucb(self, candidates: list[dict], kappa: float = 1.5) -> dict:
        import numpy as np
        if len(self._observations) < 3:
            return self._rng.choice(candidates)
        X = np.stack([self._normalize(c) for c, _ in self._observations])
        y = np.asarray([d for _, d in self._observations])
        y = (y - y.mean()) / (y.std() + 1e-8)

        def rbf(a, b, ls=0.3):
            d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
            return np.exp(-d2 / (2 * ls * ls))

        K = rbf(X, X) + 1e-4 * np.eye(len(X))
        K_inv = np.linalg.inv(K)
        C = np.stack([self._normalize(c) for c in candidates])
        Ks = rbf(C, X)
        mu = Ks @ K_inv @ y
        var = np.clip(1.0 - np.einsum("ij,jk,ik->i", Ks, K_inv, Ks),
                      1e-9, None)
        ucb = mu + kappa * np.sqrt(var)
        return candidates[int(np.argmax(ucb))]

    def on_exploit(self, trial_id: str) -> None:
        # the first post-restart score reflects the DONOR's checkpoint,
        # not the mutated hyperparams: without clearing the baseline the
        # exploit jump would be credited to the new config and bias the GP
        self._prev_score.pop(trial_id, None)

    def mutate_config(self, config: dict) -> dict:
        out = super().mutate_config(config)  # categoricals / non-bounded
        if not self._bounds:
            return out
        candidates = []
        for _ in range(32):
            cand = dict(out)
            for k, (lo, hi) in self._bounds.items():
                base = float(config.get(k, (lo + hi) / 2))
                width = (hi - lo) * 0.2
                cand[k] = min(hi, max(lo, base + self._rng.uniform(
                    -width, width)))
            candidates.append(cand)
        return self._gp_ucb(candidates)
