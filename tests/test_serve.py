"""Serve tests (models the reference's serve test strategy:
python/ray/serve/tests/ — deployment lifecycle, handles, composition,
batching, autoscaling decisions, HTTP ingress)."""

import time

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture(scope="module")
def ray_start_regular(ray_start_module):
    yield ray_start_module



@pytest.fixture
def serve_shutdown(ray_start_regular):
    yield
    serve.shutdown()


def test_deployment_function(serve_shutdown):
    @serve.deployment
    def hello(name):
        return f"hello {name}"

    handle = serve.run(hello.bind(), name="app1", route_prefix=None)
    assert handle.remote("world").result(timeout_s=30) == "hello world"
    serve.delete("app1")


def test_deployment_class_replicas(serve_shutdown):
    @serve.deployment(num_replicas=2)
    class Counter:
        def __init__(self, start):
            self.count = start

        def __call__(self, inc):
            self.count += inc
            return self.count

        def peek(self):
            return self.count

    handle = serve.run(Counter.bind(10), name="app2", route_prefix=None)
    out = handle.remote(1).result(timeout_s=30)
    assert out == 11
    st = serve.status()
    assert st["app2#Counter"]["replicas"] == 2
    # method routing
    peek = handle.peek.remote().result(timeout_s=30)
    assert peek in (10, 11)
    serve.delete("app2")


def test_composition(serve_shutdown):
    @serve.deployment
    class Doubler:
        def __call__(self, x):
            return x * 2

    @serve.deployment
    class Ingress:
        def __init__(self, doubler):
            self.doubler = doubler

        def __call__(self, x):
            resp = self.doubler.remote(x)
            return resp.result(timeout_s=20) + 1

    app = Ingress.bind(Doubler.bind())
    handle = serve.run(app, name="app3", route_prefix=None)
    assert handle.remote(5).result(timeout_s=30) == 11
    serve.delete("app3")


def test_user_config_reconfigure(serve_shutdown):
    @serve.deployment(user_config={"threshold": 5})
    class Thresholder:
        def __init__(self):
            self.threshold = None

        def reconfigure(self, config):
            self.threshold = config["threshold"]

        def __call__(self, x):
            return x > self.threshold

    handle = serve.run(Thresholder.bind(), name="app4", route_prefix=None)
    assert handle.remote(7).result(timeout_s=30) is True
    # redeploy with new user_config reconfigures in place
    handle = serve.run(Thresholder.options(
        user_config={"threshold": 10}).bind(), name="app4", route_prefix=None)
    assert handle.remote(7).result(timeout_s=30) is False
    serve.delete("app4")


def test_batching(serve_shutdown):
    @serve.deployment
    class Batched:
        def __init__(self):
            self.batch_sizes = []

        @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.2)
        async def handle_batch(self, items):
            self.batch_sizes.append(len(items))
            return [x * 10 for x in items]

        async def __call__(self, x):
            return await self.handle_batch(x)

        def sizes(self):
            return self.batch_sizes

    handle = serve.run(Batched.bind(), name="app5", route_prefix=None)
    resps = [handle.remote(i) for i in range(8)]
    out = sorted(r.result(timeout_s=30) for r in resps)
    assert out == [i * 10 for i in range(8)]
    sizes = handle.sizes.remote().result(timeout_s=30)
    assert max(sizes) > 1  # batching actually happened
    serve.delete("app5")


def test_autoscaling_decision():
    from ray_tpu.serve.config import AutoscalingConfig

    asc = AutoscalingConfig(min_replicas=1, max_replicas=5,
                            target_ongoing_requests=2)
    assert asc.decide(current=1, total_ongoing=10) == 5
    assert asc.decide(current=5, total_ongoing=2) == 1
    assert asc.decide(current=2, total_ongoing=4) == 2


def test_replica_failure_recovery(serve_shutdown):
    @serve.deployment(num_replicas=1, health_check_period_s=0.2)
    class Fragile:
        def __call__(self, x):
            return x + 1

        def die(self):
            import os
            os._exit(1)

    handle = serve.run(Fragile.bind(), name="app6", route_prefix=None)
    assert handle.remote(1).result(timeout_s=30) == 2
    try:
        handle.die.remote().result(timeout_s=5)
    except Exception:
        pass
    # controller health loop should replace the dead replica
    deadline = time.time() + 60
    ok = False
    while time.time() < deadline:
        try:
            if handle.remote(5).result(timeout_s=10) == 6:
                ok = True
                break
        except Exception:
            time.sleep(0.5)
    assert ok, "replica was not replaced after death"
    serve.delete("app6")


def test_http_proxy(serve_shutdown):
    import json
    import urllib.request

    @serve.deployment
    class Echo:
        def __call__(self, payload):
            if isinstance(payload, dict):
                return {"got": payload}
            return {"got": str(payload)}

    serve.run(Echo.bind(), name="httpapp", route_prefix="/echo")
    proxy = serve.start_http_proxy(port=18123)

    req = urllib.request.Request(
        "http://127.0.0.1:18123/echo", data=json.dumps({"a": 1}).encode(),
        headers={"Content-Type": "application/json"})
    body = json.loads(urllib.request.urlopen(req, timeout=30).read())
    assert body == {"got": {"a": 1}}

    health = urllib.request.urlopen(
        "http://127.0.0.1:18123/-/healthz", timeout=10).read()
    assert health == b"ok"
    routes = json.loads(urllib.request.urlopen(
        "http://127.0.0.1:18123/-/routes", timeout=10).read())
    assert "/echo" in routes
    serve.delete("httpapp")


def test_multiplexed_models(ray_start_regular):
    """@serve.multiplexed LRU-caches per-model state per replica; handle
    .options(multiplexed_model_id=...) routes the same model to the same
    replica (rendezvous affinity)."""
    from ray_tpu import serve

    @serve.deployment(num_replicas=2)
    class MultiModel:
        def __init__(self):
            self.loads = []

        @serve.multiplexed(max_num_models_per_replica=2)
        async def get_model(self, model_id: str):
            self.loads.append(model_id)
            return f"model-{model_id}"

        async def __call__(self, x):
            model_id = serve.get_multiplexed_model_id()
            model = await self.get_model(model_id)
            return {"model": model, "loads": len(self.loads), "x": x}

    handle = serve.run(MultiModel.bind(), name="mx", route_prefix="/mx")
    h1 = handle.options(multiplexed_model_id="m1")
    outs = [h1.remote(i).result(timeout_s=60) for i in range(4)]
    assert all(o["model"] == "model-m1" for o in outs)
    # the model loaded ONCE despite 4 requests (same replica + LRU cache)
    assert outs[-1]["loads"] == 1
    h2 = handle.options(multiplexed_model_id="m2")
    out2 = h2.remote(0).result(timeout_s=60)
    assert out2["model"] == "model-m2"
    serve.shutdown()


def test_grpc_ingress(serve_shutdown):
    """gRPC ingress (reference gRPCProxy + serve.proto wire protocol):
    generic unary calls route to deployments by method name + metadata."""
    import grpc

    from ray_tpu import serve

    @serve.deployment
    class Echo:
        def __call__(self, data: bytes) -> bytes:
            return b"echo:" + data

        def shout(self, data: bytes) -> str:
            return data.decode().upper()

    serve.run(Echo.bind(), name="gapp")
    proxy = serve.start_grpc_proxy(port=0, default_app="gapp")
    try:
        chan = grpc.insecure_channel(f"127.0.0.1:{proxy.port}")
        call = chan.unary_unary("/ray_tpu.serve.UserDefined/__call__")
        out = call(b"hi", timeout=60)
        assert out == b"echo:hi"
        shout = chan.unary_unary("/ray_tpu.serve.UserDefined/shout")
        out = shout(b"quiet", timeout=60,
                    metadata=(("application", "gapp"),))
        assert out == b"QUIET"
        health = chan.unary_unary("/grpc.health.v1.Health/Check")
        assert health(b"", timeout=30) == b"\x08\x01"
    finally:
        serve.shutdown()


def test_local_testing_mode_composition():
    """serve.run(_local_testing_mode=True): the whole app runs in-process
    with no cluster — composed deployments, method routing, and
    response-as-argument resolution all behave like the real handle
    surface (reference: serve/_private/local_testing_mode.py)."""
    from ray_tpu import serve

    @serve.deployment
    class Embedder:
        def __init__(self, scale):
            self.scale = scale

        def embed(self, x):
            return [v * self.scale for v in x]

    @serve.deployment
    class Ranker:
        def __init__(self, embedder):
            self.embedder = embedder

        def __call__(self, x):
            emb = self.embedder.options(method_name="embed").remote(x)
            return sum(emb.result())

        def top(self, x):
            return max(self.embedder.embed.remote(x).result())

    handle = serve.run(Ranker.bind(Embedder.bind(10)),
                       _local_testing_mode=True)
    assert handle.remote([1, 2, 3]).result(timeout_s=30) == 60
    assert handle.options(method_name="top").remote([1, 5, 2]).result(
        timeout_s=30) == 50
    assert handle.top.remote([2, 4]).result(timeout_s=30) == 40

    # a response passed as an argument resolves before the call
    emb_handle = handle._instance.embedder
    pre = emb_handle.embed.remote([1, 1])
    assert handle.remote(pre).result(timeout_s=30) == 200


def test_local_testing_mode_function_deployment():
    from ray_tpu import serve

    @serve.deployment
    def double(x):
        return 2 * x

    handle = serve.run(double.bind(), _local_testing_mode=True)
    assert handle.remote(21).result(timeout_s=30) == 42
