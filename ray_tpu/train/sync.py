"""Control-plane collectives: barrier / broadcast between train workers.

TPU-native analog of the reference's SynchronizationActor
(/root/reference/python/ray/train/v2/_internal/execution/checkpoint/sync_actor.py:27
and train/collective/collectives.py): a named actor all ranks rendezvous on.
Device-plane collectives are XLA's business (psum over ICI); this is only for
host-side control flow (checkpoint barriers, config broadcast).
"""

from __future__ import annotations

import threading
import time

import ray_tpu


@ray_tpu.remote(max_concurrency=64)
class SynchronizationActor:
    """Reusable barrier + value broadcast for a fixed world size.

    Generation counter makes the barrier reusable (ranks can hit it
    repeatedly); broadcast follows last-writer-from-rank-0 semantics like the
    reference's `broadcast_from_rank_zero`.
    """

    def __init__(self, world_size: int):
        self._world = world_size
        self._gen = 0
        self._arrived = 0
        self._values: dict = {}
        self._cv = threading.Condition()

    def barrier(self, rank: int, value=None, timeout: float = 600.0):
        """Block until all ranks arrive; returns the dict {rank: value}."""
        with self._cv:
            gen = self._gen
            self._values[rank] = value
            self._arrived += 1
            if self._arrived == self._world:
                self._gen += 1
                self._arrived = 0
                result = dict(self._values)
                self._values = {}
                self._last_result = result
                self._cv.notify_all()
                return result
            deadline = time.monotonic() + timeout
            while self._gen == gen:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"barrier timeout: {self._arrived}/{self._world} "
                        f"ranks arrived")
                self._cv.wait(remaining)
            return self._last_result

    def broadcast_from_rank_zero(self, rank: int, value=None,
                                 timeout: float = 600.0):
        result = self.barrier(rank, value, timeout)
        return result.get(0)


def create_sync_actor(world_size: int, name: str):
    return SynchronizationActor.options(name=name).remote(world_size)
