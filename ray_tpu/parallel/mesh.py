"""Device mesh construction over ICI × DCN.

The TPU-native communication substrate (SURVEY.md §2.3, §5.8): where the
reference wires NCCL process groups per parallelism strategy
(/root/reference/python/ray/train/torch/config.py:73,
python/ray/util/collective/collective.py:166), this framework expresses every
parallelism as axes of a single `jax.sharding.Mesh` — XLA emits the
collectives (psum/all-gather/reduce-scatter/ppermute/all-to-all) over ICI
within a slice and DCN across slices.

Canonical axis order (outer → inner, slowest → fastest varying):
    ("replica", "data", "fsdp", "expert", "pipeline", "context", "tensor")
DCN-parallel axes (replica/data) go outermost so cross-slice traffic is
minimized; tensor goes innermost so its collectives ride the shortest ICI
links (the scaling-book layout recipe).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

# canonical axis order, outermost first
AXIS_ORDER = ("replica", "data", "fsdp", "expert", "pipeline", "context", "tensor")
# axes whose collectives may cross DCN (slices); the rest must stay on ICI
DCN_AXES = ("replica", "data")


@dataclass
class MeshSpec:
    """Logical parallelism spec, independent of physical devices."""

    data: int = 1
    fsdp: int = 1
    tensor: int = 1
    pipeline: int = 1
    expert: int = 1
    context: int = 1
    replica: int = 1
    # multislice: how many slices the replica/data axes span (1 = single slice)
    num_slices: int = 1

    def axis_sizes(self) -> dict[str, int]:
        return {name: getattr(self, name) for name in AXIS_ORDER}

    def total_devices(self) -> int:
        return math.prod(self.axis_sizes().values())

    def active_axes(self) -> tuple[str, ...]:
        return tuple(a for a in AXIS_ORDER if self.axis_sizes()[a] > 1)

    @classmethod
    def infer(cls, n_devices: int, *, tensor: int = 1, pipeline: int = 1,
              expert: int = 1, context: int = 1, fsdp: int | None = None,
              num_slices: int = 1) -> "MeshSpec":
        """Fill the fsdp/data axes to cover all devices: explicit model axes
        first, fsdp soaks up the rest (pure-DP when fsdp=1 is requested)."""
        model = tensor * pipeline * expert * context
        if n_devices % model != 0:
            raise ValueError(f"{n_devices} devices not divisible by model axes {model}")
        rest = n_devices // model
        if fsdp is None:
            fsdp = rest
            data = 1
        else:
            if rest % fsdp != 0:
                raise ValueError(f"residual {rest} not divisible by fsdp={fsdp}")
            data = rest // fsdp
        return cls(data=data, fsdp=fsdp, tensor=tensor, pipeline=pipeline,
                   expert=expert, context=context, num_slices=num_slices)


def build_mesh(spec: MeshSpec, devices=None) -> Mesh:
    """Build a Mesh whose physical layout respects ICI topology.

    Single-slice: `mesh_utils.create_device_mesh` lays axes onto the torus so
    inner axes get contiguous ICI neighborhoods. Multislice:
    `create_hybrid_device_mesh` puts DCN axes across slices.
    """
    if devices is None:
        devices = jax.devices()
    sizes = spec.axis_sizes()
    names = tuple(sizes.keys())
    shape = tuple(sizes[n] for n in names)
    n = math.prod(shape)
    if n > len(devices):
        raise ValueError(f"mesh needs {n} devices, have {len(devices)}")
    devices = devices[:n]
    if spec.num_slices > 1:
        dcn_shape = tuple(
            sizes[a] if a in DCN_AXES else 1 for a in names)
        if math.prod(dcn_shape) != spec.num_slices:
            raise ValueError(
                f"DCN axes {DCN_AXES} product {math.prod(dcn_shape)} "
                f"!= num_slices {spec.num_slices}")
        ici_shape = tuple(
            1 if a in DCN_AXES else sizes[a] for a in names)
        dev_array = mesh_utils.create_hybrid_device_mesh(
            ici_shape, dcn_shape, devices=devices)
    else:
        try:
            dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
        except Exception:
            dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, names)


def single_device_mesh() -> Mesh:
    return build_mesh(MeshSpec(), jax.devices()[:1])


def validate_spec_for_slice(spec: MeshSpec, *, ici_devices: int) -> None:
    """Reject specs whose ICI-only axes don't fit in one slice — collectives on
    tensor/context/pipeline axes must never cross DCN."""
    ici = math.prod(v for a, v in spec.axis_sizes().items() if a not in DCN_AXES)
    if ici > ici_devices:
        raise ValueError(
            f"ICI axes need {ici} devices but a slice has {ici_devices}; "
            f"move parallelism to the data/replica (DCN) axes")
