"""Runtime-env packaging + materialization.

Reference: python/ray/_private/runtime_env/packaging.py (zip working_dir /
py_modules into the GCS KV under content-hash URIs; agents download + cache
by URI) and runtime_env/agent (per-node materialization before worker
start).
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import shutil
import tempfile
import zipfile

_PKG_PREFIX = "pkg:"
_ENV_ROOT = "/tmp/ray_tpu_envs"
_MAX_PKG_BYTES = 100 * 1024 * 1024


class RuntimeEnvError(ValueError):
    pass


def _zip_dir(path: str) -> bytes:
    buf = io.BytesIO()
    base = os.path.abspath(path)
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, _dirs, files in os.walk(base):
            if "__pycache__" in root:
                continue
            for f in files:
                full = os.path.join(root, f)
                zf.write(full, os.path.relpath(full, base))
    data = buf.getvalue()
    if len(data) > _MAX_PKG_BYTES:
        raise RuntimeEnvError(
            f"runtime_env package {path} is {len(data)} bytes "
            f"(limit {_MAX_PKG_BYTES}); ship data through the object store "
            f"instead")
    return data


def _upload_dir(rt, path: str) -> str:
    """Zip a directory into the CP KV; returns its kv:// URI."""
    if not os.path.isdir(path):
        raise RuntimeEnvError(f"runtime_env dir not found: {path}")
    data = _zip_dir(path)
    digest = hashlib.sha1(data).hexdigest()[:20]
    key = f"{_PKG_PREFIX}{digest}"
    rt.cp_client.call_with_retry(
        "kv_put", {"key": key, "value": data, "overwrite": False},
        timeout=60.0)
    return f"kv://{key}"


def prepare_runtime_env(rt, runtime_env: dict | None) -> dict | None:
    """Driver side: validate + upload local dirs, returning a normalized
    runtime_env whose dirs are kv:// URIs (safe to ship in a TaskSpec)."""
    if not runtime_env:
        return None
    out = dict(runtime_env)
    unknown = set(out) - {"env_vars", "working_dir", "py_modules", "pip"}
    if unknown:
        raise RuntimeEnvError(f"unsupported runtime_env keys: {unknown}")
    if out.get("env_vars"):
        if not all(isinstance(k, str) and isinstance(v, str)
                   for k, v in out["env_vars"].items()):
            raise RuntimeEnvError("env_vars must be str->str")
    wd = out.get("working_dir")
    if wd and not wd.startswith("kv://"):
        out["working_dir"] = _upload_dir(rt, wd)
    mods = out.get("py_modules")
    if mods:
        out["py_modules"] = [
            m if m.startswith("kv://") else _upload_dir(rt, m) for m in mods]
    pip = out.get("pip")
    if pip:
        out["pip"] = _normalize_pip(pip)
    return out


def _is_local_req(req: str) -> bool:
    """A requirement installs offline iff it is an EXPLICIT path (absolute,
    ./relative, or file://). Bare names never count — probing the
    filesystem for them would make 'requests' mean a same-named CWD
    directory on one node and the PyPI package on another."""
    return req.startswith(("/", "./", "file://"))


def _normalize_pip(pip) -> dict:
    """Accept the reference's shapes — a list of requirement strings or
    {"packages": [...]} — normalized to {"packages": [...]}. Requirements
    that are local paths (wheels / directories) install offline; anything
    else needs the network and is gated by config, since index installs on
    an air-gapped TPU pod would hang every lease that needs the env."""
    if isinstance(pip, (list, tuple)):
        pip = {"packages": list(pip)}
    if not isinstance(pip, dict) or not isinstance(
            pip.get("packages"), (list, tuple)):
        raise RuntimeEnvError(
            "runtime_env['pip'] must be a list of requirements or "
            "{'packages': [...]}")
    pkgs = [str(p) for p in pip["packages"]]
    needs_net = [p for p in pkgs if not _is_local_req(p)]
    if needs_net:
        from ray_tpu.core.config import get_config
        if not get_config().allow_runtime_env_pip:
            raise RuntimeEnvError(
                f"runtime_env pip requirements {needs_net} need network "
                "access; set RAY_TPU_ALLOW_RUNTIME_ENV_PIP=1 to enable "
                "(local wheel/dir paths install without it)")
    return {"packages": pkgs}


def _venv_python(spec: dict) -> str:
    """Materialize an isolated virtualenv for a pip runtime_env; returns
    its python executable. Cached under a spec-hash directory with a
    .ready marker (reference: _private/runtime_env/uv.py / pip.py +
    uri_cache.py). Prefers ``uv venv``/``uv pip`` when uv is on PATH
    (reference uv plugin); falls back to stdlib venv + pip.

    --system-site-packages: the env inherits the base interpreter's
    packages (jax, numpy, the framework) and installed requirements
    shadow them — per-job package ISOLATION with shared heavyweights,
    the reference pip plugin's behavior."""
    import subprocess
    import sys

    spec_key = hashlib.sha1(
        json.dumps(spec, sort_keys=True).encode()).hexdigest()[:16]
    dest = os.path.join(_ENV_ROOT, f"venv-{spec_key}")
    py = os.path.join(dest, "bin", "python")
    marker = os.path.join(dest, ".ready")
    if os.path.exists(marker):
        return py
    os.makedirs(_ENV_ROOT, exist_ok=True)
    tmp = tempfile.mkdtemp(prefix=f"venv-{spec_key}.tmp.", dir=_ENV_ROOT)
    tmp_py = os.path.join(tmp, "bin", "python")
    try:
        uv = shutil.which("uv")
        if uv:
            subprocess.run(
                [uv, "venv", "--system-site-packages",
                 "--python", sys.executable, tmp],
                check=True, capture_output=True, timeout=300)
            install = [uv, "pip", "install", "--python", tmp_py]
        else:
            subprocess.run(
                [sys.executable, "-m", "venv", "--system-site-packages",
                 tmp],
                check=True, capture_output=True, timeout=300)
            install = [tmp_py, "-m", "pip", "install", "--quiet"]
        # --system-site-packages exposes the BASE interpreter's packages;
        # when this process itself runs in a venv (the common dev install),
        # that loses its site-packages (numpy, jax, ...). A .pth appends
        # the parent's site dirs AFTER the new env's own, so installed
        # requirements still shadow them.
        parent_sites = [p for p in sys.path
                        if p.rstrip("/").endswith("site-packages")]
        if parent_sites:
            import glob as _glob
            for sp in _glob.glob(os.path.join(
                    tmp, "lib", "python*", "site-packages")):
                with open(os.path.join(sp, "_rtpu_parent_sites.pth"),
                          "w") as f:
                    f.write("\n".join(parent_sites) + "\n")
        pkgs = list(spec.get("packages") or [])
        local_only = all(_is_local_req(p) for p in pkgs)
        if pkgs:
            cmd = install + (["--no-index"] if local_only else []) + pkgs
            r = subprocess.run(cmd, capture_output=True, timeout=600)
            if r.returncode != 0:
                raise RuntimeEnvError(
                    f"pip install for runtime_env failed: "
                    f"{r.stderr.decode()[-500:]}")
        open(os.path.join(tmp, ".ready"), "w").close()
        try:
            os.rename(tmp, dest)
        except OSError:
            if not os.path.exists(marker):
                raise
            shutil.rmtree(tmp, ignore_errors=True)
    except Exception:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    return py


def env_hash(runtime_env: dict | None) -> str:
    """Stable identity for worker pooling (reference worker_pool env hash)."""
    if not runtime_env:
        return ""
    return hashlib.sha1(
        json.dumps(runtime_env, sort_keys=True).encode()).hexdigest()[:16]


def _fetch_pkg(cp_client, uri: str) -> str:
    """Download + unzip a kv:// package on this node; cached by digest."""
    key = uri[len("kv://"):]
    dest = os.path.join(_ENV_ROOT, key.replace(":", "_"))
    marker = os.path.join(dest, ".ready")
    if os.path.exists(marker):
        return dest
    data = cp_client.call_with_retry("kv_get", {"key": key}, timeout=60.0)
    if data is None:
        raise RuntimeEnvError(f"runtime_env package missing from KV: {uri}")
    os.makedirs(_ENV_ROOT, exist_ok=True)
    # extract to a private temp dir + atomic rename: concurrent lease
    # threads materializing the same env must never interleave writes into
    # a directory a worker is already importing from
    tmp = tempfile.mkdtemp(prefix=os.path.basename(dest) + ".tmp.",
                           dir=_ENV_ROOT)
    try:
        with zipfile.ZipFile(io.BytesIO(data)) as zf:
            zf.extractall(tmp)
        open(os.path.join(tmp, ".ready"), "w").close()
        try:
            os.rename(tmp, dest)
        except OSError:
            # a racer beat us to the rename — their copy is identical
            if not os.path.exists(marker):
                raise
            shutil.rmtree(tmp, ignore_errors=True)
    except Exception:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    return dest


def materialize_runtime_env(cp_client, runtime_env: dict | None
                            ) -> tuple[dict, str | None, list[str],
                                       str | None]:
    """Agent side (before worker spawn): returns (env_vars, cwd,
    pythonpath_entries, python_exe) for the worker process. python_exe is
    non-None when the env carries a pip spec — the worker must run inside
    that spec's virtualenv."""
    if not runtime_env:
        return {}, None, [], None
    env_vars = dict(runtime_env.get("env_vars") or {})
    cwd = None
    pypath: list[str] = []
    wd = runtime_env.get("working_dir")
    if wd:
        cwd = _fetch_pkg(cp_client, wd)
        pypath.append(cwd)
    for uri in runtime_env.get("py_modules") or []:
        pypath.append(_fetch_pkg(cp_client, uri))
    python_exe = None
    pip = runtime_env.get("pip")
    if pip:
        python_exe = _venv_python(_normalize_pip(pip))
    return env_vars, cwd, pypath, python_exe
