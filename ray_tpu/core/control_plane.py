"""Control plane — the cluster's single source of truth.

TPU-native analog of the reference's GCS (/root/reference/src/ray/gcs/ —
GcsServer gcs_server.h:95): owns node membership, the actor directory and actor
scheduling (GcsActorManager gcs_actor_manager.h:92, GcsActorScheduler
gcs_actor_scheduler.h:108), placement groups with 2-phase prepare/commit
(gcs_placement_group_scheduler.cc), internal KV (store_client_kv.cc), pubsub
(GcsPublisher), health checks (gcs_health_check_manager.h:45), and the cluster
resource view (GcsResourceManager + RaySyncer-style reports).

Runs as a thread-hosted RPC server inside the head process (or standalone via
``python -m ray_tpu.core.control_plane``). State lives in a pluggable store —
in-memory by default, file-backed for restart fault tolerance (the analog of
the reference's Redis-backed GCS FT, redis_store_client.cc).
"""

from __future__ import annotations

import enum
import logging
import threading
import time
import uuid
from dataclasses import dataclass, field

from ray_tpu.core.config import get_config
from ray_tpu.core.ids import ActorID, JobID, NodeID, PlacementGroupID, WorkerID
from ray_tpu.core.rpc import ClientPool, RpcServer
from ray_tpu.core.scheduler import NodeView, add, pick_node, place_bundles, place_slice_bundles, subtract
from ray_tpu.core.task_spec import TaskSpec
from ray_tpu.exceptions import PlacementGroupSchedulingError
from ray_tpu.observability import events as _events
from ray_tpu.util import metrics as _metrics

# cluster prefix-index namespace for the tiered KV cache
# (serve/llm/kv_tier.py); one key per spilled page chain digest
_KV_TIER_PREFIX = "kv_tier:"

logger = logging.getLogger(__name__)

# Built-in scheduler metrics (ISSUE 4; ref: stats/metric_defs.cc
# scheduler_* series). Module-level: several CP instances in one test
# process must not register duplicate series.
_SCHED_PENDING_GAUGE = _metrics.Gauge(
    "ray_tpu_scheduler_pending_actors",
    "actors waiting for placement (incl. mid-pass snapshot)")
_SCHED_PLACING_GAUGE = _metrics.Gauge(
    "ray_tpu_scheduler_placing_actors",
    "actor placements with an in-flight lease RPC")
_LEASE_LATENCY_HIST = _metrics.Histogram(
    "ray_tpu_scheduler_lease_latency_seconds",
    "actor lease dispatch -> grant/reject round-trip",
    boundaries=[0.001, 0.01, 0.1, 1, 10],
    tag_keys=("granted",))


class ActorState(enum.Enum):
    PENDING = "PENDING_CREATION"
    ALIVE = "ALIVE"
    RESTARTING = "RESTARTING"
    DEAD = "DEAD"


@dataclass
class ActorInfo:
    actor_id: ActorID
    spec: TaskSpec
    name: str = ""
    detached: bool = False
    state: ActorState = ActorState.PENDING
    addr: tuple[str, int] | None = None
    node_id: NodeID | None = None
    worker_id: WorkerID | None = None
    num_restarts: int = 0
    max_restarts: int = 0
    death_cause: str = ""
    pg_id: PlacementGroupID | None = None


class PGState(enum.Enum):
    PENDING = "PENDING"
    CREATED = "CREATED"
    REMOVED = "REMOVED"


@dataclass
class PGInfo:
    pg_id: PlacementGroupID
    bundles: list[dict]
    strategy: str
    state: PGState = PGState.PENDING
    name: str = ""
    node_ids: list[NodeID] = field(default_factory=list)
    creator_job: JobID | None = None


@dataclass
class _Node:
    view: NodeView
    missed_health_checks: int = 0
    metrics: dict | None = None  # last heartbeat's system gauges
    res_version: int = 0  # last applied resource-view version (RaySyncer)
    # ALIVE → DRAINING → DRAINED | DEAD (ref: node_manager.proto:448
    # DrainRaylet + autoscaler DrainNode). DRAINING keeps view.alive True
    # (the node still heartbeats and finishes in-flight work) but the
    # schedulers exclude it; DRAINED/DEAD both imply view.alive False and
    # differ only in why.
    state: str = "ALIVE"
    draining_since: float | None = None


class ControlPlane:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 store_path: str | None = None):
        from ray_tpu.core.meta_store import make_meta_store

        self._lock = threading.RLock()
        self._nodes: dict[NodeID, _Node] = {}
        self._actors: dict[ActorID, ActorInfo] = {}
        self._named_actors: dict[str, ActorID] = {}
        self._pgs: dict[PlacementGroupID, PGInfo] = {}
        self._kv: dict[str, bytes] = {}
        self._jobs: dict[JobID, dict] = {}
        self._subs: dict[str, set[tuple[str, int]]] = {}
        self._sub_strikes: dict[tuple, int] = {}  # (channel, addr) -> fails
        self._chan_seq: dict[str, int] = {}       # pubsub sequence numbers
        self._chan_log: dict[str, list] = {}      # bounded history for poll
        # pubsub epoch: fresh per CP instance, rides every subscribe reply
        # and poll result. Subscribers that observe it change know the CP
        # restarted (all subscriptions + seq state gone) and re-subscribe +
        # reconcile missed death events (the NotifyGCSRestart analog for
        # the pubsub plane).
        self._epoch = uuid.uuid4().hex
        # in-flight graceful drains: node_id -> finisher thread
        self._drain_threads: dict[NodeID, threading.Thread] = {}
        # DEDICATED pubsub lock (never the CP's global lock: parked/cycling
        # long-poll threads would starve every other CP operation).
        # Subscribe registration, target snapshot and seq assignment are all
        # atomic under it, so a message can never land in the subscribe/
        # publish window where it is neither pushed (subscriber not yet in
        # targets) nor polled (seeded seq past it).
        self._pub_cv = threading.Condition()
        self._pool = ClientPool("cp")
        self._pending_actors: list[ActorID] = []
        self._pending_pgs: list[PlacementGroupID] = []
        # placements with an in-flight async lease RPC: aid -> (node_id,
        # dispatch ts). Also feeds the autoscaler demand poll — without it,
        # a poll during a placement pass reads zero demand and scales down
        self._placing_actors: dict[ActorID, tuple] = {}
        self._scheduling_pass: list[ActorID] = []  # mid-pass demand snapshot
        self._placing_pgs: list[PlacementGroupID] = []
        # lease fan-out bound: how many actor placements may be in flight
        # at once (ref: worker_pool.h maximum_startup_concurrency spirit)
        self._max_inflight_leases = 100
        self._wake = threading.Condition()
        self._stopped = threading.Event()
        self._task_events: list[dict] = []  # GcsTaskManager-style sink (bounded)
        self._task_event_counts: dict[str, int] = {}  # running totals
        # trace store (observability/tracing.py sink): spans grouped per
        # trace, whole oldest traces evicted past trace_store_max_spans
        self._trace_index: dict[str, list[dict]] = {}  # trace_id -> spans
        self._trace_meta: dict[str, dict] = {}         # trace_id -> summary
        self._trace_order: list[str] = []              # insertion order
        self._trace_span_count = 0
        # SLO exemplar store (observability/attribution.py): full
        # critical-path timelines of SLO-violating requests plus a sampled
        # baseline, append order = age; oldest evicted past
        # slo_exemplar_max_records and on owner death (worker/node GC)
        self._slo_exemplars: list[dict] = []
        # flight-recorder journal (observability/events.py sink): one
        # bounded list in arrival order with severity-tiered retention —
        # past events_max_records, older INFOs downsample first, then the
        # oldest non-ERROR evicts, so sparse ERRORs outlive chatty INFOs
        self._events: list[dict] = []
        # time-series store (util/metrics.py flusher sink; Monarch-shaped:
        # per-series bounded ring, delta reports accumulated CP-side into
        # cumulative points so queries never re-derive counter state)
        # (name, tag-values tuple, source) -> {"points": [(ts, value)]}
        self._metric_series: dict[tuple, dict] = {}
        self._metrics_meta: dict[str, dict] = {}   # name -> kind/desc/...
        self._metric_sources: dict[str, set] = {}  # source -> series keys
        self._source_nodes: dict[str, str] = {}    # source -> node_id hex
        self._dead_workers: set[str] = set()       # retracted worker ids
        # kv_tier: namespace hit accounting (serve/llm/kv_tier.py cluster
        # index — surfaced by _h_kv_tier_index for the CLI/dashboard)
        self._kv_tier_counters = {"match_calls": 0, "hits": 0,
                                  "misses": 0, "hit_pages": 0}
        self._store = make_meta_store(
            store_path if store_path is not None
            else (get_config().cp_store_path or None))
        self._restore()
        # the CP hosts the journal: its own emitters (node state machine,
        # restart marker below) deposit directly, no RPC hop. Install
        # before the restart marker so head-mode co-residents share it.
        _events.set_local_sink(self._event_sink)
        self._emit_cp_event(
            "cp_restart", "WARNING", reason="control plane started",
            attrs={"epoch": self._epoch})
        self._server = RpcServer(
            self._handle, host=host, port=port, name="controlplane",
            blocking_methods={"resolve_actor", "pg_ready", "get_actor_by_name", "pubsub_poll",
                              "profiling_start", "profiling_stop",
                              "save_device_memory_profile", "drain_node"},
            pool_size=16)
        self.addr = self._server.addr
        self._sched_thread = threading.Thread(
            target=self._scheduling_loop, name="cp-sched", daemon=True)
        self._sched_thread.start()
        self._health_thread = threading.Thread(
            target=self._health_loop, name="cp-health", daemon=True)
        self._health_thread.start()
        # the CP process's own registry (rpc server histograms, scheduler
        # gauges) flushes straight into the local store — no RPC hop
        self._metrics_flusher = None
        if get_config().metrics_enabled:
            self._metrics_flusher = _metrics.start_flusher(
                self._h_metrics_report, source="cp")

    # ------------------------------------------------------------------
    def _restore(self):
        """Replay persisted state after a restart (ref: gcs_init_data.cc).
        Nodes are NOT persisted — live agents re-register via the heartbeat
        (the NotifyGCSRestart analog, node_manager.proto:406)."""
        for key, val in self._store.load_all("kv"):
            self._kv[key.decode()] = val
        for key, val in self._store.load_all("job"):
            self._jobs[JobID(key)] = val
        restored_actors = 0
        for key, info in self._store.load_all("actor"):
            self._actors[info.actor_id] = info
            if info.name and info.state != ActorState.DEAD:
                self._named_actors[info.name] = info.actor_id
            if info.state in (ActorState.PENDING, ActorState.RESTARTING):
                self._pending_actors.append(info.actor_id)
            restored_actors += 1
        for key, pg in self._store.load_all("pg"):
            self._pgs[pg.pg_id] = pg
            if pg.state == PGState.PENDING:
                self._pending_pgs.append(pg.pg_id)
        if restored_actors or self._kv or self._pgs:
            logger.info(
                "control plane restored: %d actors, %d kv keys, %d pgs, "
                "%d jobs", restored_actors, len(self._kv), len(self._pgs),
                len(self._jobs))

    def _persist_actor(self, info: ActorInfo) -> None:
        self._store.save("actor", info.actor_id.binary(), info)

    def _persist_pg(self, pg: PGInfo) -> None:
        self._store.save("pg", pg.pg_id.binary(), pg)

    def _handle(self, method: str, body, peer):
        fn = getattr(self, "_h_" + method, None)
        if fn is None:
            raise ValueError(f"control plane: unknown method {method}")
        return fn(body)

    def _wake_scheduler(self):
        with self._wake:
            self._wake.notify_all()

    # ---- nodes --------------------------------------------------------
    def _h_register_node(self, body):
        view = NodeView(
            node_id=body["node_id"], addr=tuple(body["addr"]),
            total=dict(body["resources"]), available=dict(body["resources"]),
            labels=dict(body.get("labels") or {}))
        with self._lock:
            self._nodes[view.node_id] = _Node(view=view)
        logger.info("node %s registered at %s resources=%s labels=%s",
                    view.node_id.hex()[:8], view.addr, view.total, view.labels)
        self._publish("node", {"event": "alive", "node_id": view.node_id})
        self._wake_scheduler()
        return {"ok": True}

    def _h_report_resources(self, body):
        """Versioned resource-view sync (ref: ray_syncer.h:87): stale or
        reordered snapshots (version <= last applied) are discarded."""
        with self._lock:
            node = self._nodes.get(body["node_id"])
            if node is not None and self._fresher(node, body):
                node.view.available = dict(body["available"])
        self._wake_scheduler()

    @staticmethod
    def _fresher(node, body) -> bool:
        v = body.get("version")
        if v is None:
            return True  # unversioned caller (tests/legacy): accept
        if v <= node.res_version and node.res_version - v < 1 << 30:
            return False
        node.res_version = v
        return True

    def _h_heartbeat(self, body):
        """Agent heartbeat. Returns known=False after a CP restart so the
        agent re-registers (the NotifyGCSRestart→reconnect analog,
        node_manager.proto:406)."""
        with self._lock:
            node = self._nodes.get(body["node_id"])
            if node is None:
                return {"known": False}
            if not node.view.alive:
                # a DRAINED node must NOT be told to re-register — that
                # would resurrect it as ALIVE while the provider is about
                # to reclaim the VM (the deferred-terminate window). Any
                # other dead node re-registers (CP-restart analog).
                if node.state == "DRAINED":
                    return {"known": True, "state": "DRAINED"}
                return {"known": False}
            if self._fresher(node, body):
                node.view.available = dict(body["available"])
            node.missed_health_checks = 0
            if body.get("metrics"):
                node.metrics = body["metrics"]
            state = node.state
        self._wake_scheduler()
        # the reply carries the node's CP-side state so a DRAINING node
        # whose drain notify was lost still learns to stop taking leases
        return {"known": True, "state": state}

    def _h_get_node_metrics(self, body):
        """Raw per-node heartbeat gauges for the dashboard's drill-down and
        timeseries sampler (the Prometheus endpoint renders these same
        gauges as text; this is the JSON view)."""
        with self._lock:
            return [{"node_id": n.view.node_id, "alive": n.view.alive,
                     "state": n.state,
                     "resources": dict(n.view.total),
                     "available": dict(n.view.available),
                     "metrics": dict(getattr(n, "metrics", None) or {})}
                    for n in self._nodes.values()]

    def _h_get_nodes(self, body):
        with self._lock:
            return [
                {"node_id": n.view.node_id, "addr": n.view.addr, "alive": n.view.alive,
                 "state": n.state, "draining_since": n.draining_since,
                 "resources": dict(n.view.total), "available": dict(n.view.available),
                 "labels": dict(n.view.labels)}
                for n in self._nodes.values()]

    def _h_get_pending_demand(self, body):
        """Unplaceable work for the autoscaler (ref: autoscaler.proto:376
        AutoscalerStateService resource demand): resource shapes of pending
        actors and pending placement-group bundles."""
        with self._lock:
            actor_ids = dict.fromkeys(
                list(self._pending_actors) + list(self._placing_actors)
                + list(self._scheduling_pass))
            actor_shapes = [dict(self._actors[a].spec.resources)
                            for a in actor_ids if a in self._actors]
            bundle_shapes = []
            for pg_id in dict.fromkeys(
                    list(self._pending_pgs) + list(self._placing_pgs)):
                pg = self._pgs.get(pg_id)
                if pg is not None:
                    bundle_shapes.extend(dict(b) for b in pg.bundles)
        return {"actor_shapes": actor_shapes, "bundle_shapes": bundle_shapes}

    def _h_drain_node(self, body):
        """Graceful drain (ref: node_manager.proto:448 DrainRaylet, the
        autoscaler's DrainNode): flip ALIVE→DRAINING immediately (the
        schedulers stop placing there, the agent stops granting leases),
        then a background finisher lets in-flight leases run to completion
        under drain_deadline_s, migrates primary objects owned only by the
        draining node to a survivor, and finalizes DRAINING→DRAINED.
        Idempotent; body: {node_id, wait?, reason?}. Registered in
        blocking_methods so wait=True never parks the shared handler pool."""
        node_id = body["node_id"]
        reason = body.get("reason") or "drain requested"
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None:
                return {"ok": False, "error": "unknown node"}
            if not node.view.alive:
                return {"ok": True, "state": node.state}
            started = node.state == "ALIVE"
            if started:
                node.state = "DRAINING"
                node.draining_since = time.time()
                finisher = threading.Thread(
                    target=self._finish_drain, args=(node_id,),
                    name="cp-drain", daemon=True)
                self._drain_threads[node_id] = finisher
            else:
                finisher = self._drain_threads.get(node_id)
            addr = node.view.addr
        if started:
            logger.info("draining node %s: %s", node_id.hex()[:8], reason)
            # tell the agent directly (fast path; the heartbeat reply's
            # `state` field covers a lost notify) and the subscribers (the
            # serve controller pre-starts replacement replicas on this)
            try:
                # graftlint: fire-and-forget (heartbeat reply carries state)
                self._pool.get(addr).notify("drain", {"reason": reason})
            except Exception:  # noqa: BLE001 - heartbeat will deliver it
                pass
            self._publish("node", {"event": "draining", "node_id": node_id})
            self._emit_cp_event("node_drain", "WARNING",
                                node=node_id.hex(), reason=reason)
            finisher.start()
        if body.get("wait") and finisher is not None:
            finisher.join(timeout=get_config().drain_deadline_s + 30.0)
        with self._lock:
            node = self._nodes.get(node_id)
            state = node.state if node is not None else "DEAD"
        return {"ok": True, "state": state}

    def _finish_drain(self, node_id: NodeID):
        """Drain finisher: poll the agent until its in-flight leases hit
        zero (or drain_deadline_s elapses — work past the deadline is lost
        exactly as a kill would lose it), re-home its primary objects, then
        mark the node DRAINED through the normal dead-node path (actor
        failover, metric/kv-tier retraction, death publish)."""
        cfg = get_config()
        deadline = time.monotonic() + cfg.drain_deadline_s
        with self._lock:
            node = self._nodes.get(node_id)
            addr = node.view.addr if node is not None else None
        if addr is not None:
            agent = self._pool.get(addr)
            while not self._stopped.is_set():
                with self._lock:
                    node = self._nodes.get(node_id)
                    if node is None or not node.view.alive \
                            or node.state != "DRAINING":
                        self._drain_threads.pop(node_id, None)
                        return  # died / re-registered mid-drain
                if time.monotonic() >= deadline:
                    logger.warning(
                        "drain deadline (%.0fs) reached for node %s with "
                        "work in flight", cfg.drain_deadline_s,
                        node_id.hex()[:8])
                    break
                try:
                    st = agent.call("drain_status", None, timeout=5.0)
                except Exception:  # noqa: BLE001 - agent gone: finalize
                    break
                if st and st.get("inflight_leases", 0) == 0 \
                        and st.get("busy_workers", 0) == 0:
                    break
                time.sleep(0.25)
            # re-home primary objects whose only copy lives on the
            # draining node: the agent pushes them to a surviving peer so
            # gets after the drain need no lineage reconstruction
            with self._lock:
                target = next(
                    ((n.view.addr, n.view.node_id)
                     for n in self._nodes.values()
                     if n.view.alive and n.state == "ALIVE"
                     and n.view.node_id != node_id), None)
            if target is not None:
                try:
                    agent.call("drain_objects",
                               {"target_addr": target[0],
                                "target_node_id": target[1]},
                               timeout=max(10.0, cfg.drain_deadline_s))
                except Exception:  # noqa: BLE001 - degrade to lineage
                    pass
        self._on_node_dead(node_id, "drained")
        with self._lock:
            self._drain_threads.pop(node_id, None)

    # ---- jobs ---------------------------------------------------------
    def _h_register_job(self, body):
        with self._lock:
            self._jobs[body["job_id"]] = {"driver_addr": tuple(body["addr"]),
                                          "start_time": time.time(), "alive": True}
            self._store.save("job", body["job_id"].binary(),
                             self._jobs[body["job_id"]])
        return {"ok": True}

    def _h_finish_job(self, body):
        with self._lock:
            if body["job_id"] in self._jobs:
                self._jobs[body["job_id"]]["alive"] = False
                self._store.save("job", body["job_id"].binary(),
                                 self._jobs[body["job_id"]])
        # non-detached actors of the job die with it (ref: GcsActorManager
        # OnJobFinished)
        doomed = []
        with self._lock:
            for info in self._actors.values():
                if (not info.detached and info.spec.job_id == body["job_id"]
                        and info.state not in (ActorState.DEAD,)):
                    doomed.append(info.actor_id)
        for aid in doomed:
            self._kill_actor(aid, no_restart=True, reason="job finished")
        return {"ok": True}

    def _h_list_jobs(self, body):
        with self._lock:
            return [{"job_id": j, **info} for j, info in self._jobs.items()]

    # ---- kv (function table, serve config, ...) -----------------------
    def _h_kv_put(self, body):
        with self._lock:
            exists = body["key"] in self._kv
            if body.get("overwrite", True) or not exists:
                self._kv[body["key"]] = body["value"]
                self._store.save("kv", body["key"].encode(), body["value"])
                return True
            return False

    def _h_kv_mput(self, body):
        """Batched kv_put: one RPC registers many keys. The kv-tier
        publisher uses it to index a whole spilled chain (one entry per
        page) per round trip — per-page kv_put serializes a long-prompt
        disagg handoff behind O(pages) RPCs on the publisher thread."""
        with self._lock:
            for key, value in body["items"]:
                self._kv[key] = value
                self._store.save("kv", key.encode(), value)
        return True

    def _h_kv_get(self, body):
        with self._lock:
            return self._kv.get(body["key"])

    def _h_kv_del(self, body):
        with self._lock:
            self._store.delete("kv", body["key"].encode())
            return self._kv.pop(body["key"], None) is not None

    def _h_kv_exists(self, body):
        with self._lock:
            return body["key"] in self._kv

    def _h_kv_keys(self, body):
        prefix = body.get("prefix", "")
        with self._lock:
            return [k for k in self._kv if k.startswith(prefix)]

    # ---- kv tier (serve/llm/kv_tier.py cluster prefix index) ----------
    # One kv_tier:<ns>:<chain-digest-hex> entry per spilled KV page (the
    # ns segment is the owner's model-identity hash — replicas serving
    # different models never see each other's pages); values are JSON
    # dicts carrying {owner, node, store, ref, blob, off, tokens, nbytes,
    # tier, ts, ttl_s, ns}. Entries die with their owning worker/node
    # (same GC shape as the metrics store), by owner retraction
    # (_h_kv_tier_del — compare-and-delete on (store, blob) so a
    # re-spilled digest's newer entry survives its old blob's drop), or
    # by TTL (_h_kv_tier_gc).

    @staticmethod
    def _kv_tier_entry(value):
        import json
        try:
            return json.loads(value.decode() if isinstance(value, bytes)
                              else value)
        except (ValueError, AttributeError):
            return None

    def _h_kv_tier_match(self, body):
        """Longest-prefix probe: returns the stored values for the
        leading contiguous run of ``digests`` present in the index (one
        round trip for the whole chain probe instead of one kv_get per
        page)."""
        digests = body.get("digests") or []
        ns = body.get("ns") or ""
        pre = _KV_TIER_PREFIX + (ns + ":" if ns else "")
        with self._lock:
            vals = [self._kv.get(pre + d) for d in digests]
            run = 0
            for v in vals:
                if v is None:
                    break
                run += 1
            c = self._kv_tier_counters
            c["match_calls"] += 1
            if run:
                c["hits"] += 1
                c["hit_pages"] += run
            else:
                c["misses"] += 1
            return {"entries": vals[:run]}

    def _h_kv_tier_del(self, body):
        """Retract one index entry, conditionally: when the caller sends
        (store, blob), the key is only dropped if the stored entry still
        carries them — a digest re-spilled into a newer blob keeps its
        fresh registration when the OLD blob's retraction arrives late.
        Unparseable entries always drop."""
        key = body["key"]
        with self._lock:
            cur = self._kv_tier_entry(self._kv.get(key)) \
                if key in self._kv else None
            if key in self._kv and cur is not None \
                    and body.get("blob") is not None:
                if (cur.get("store") != body.get("store")
                        or cur.get("blob") != body.get("blob")):
                    return {"deleted": False}
            if self._kv.pop(key, None) is not None:
                self._store.delete("kv", key.encode())
                return {"deleted": True}
            return {"deleted": False}

    def _h_kv_tier_index(self, body):
        """Whole-index dump for `ray-tpu kvtier` / the dashboard table:
        parsed entries (ref stripped — it's a pickled object ref) plus
        the CP-side hit counters."""
        with self._lock:
            raw = {k: v for k, v in self._kv.items()
                   if k.startswith(_KV_TIER_PREFIX)}
            counters = dict(self._kv_tier_counters)
        entries = []
        for k, v in raw.items():
            e = self._kv_tier_entry(v)
            if e is None:
                continue
            e.pop("ref", None)
            # key is kv_tier:[<ns>:]<digest>; un-namespaced keys predate
            # the model-identity scoping (and appear in tests)
            ns, _, dig = k[len(_KV_TIER_PREFIX):].rpartition(":")
            e["digest"] = dig
            if ns:
                e.setdefault("ns", ns)
            entries.append(e)
        entries.sort(key=lambda e: (e.get("owner", ""), e.get("blob", ""),
                                    e.get("off", 0)))
        return {"entries": entries, "counters": counters}

    def _h_kv_tier_gc(self, body):
        """Drop expired (and unparseable) index entries — the owner
        normally retracts its own, but a wedged owner's entries must not
        advertise restorable prefixes forever."""
        now = time.time()
        dropped = 0
        with self._lock:
            for k in [k for k in self._kv
                      if k.startswith(_KV_TIER_PREFIX)]:
                e = self._kv_tier_entry(self._kv[k])
                ttl = (e or {}).get("ttl_s") or 0
                if e is None or (ttl > 0
                                 and now - e.get("ts", now) > ttl):
                    self._kv.pop(k, None)
                    self._store.delete("kv", k.encode())
                    dropped += 1
        return {"dropped": dropped}

    def _retract_kv_tier_locked(self, whex: str | None = None,
                                nhex: str | None = None) -> None:
        """Drop every kv_tier: entry owned by a dead worker or node —
        their object refs are unservable, and a cold replica probing the
        index must miss, not hang on a fetch. Caller holds self._lock
        (same discipline as _retract_metrics_source)."""
        for k in [k for k in self._kv if k.startswith(_KV_TIER_PREFIX)]:
            e = self._kv_tier_entry(self._kv[k])
            if e is None:
                continue
            if (whex is not None and e.get("owner") == whex) or \
                    (nhex is not None and e.get("node") == nhex):
                self._kv.pop(k, None)
                self._store.delete("kv", k.encode())

    # ---- pubsub -------------------------------------------------------
    def _h_subscribe(self, body):
        with self._pub_cv:
            self._subs.setdefault(body["channel"], set()).add(tuple(body["addr"]))
            seq = self._chan_seq.get(body["channel"], 0)
        return {"ok": True, "seq": seq, "epoch": self._epoch}

    def _gc_channels_locked(self):
        """Bound channel bookkeeping: per-actor channels would otherwise
        accumulate for the cluster's lifetime. Oldest subscriber-less
        channels go first (lock held)."""
        if len(self._chan_log) <= 1024:
            return
        for ch in list(self._chan_log):
            if len(self._chan_log) <= 1024:
                break
            if not self._subs.get(ch):
                self._chan_log.pop(ch, None)
                self._chan_seq.pop(ch, None)

    def _h_pubsub_poll(self, body):
        """Long-poll recovery (ref: pubsub.proto:224 SubscriberService /
        long_poll semantics): the caller sends {channel: last_seen_seq} and
        blocks until any channel has newer messages (or timeout). Push
        delivery stays the fast path; this loop guarantees at-least-once —
        a dropped push is recovered on the next poll with seq-based dedup
        at the subscriber."""
        channels: dict = body.get("channels", {})
        deadline = time.monotonic() + min(float(body.get("timeout", 30.0)), 60.0)
        # every reply (fresh messages, timeout, shutdown) carries the CP's
        # pubsub epoch so pollers detect a restart even on quiet channels
        while not self._stopped.is_set():
            out = {"__epoch": self._epoch}
            with self._pub_cv:
                for ch, last in channels.items():
                    log = self._chan_log.get(ch)
                    if not log:
                        continue
                    fresh = [(seq, msg) for seq, msg in log if seq > last]
                    if fresh:
                        out[ch] = fresh
                if len(out) > 1:
                    return out
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return out
                self._pub_cv.wait(min(remaining, 1.0))
        return {"__epoch": self._epoch}

    def _h_unsubscribe(self, body):
        with self._pub_cv:
            self._subs.get(body["channel"], set()).discard(tuple(body["addr"]))
        return {"ok": True}

    def _h_publish(self, body):
        self._publish(body["channel"], body["msg"])
        return {"ok": True}

    def _publish(self, channel: str, msg):
        with self._pub_cv:
            targets = list(self._subs.get(channel, ()))
            seq = self._chan_seq.get(channel, 0) + 1
            self._chan_seq[channel] = seq
            log = self._chan_log.setdefault(channel, [])
            log.append((seq, msg))
            del log[:-200]  # bounded per-channel history for poll recovery
            self._gc_channels_locked()
            self._pub_cv.notify_all()
        msg = {"__seq": seq, "payload": msg}
        for addr in targets:
            try:
                # push fan-out is at-most-once by design: the long-poll
                # side channel (_h_pubsub_poll + seq dedup) upgrades the
                # stream to at-least-once, and strike GC drops dead subs
                # graftlint: fire-and-forget
                self._pool.get(addr).notify("pubsub", {"channel": channel, "msg": msg})
                # lock-free pre-check keeps the hot success path uncontended:
                # the key only exists after a prior delivery failure
                if (channel, addr) in self._sub_strikes:
                    with self._pub_cv:
                        self._sub_strikes.pop((channel, addr), None)
            except Exception:
                # subscribers that exited without unsubscribing must not
                # accumulate connect churn forever: drop after 3 consecutive
                # failed deliveries (a live one re-establishes on success).
                # Strike bookkeeping is under _pub_cv: concurrent publisher
                # threads doing unlocked read-modify-write could drop a live
                # subscriber before 3 true consecutive failures.
                self._pool.invalidate(addr)
                with self._pub_cv:
                    strikes = self._sub_strikes.get((channel, addr), 0) + 1
                    self._sub_strikes[(channel, addr)] = strikes
                    if strikes >= 3:
                        self._subs.get(channel, set()).discard(addr)
                        self._sub_strikes.pop((channel, addr), None)

    # ---- task events (observability sink; ref: gcs_task_manager.cc) ----
    def _h_report_task_events(self, body):
        with self._lock:
            for ev in body["events"]:
                s = ev.get("state", "UNKNOWN")
                self._task_event_counts[s] = \
                    self._task_event_counts.get(s, 0) + 1
            self._task_events.extend(body["events"])
            overflow = len(self._task_events) - get_config().task_events_buffer_size
            if overflow > 0:
                del self._task_events[:overflow]
        return {"ok": True}

    def _h_list_task_events(self, body):
        limit = body.get("limit", 1000) if body else 1000
        with self._lock:
            return list(self._task_events[-limit:])

    # ---- trace store (observability/tracing.py sink) -------------------
    def _h_report_spans(self, body):
        import json as _json
        spans = (body or {}).get("spans") or []
        touched: set[str] = set()
        with self._lock:
            for s in spans:
                tid = s.get("trace_id")
                if not tid:
                    continue
                if tid not in self._trace_index:
                    self._trace_index[tid] = []
                    self._trace_order.append(tid)
                    self._trace_meta[tid] = {
                        "trace_id": tid, "name": s.get("name", ""),
                        "start": s.get("start"), "end": s.get("end"),
                        "num_spans": 0, "root_seen": False}
                self._trace_index[tid].append(s)
                self._trace_span_count += 1
                touched.add(tid)
                meta = self._trace_meta[tid]
                meta["num_spans"] += 1
                st, en = s.get("start"), s.get("end")
                if st is not None and (meta["start"] is None
                                       or st < meta["start"]):
                    meta["start"] = st
                if en is not None and (meta["end"] is None
                                       or en > meta["end"]):
                    meta["end"] = en
                if not s.get("parent_id"):
                    # the root span names the trace
                    meta["name"] = s.get("name", meta["name"])
                    meta["root_seen"] = True
            # whole-trace eviction, oldest first (bounded ring)
            max_spans = max(1, get_config().trace_store_max_spans)
            while (self._trace_span_count > max_spans
                   and len(self._trace_order) > 1):
                old = self._trace_order.pop(0)
                gone = self._trace_index.pop(old, [])
                self._trace_span_count -= len(gone)
                self._trace_meta.pop(old, None)
                touched.discard(old)
                self._h_kv_del({"key": f"trace:{old}"})
            # KV index: one summary key per trace, queryable via kv_keys
            # (RLock: _h_kv_put re-enters safely)
            for tid in touched:
                meta = self._trace_meta.get(tid)
                if meta is not None:
                    self._h_kv_put({
                        "key": f"trace:{tid}",
                        "value": _json.dumps(meta).encode()})
        return {"ok": True}

    def _h_get_trace(self, body):
        tid = (body or {}).get("trace_id") or ""
        with self._lock:
            full = tid if tid in self._trace_index else next(
                (t for t in self._trace_order if t.startswith(tid)), None)
            if full is None:
                return None
            spans = sorted(self._trace_index[full],
                           key=lambda s: s.get("start") or 0.0)
            return {"trace_id": full,
                    "meta": dict(self._trace_meta.get(full) or {}),
                    "spans": spans}

    def _h_list_traces(self, body):
        limit = (body or {}).get("limit", 100)
        with self._lock:
            metas = [dict(self._trace_meta[t])
                     for t in reversed(self._trace_order)
                     if t in self._trace_meta]
        return metas[:limit]

    # ---- SLO exemplar store (observability/attribution.py sink) --------
    def _h_report_slo_exemplar(self, body):
        """Persist one request's critical-path timeline. Bounded: oldest
        records (and their `slo_exemplar:` KV keys) evict first past
        slo_exemplar_max_records; reports from retracted workers are
        rejected like late metric flushes."""
        import json as _json
        rec = (body or {}).get("record")
        if not isinstance(rec, dict) or not rec.get("request_id"):
            return {"ok": False, "error": "malformed record"}
        source = rec.get("source") or ""
        with self._lock:
            if source and source in self._dead_workers:
                return {"ok": False, "error": "source retracted"}
            self._slo_exemplars.append(rec)
            cap = max(1, get_config().slo_exemplar_max_records)
            while len(self._slo_exemplars) > cap:
                old = self._slo_exemplars.pop(0)
                self._h_kv_del(
                    {"key": f"slo_exemplar:{old.get('request_id')}"})
            # KV index entry: summary queryable via kv_keys, retracted
            # with the record (RLock: _h_kv_put re-enters safely)
            self._h_kv_put({
                "key": f"slo_exemplar:{rec['request_id']}",
                "value": _json.dumps({
                    "request_id": rec.get("request_id"),
                    "kind": rec.get("kind"),
                    "violated": rec.get("violated"),
                    "deployment": rec.get("deployment"),
                    "replica": rec.get("replica"),
                    "ttft_ms": rec.get("ttft_ms"),
                    "e2e_ms": rec.get("e2e_ms"),
                    "ts": rec.get("ts")}).encode()})
        return {"ok": True}

    def _h_list_slo_exemplars(self, body):
        """Summaries, newest first; `kind` filters violation/baseline."""
        body = body or {}
        limit = body.get("limit", 50)
        kind = body.get("kind")
        with self._lock:
            recs = [r for r in reversed(self._slo_exemplars)
                    if kind is None or r.get("kind") == kind]
        return [{k: r.get(k) for k in
                 ("request_id", "ts", "app", "deployment", "replica",
                  "kind", "violated", "ttft_ms", "e2e_ms", "error")}
                for r in recs[:limit]]

    def _h_get_slo_exemplar(self, body):
        """One full exemplar record by request id (prefix ok, newest
        match wins — retries re-ship under the same id)."""
        rid = (body or {}).get("request_id") or ""
        with self._lock:
            for r in reversed(self._slo_exemplars):
                if r.get("request_id", "").startswith(rid):
                    return dict(r)
        return None

    def _h_slo_report(self, body):
        """Fleet tail-latency breakdown over the stored exemplars:
        per-stage percentiles, dominant-stage attribution for the tail,
        per-replica skew (attribution.aggregate_report)."""
        from ray_tpu.observability import attribution as _attr
        deployment = (body or {}).get("deployment")
        with self._lock:
            recs = [dict(r) for r in self._slo_exemplars
                    if deployment is None
                    or r.get("deployment") == deployment]
        return _attr.aggregate_report(recs)

    def _retract_slo_exemplars_locked(self, whex: str) -> None:
        """Drop every exemplar shipped by a dead worker (caller holds
        self._lock; same discipline as _retract_metrics_source) — its
        `slo_exemplar:` KV keys go with it, unless a surviving proxy
        re-shipped the same request id."""
        keep, gone = [], []
        for r in self._slo_exemplars:
            (gone if r.get("source") == whex else keep).append(r)
        if not gone:
            return
        self._slo_exemplars = keep
        live = {r.get("request_id") for r in keep}
        for r in gone:
            rid = r.get("request_id")
            if rid not in live:
                self._h_kv_del({"key": f"slo_exemplar:{rid}"})

    # ---- flight recorder (observability/events.py journal) -------------
    def _event_sink(self, ev: dict) -> None:
        """Local deposit path for events emitted inside the CP process
        (installed as the observability.events sink in __init__)."""
        if not isinstance(ev, dict) or ev.get("kind") not in _events.KINDS:
            return
        with self._lock:
            self._events.append(ev)
            self._trim_events_locked()

    def _emit_cp_event(self, kind: str, severity: str = "INFO",
                       **fields) -> None:
        """Journal one CP-side event (node state machine, restart
        marker). Malformed emits are dropped, never raised — the node
        lifecycle must not depend on the flight recorder."""
        try:
            if not get_config().events_enabled:
                return
            self._event_sink(_events.make_event(kind, severity, **fields))
        except Exception:  # noqa: BLE001
            pass

    def _trim_events_locked(self) -> None:
        """Severity-tiered retention (caller holds self._lock). Past
        events_max_records: (1) downsample INFOs in the older half of
        the journal (every other one drops — the metrics-store
        downsample, applied by severity), (2) evict the oldest
        non-ERROR, (3) only then let the oldest ERRORs go (hard bound)."""
        cap = max(8, int(get_config().events_max_records))
        if len(self._events) <= cap:
            return
        half = len(self._events) // 2
        kept, drop_next = [], True
        for i, ev in enumerate(self._events):
            if i < half and ev.get("severity", "INFO") == "INFO":
                drop_next = not drop_next
                if drop_next:
                    continue
            kept.append(ev)
        overflow = len(kept) - cap
        if overflow > 0:
            survivors = []
            for ev in kept:
                if overflow > 0 and ev.get("severity") != "ERROR":
                    overflow -= 1
                    continue
                survivors.append(ev)
            kept = survivors
        self._events[:] = kept
        while len(self._events) > cap:
            self._events.pop(0)

    def _h_report_events(self, body):
        """Accept one batch from a worker's EventFlusher. Events outside
        the fixed taxonomy are dropped record-by-record (the batch still
        acks — a single bad emit site must not wedge a worker's backlog
        forever); batches from retracted workers are rejected whole like
        late metric flushes."""
        body = body or {}
        evs = body.get("events")
        source = str(body.get("source") or "")
        if not isinstance(evs, list):
            return {"ok": False, "error": "malformed batch"}
        accepted = 0
        with self._lock:
            if source and source in self._dead_workers:
                return {"ok": False, "error": "source retracted"}
            for ev in evs:
                if not isinstance(ev, dict) or \
                        ev.get("kind") not in _events.KINDS:
                    continue
                ev = dict(ev)
                if source and not ev.get("source"):
                    ev["source"] = source
                self._events.append(ev)
                accepted += 1
            self._trim_events_locked()
        return {"ok": True, "accepted": accepted}

    @staticmethod
    def _event_matches(ev: dict, kind, severity, entity,
                       since, until) -> bool:
        if kind is not None and ev.get("kind") != kind:
            return False
        if severity is not None:
            rank = _events.SEVERITY_RANK
            if rank.get(ev.get("severity", "INFO"), 0) < \
                    rank.get(severity, 0):
                return False
        ts = float(ev.get("ts") or 0.0)
        if since is not None and ts < since:
            return False
        if until is not None and ts > until:
            return False
        if entity:
            hay = (ev.get("node"), ev.get("deployment"), ev.get("replica"),
                   ev.get("request_id"), ev.get("source"))
            if not any(entity in h for h in hay if h):
                return False
        return True

    def _h_list_events(self, body):
        """Journal query, newest first. Filters: kind (exact), severity
        (minimum — ERROR shows only errors, WARNING hides INFO), entity
        (substring over node/deployment/replica/request_id/source),
        since/until (unix ts), limit."""
        body = body or {}
        kind = body.get("kind")
        severity = body.get("severity")
        entity = body.get("entity")
        since = body.get("since")
        until = body.get("until")
        since = None if since is None else float(since)
        until = None if until is None else float(until)
        limit = max(1, int(body.get("limit") or 100))
        with self._lock:
            out = [dict(ev) for ev in reversed(self._events)
                   if self._event_matches(ev, kind, severity, entity,
                                          since, until)]
        return out[:limit]

    def _h_events_postmortem(self, body):
        """One ordered incident timeline for a window: every journal
        event, every SLO-violation exemplar, and a per-series spike
        summary of the metric timeseries, merged by timestamp — "what
        happened around this p99 spike" in a single response."""
        body = body or {}
        try:
            window = float(body.get("window_s") or 300.0)
        except (TypeError, ValueError):
            window = 300.0
        until = body.get("until")
        until = time.time() if until is None else float(until)
        since = until - window
        items: list[dict] = []
        metric_items: list[dict] = []
        with self._lock:
            for ev in self._events:
                ts = float(ev.get("ts") or 0.0)
                if since <= ts <= until:
                    it = dict(ev)
                    it["type"] = "event"
                    items.append(it)
            for r in self._slo_exemplars:
                if r.get("kind") != "violation":
                    continue
                ts = float(r.get("ts") or 0.0)
                if since <= ts <= until:
                    items.append({
                        "type": "exemplar", "ts": ts,
                        "request_id": r.get("request_id"),
                        "deployment": r.get("deployment"),
                        "replica": r.get("replica"),
                        "violated": r.get("violated"),
                        "ttft_ms": r.get("ttft_ms"),
                        "e2e_ms": r.get("e2e_ms")})
            for (name, tags, source), ser in self._metric_series.items():
                pts = [(t, v) for t, v in ser["points"]
                       if since <= t <= until and isinstance(v, (int, float))]
                if not pts:
                    continue
                peak_ts, peak = max(pts, key=lambda p: p[1])
                metric_items.append({
                    "type": "metric", "ts": peak_ts, "name": name,
                    "source": source, "tags": list(tags),
                    "points": len(pts), "peak": peak,
                    "first": pts[0][1], "last": pts[-1][1]})
        # one spike summary per series, loudest movers only — the
        # timeline is for reading, not for re-plotting the whole store
        metric_items.sort(
            key=lambda m: abs(m["peak"] - m["first"]), reverse=True)
        items.extend(metric_items[:40])
        items.sort(key=lambda x: float(x.get("ts") or 0.0))
        return {"since": since, "until": until, "window_s": window,
                "items": items}

    # ---- metrics time-series store (util/metrics.py flusher sink) ------
    def _h_metrics_report(self, body):
        """Accept one delta snapshot from a process flusher. Counters and
        histogram buckets arrive as deltas and are accumulated into
        cumulative points here (one accumulator per (name, tags, source));
        gauges arrive as absolute values. The caller's `ts` is honored so
        replayed/fake-clock injections land where they claim to be."""
        body = body or {}
        source = str(body.get("source") or "unknown")
        try:
            ts = float(body.get("ts"))
        except (TypeError, ValueError):
            ts = time.time()
        cfg = get_config()
        with self._lock:
            if source in self._dead_workers:
                return {"ok": False, "retracted": True}
            node_id = body.get("node_id")
            if node_id:
                self._source_nodes[source] = str(node_id)
            for md in body.get("metrics") or ():
                name = md.get("name")
                if not name:
                    continue
                kind = md.get("kind", "gauge")
                meta = self._metrics_meta.get(name)
                if meta is None:
                    meta = self._metrics_meta[name] = {
                        "name": name, "kind": kind,
                        "description": md.get("description", ""),
                        "tag_keys": list(md.get("tag_keys") or ()),
                        "boundaries": list(md.get("boundaries") or ())}
                elif not meta["description"] and md.get("description"):
                    meta["description"] = md["description"]
                for s in md.get("series") or ():
                    tags = tuple(s.get("tags") or ())
                    key = (name, tags, source)
                    ser = self._metric_series.get(key)
                    if ser is None:
                        ser = self._metric_series[key] = {"points": []}
                        self._metric_sources.setdefault(
                            source, set()).add(key)
                    pts = ser["points"]
                    prev = pts[-1][1] if pts else None
                    if kind == "counter":
                        val = (prev or 0.0) + float(
                            s.get("delta", s.get("value", 0.0)))
                    elif kind == "histogram":
                        buckets = list(s.get("buckets") or ())
                        dsum = float(s.get("sum", 0.0))
                        dcount = int(s.get("count", 0))
                        if isinstance(prev, dict) and \
                                len(prev.get("buckets") or ()) == len(buckets):
                            buckets = [a + b for a, b in
                                       zip(prev["buckets"], buckets)]
                            dsum += prev["sum"]
                            dcount += prev["count"]
                        val = {"buckets": buckets, "sum": dsum,
                               "count": dcount}
                    else:
                        val = float(s.get("value", 0.0))
                    pts.append((ts, val))
                    # retention window, oldest-first (relative to the
                    # series' own clock so fake-clock series age coherently)
                    cutoff = ts - cfg.metrics_retention_s
                    while pts and pts[0][0] < cutoff:
                        pts.pop(0)
                    # point cap: downsample (thin every other point of the
                    # older half) instead of hard truncation, preserving
                    # both history shape and the fresh tail
                    cap = max(4, cfg.metrics_max_points_per_series)
                    if len(pts) > cap:
                        half = len(pts) // 2
                        ser["points"] = pts[:half][::2] + pts[half:]
        return {"ok": True}

    @staticmethod
    def _tags_match(tag_keys: list, tag_values: tuple,
                    want: dict | None) -> bool:
        if not want:
            return True
        got = dict(zip(tag_keys, tag_values))
        return all(got.get(k) == v for k, v in want.items())

    def _h_metrics_query(self, body):
        """Points of one metric: tag-subset filter + [since, until] time
        range. Histogram points come back as {buckets, sum, count} dicts;
        `merged` carries the cross-source cumulative merge of each series'
        latest in-range point (the percentile views build on it)."""
        body = body or {}
        name = body.get("name") or ""
        want = body.get("tags") or None
        since = body.get("since")
        until = body.get("until")
        with self._lock:
            meta = self._metrics_meta.get(name)
            if meta is None:
                return None
            out = {"name": name, "kind": meta["kind"],
                   "description": meta["description"],
                   "tag_keys": list(meta["tag_keys"]),
                   "boundaries": list(meta["boundaries"]), "series": []}
            for (n, tags, source), ser in self._metric_series.items():
                if n != name or not self._tags_match(
                        meta["tag_keys"], tags, want):
                    continue
                pts = [[ts, val] for ts, val in ser["points"]
                       if (since is None or ts >= since)
                       and (until is None or ts <= until)]
                if pts:
                    out["series"].append(
                        {"tags": list(tags), "source": source,
                         "points": pts})
        if meta["kind"] == "histogram":
            latest = [{"boundaries": out["boundaries"],
                       **s["points"][-1][1]} for s in out["series"]]
            out["merged"] = _metrics.merge_histograms(latest)
        return out

    def _h_metrics_list_series(self, body):
        """Catalogue of stored series (name, kind, tags, source, point
        count, last timestamp), optionally filtered by name prefix."""
        prefix = (body or {}).get("prefix", "")
        with self._lock:
            out = []
            for (name, tags, source), ser in self._metric_series.items():
                if not name.startswith(prefix) or not ser["points"]:
                    continue
                meta = self._metrics_meta.get(name) or {}
                out.append({
                    "name": name, "kind": meta.get("kind", "gauge"),
                    "tags": dict(zip(meta.get("tag_keys") or (), tags)),
                    "source": source, "points": len(ser["points"]),
                    "last_ts": ser["points"][-1][0]})
        out.sort(key=lambda r: (r["name"], r["source"]))
        return out

    def _retract_metrics_source(self, source: str) -> None:
        """Drop every stored series owned by one flusher source (worker or
        node agent death). Caller holds self._lock."""
        for key in self._metric_sources.pop(source, ()):  # noqa: B020
            self._metric_series.pop(key, None)
        self._source_nodes.pop(source, None)

    def _metrics_dump_locked(self, exclude_sources: set) -> list[dict]:
        """Latest point of every stored series, grouped per metric in the
        shared metric-dict shape (render_exposition input)."""
        by_name: dict[str, list] = {}
        for (name, tags, source), ser in self._metric_series.items():
            if source in exclude_sources or not ser["points"]:
                continue
            latest = ser["points"][-1][1]
            if isinstance(latest, dict):
                by_name.setdefault(name, []).append(
                    {"tags": list(tags), **latest})
            else:
                by_name.setdefault(name, []).append(
                    {"tags": list(tags), "value": latest})
        return [{**self._metrics_meta[name], "series": series}
                for name, series in by_name.items()
                if name in self._metrics_meta]

    def _cp_state_dicts_locked(self) -> list[dict]:
        """CP-derived system gauges in metric-dict shape (node membership,
        actor states, per-node heartbeat gauges — the old ad-hoc /metrics
        emitter, now through the shared renderer)."""
        nodes = list(self._nodes.values())
        actors_by_state: dict[str, int] = {}
        for a in self._actors.values():
            s = getattr(a.state, "name", str(a.state))
            actors_by_state[s] = actors_by_state.get(s, 0) + 1
        dicts = [
            {"name": "ray_tpu_nodes_alive", "kind": "gauge",
             "description": "alive nodes", "tag_keys": [],
             "series": [{"tags": [], "value": sum(
                 1 for n in nodes if n.view.alive)}]},
            {"name": "ray_tpu_nodes_total", "kind": "gauge",
             "description": "registered nodes", "tag_keys": [],
             "series": [{"tags": [], "value": len(nodes)}]},
            {"name": "ray_tpu_nodes_draining", "kind": "gauge",
             "description": "nodes mid graceful drain", "tag_keys": [],
             "series": [{"tags": [], "value": sum(
                 1 for n in nodes if n.state == "DRAINING")}]},
            {"name": "ray_tpu_actors", "kind": "gauge",
             "description": "actors by state", "tag_keys": ["state"],
             "series": [{"tags": [s], "value": c} for s, c in
                        sorted(actors_by_state.items())]},
            {"name": "ray_tpu_placement_groups", "kind": "gauge",
             "description": "placement groups", "tag_keys": [],
             "series": [{"tags": [], "value": len(self._pgs)}]},
            {"name": "ray_tpu_jobs", "kind": "gauge",
             "description": "jobs", "tag_keys": [],
             "series": [{"tags": [], "value": len(self._jobs)}]},
            {"name": "ray_tpu_task_events_total", "kind": "counter",
             "description": "task events by state", "tag_keys": ["state"],
             "series": [{"tags": [s], "value": c} for s, c in
                        sorted(self._task_event_counts.items())]},
        ]
        plain: dict[str, list] = {}
        resource: dict[str, list] = {}
        for n in nodes:
            if not n.view.alive:
                continue
            nid = n.view.node_id.hex()[:12]
            for k, v in (getattr(n, "metrics", None) or {}).items():
                if ":" in k:
                    base, res = k.split(":", 1)
                    resource.setdefault(base, []).append(
                        {"tags": [nid, res], "value": v})
                else:
                    plain.setdefault(k, []).append(
                        {"tags": [nid], "value": v})
        for k, series in sorted(plain.items()):
            dicts.append({"name": f"ray_tpu_node_{k}", "kind": "gauge",
                          "description": "node agent heartbeat gauge",
                          "tag_keys": ["node"], "series": series})
        for k, series in sorted(resource.items()):
            dicts.append({"name": f"ray_tpu_node_{k}", "kind": "gauge",
                          "description": "node agent heartbeat gauge",
                          "tag_keys": ["node", "resource"],
                          "series": series})
        return dicts

    def _h_metrics_dump(self, body):
        """Aggregatable snapshot for scrapers: CP system gauges + latest
        stored series (minus `exclude_sources` — a scraper co-resident with
        a flusher substitutes its own fresher local registry). Every
        producer reports through the flusher pipeline now — the legacy
        `metrics:<worker>` KV exposition blobs are gone."""
        exclude = set((body or {}).get("exclude_sources") or ())
        with self._lock:
            dicts = (self._cp_state_dicts_locked()
                     + self._metrics_dump_locked(exclude))
        return {"metrics": dicts}

    def _h_get_metrics(self, body):
        """Prometheus exposition of cluster metrics: CP-derived gauges +
        the aggregated time-series store (counters summed and histogram
        buckets merged across workers — duplicate series never emitted;
        ref: stats/metric_defs.cc + dashboard/modules/metrics/)."""
        dump = self._h_metrics_dump(body)
        return _metrics.render_exposition(dump["metrics"])

    # ---- on-demand profiling (observability/profiling.py) -------------
    def _profiling_targets(self, node_sel) -> list:
        """(node_hex, agent_addr) for the selected node — full or prefix
        hex id — or every alive node when no selector is given."""
        with self._lock:
            nodes = [(n.view.node_id.hex(), n.view.addr)
                     for n in self._nodes.values() if n.view.alive]
        if not node_sel:
            return nodes
        sel = str(node_sel)
        hits = [t for t in nodes if t[0].startswith(sel)]
        if not hits:
            raise ValueError(f"no alive node matches id '{sel}'")
        return hits

    def _profiling_fanout(self, method: str, body) -> dict:
        """Forward a profiling RPC to the selected node agents (they fan
        out to their workers). Runs nested RPCs — registered in
        blocking_methods so a slow capture never parks the CP's shared
        handler pool."""
        body = body or {}
        targets = self._profiling_targets(body.get("node_id"))
        fwd = {k: v for k, v in body.items() if k != "node_id"}
        out = {}
        for nhex, addr in targets:
            try:
                out[nhex] = self._pool.get(tuple(addr)).call(
                    method, fwd, timeout=60.0, connect_timeout=3.0)
            except Exception as e:  # noqa: BLE001 - report per node
                out[nhex] = {"ok": False, "error": repr(e)}
        return out

    def _h_profiling_start(self, body):
        """Start an XPlane capture on the selected node(s)' workers
        (`ray-tpu profile` / dashboard `/api/profile?node=`)."""
        return {"nodes": self._profiling_fanout("profiling_start", body)}

    def _h_profiling_stop(self, body):
        """Stop the captures and REGISTER each produced trace as a
        `profile_artifact:<id>` KV entry (node, worker, pid, logdir,
        duration) — the dashboard lists and serves these."""
        import json
        import uuid

        nodes = self._profiling_fanout("profiling_stop", body)
        artifacts = []
        for nhex, nres in nodes.items():
            workers = (nres.get("workers") or {}) \
                if isinstance(nres, dict) else {}
            for wid, wres in workers.items():
                if not (isinstance(wres, dict) and wres.get("ok")
                        and wres.get("logdir")):
                    continue
                art = {"id": uuid.uuid4().hex[:12], "kind": "xplane",
                       "node_id": nhex, "worker_id": wid,
                       "pid": wres.get("pid"), "logdir": wres["logdir"],
                       "duration_s": wres.get("duration_s"),
                       "ts": time.time()}
                self._h_kv_put({"key": f"profile_artifact:{art['id']}",
                                "value": json.dumps(art).encode()})
                artifacts.append(art)
        return {"nodes": nodes, "artifacts": artifacts}

    def _h_save_device_memory_profile(self, body):
        """Device-memory (pprof) dump on the selected node(s)' workers."""
        return {"nodes": self._profiling_fanout(
            "save_device_memory_profile", body)}

    def _h_list_profile_artifacts(self, body):
        """Registered capture artifacts, newest first."""
        import json

        with self._lock:
            raw = [v for k, v in self._kv.items()
                   if k.startswith("profile_artifact:")]
        out = []
        for v in raw:
            try:
                out.append(json.loads(
                    v.decode() if isinstance(v, bytes) else v))
            except Exception:  # noqa: BLE001 - skip corrupt entries
                continue
        out.sort(key=lambda a: a.get("ts") or 0, reverse=True)
        return out

    # ---- actors -------------------------------------------------------
    def _h_create_actor(self, body):
        spec: TaskSpec = body["spec"]
        info = ActorInfo(
            actor_id=spec.actor_id, spec=spec, name=body.get("name", ""),
            detached=body.get("detached", False), max_restarts=spec.max_restarts,
            pg_id=getattr(spec.strategy, "pg_id", None))
        with self._lock:
            if info.name:
                if info.name in self._named_actors:
                    raise ValueError(f"actor name '{info.name}' already taken")
                self._named_actors[info.name] = info.actor_id
            self._actors[info.actor_id] = info
            self._pending_actors.append(info.actor_id)
            self._persist_actor(info)
        self._wake_scheduler()
        return {"actor_id": info.actor_id}

    def _h_resolve_actor(self, body):
        """Blocking resolve: return (state, addr) once ALIVE or DEAD."""
        deadline = time.monotonic() + body.get("timeout", 60.0)
        aid = body["actor_id"]
        while time.monotonic() < deadline and not self._stopped.is_set():
            with self._lock:
                info = self._actors.get(aid)
                if info is None:
                    raise ValueError(f"unknown actor {aid}")
                if info.state == ActorState.ALIVE:
                    return {"state": "ALIVE", "addr": info.addr, "worker_id": info.worker_id}
                if info.state == ActorState.DEAD:
                    return {"state": "DEAD", "death_cause": info.death_cause}
            time.sleep(0.01)
        return {"state": "TIMEOUT"}

    def _h_get_actor_by_name(self, body):
        deadline = time.monotonic() + body.get("timeout", 0.0)
        while True:
            with self._lock:
                aid = self._named_actors.get(body["name"])
                if aid is not None:
                    info = self._actors[aid]
                    return {"actor_id": aid, "spec": info.spec}
            if time.monotonic() >= deadline:
                return None
            time.sleep(0.02)

    def _h_kill_actor(self, body):
        self._kill_actor(body["actor_id"], body.get("no_restart", True), "ray_tpu.kill")
        return {"ok": True}

    def _h_actor_exited(self, body):
        """Actor called exit_actor() or its worker exited cleanly."""
        self._on_actor_down(body["actor_id"], "actor exited", clean=True)
        return {"ok": True}

    def _h_worker_died(self, body):
        """Reported by a node agent (ref: GcsActorManager::OnWorkerDead).
        Besides actor failover, a dead worker's metric series are retracted
        — a scrape must never keep serving a gone process's series — and
        late flusher reports from it are rejected (_dead_workers)."""
        wid = body.get("worker_id")
        if wid is not None:
            whex = wid.hex() if hasattr(wid, "hex") else str(wid)
            with self._lock:
                self._dead_workers.add(whex)
                self._retract_metrics_source(whex)
                # its spilled KV chains are gone with it: a replica
                # probing the tier index must miss instead of fetching
                # a dead worker's object refs
                self._retract_kv_tier_locked(whex=whex)
                # and its SLO exemplars: a dead proxy/replica process must
                # not keep serving stale slow-request timelines
                self._retract_slo_exemplars_locked(whex)
        aid = body.get("actor_id")
        if aid is not None:
            self._on_actor_down(aid, body.get("reason", "worker died"), clean=False)
        return {"ok": True}

    def _h_list_actors(self, body):
        with self._lock:
            return [
                {"actor_id": i.actor_id, "name": i.name, "state": i.state.value,
                 "node_id": i.node_id, "addr": i.addr, "num_restarts": i.num_restarts,
                 "class_name": i.spec.name, "death_cause": i.death_cause}
                for i in self._actors.values()]

    def _kill_actor(self, actor_id: ActorID, no_restart: bool, reason: str):
        with self._lock:
            info = self._actors.get(actor_id)
            if info is None:
                return
            addr = info.addr
            if no_restart:
                info.max_restarts = info.num_restarts  # exhaust budget
        if addr is not None:
            try:
                # best-effort fast kill: the worker may exit before it could
                # ack, and _on_actor_down below settles the actor's fate
                # either way — an acked call() would only add a stall
                # graftlint: fire-and-forget
                self._pool.get(addr).notify("kill_actor", {"actor_id": actor_id})
            except Exception:
                pass
        # clean=False so kill(no_restart=False) consumes the restart budget and
        # restarts the actor (ref: GcsActorManager::DestroyActor no_restart arg)
        self._on_actor_down(actor_id, reason, clean=False, force_dead=no_restart)

    def _on_actor_down(self, actor_id: ActorID, reason: str, clean: bool,
                       force_dead: bool = False):
        with self._lock:
            info = self._actors.get(actor_id)
            if info is None or info.state == ActorState.DEAD:
                return
            # release lease resources (PG actors drew from the bundle
            # reservation, which stays held by the PG until it is removed)
            if info.node_id is not None and info.pg_id is None:
                self._release_node_resources(info.node_id, info.spec.resources)
            restartable = (not force_dead and not clean
                           and (info.max_restarts < 0 or info.num_restarts < info.max_restarts))
            if restartable:
                info.state = ActorState.RESTARTING
                info.num_restarts += 1
                info.addr = None
                info.node_id = None
                self._pending_actors.append(actor_id)
                state_msg = "RESTARTING"
            else:
                info.state = ActorState.DEAD
                info.death_cause = reason
                info.addr = None
                state_msg = "DEAD"
                if info.name and not restartable:
                    self._named_actors.pop(info.name, None)
            self._persist_actor(info)
        self._publish(f"actor:{actor_id.hex()}",
                      {"state": state_msg, "reason": reason})
        self._wake_scheduler()

    def _release_node_resources(self, node_id: NodeID, resources: dict):
        node = self._nodes.get(node_id)
        if node is not None:
            add(node.view.available, resources)

    # ---- placement groups ---------------------------------------------
    def _h_create_pg(self, body):
        pg = PGInfo(pg_id=body["pg_id"], bundles=body["bundles"],
                    strategy=body["strategy"], name=body.get("name", ""),
                    creator_job=body.get("job_id"))
        with self._lock:
            self._pgs[pg.pg_id] = pg
            self._pending_pgs.append(pg.pg_id)
            self._persist_pg(pg)
        self._wake_scheduler()
        return {"pg_id": pg.pg_id}

    def _h_pg_ready(self, body):
        deadline = time.monotonic() + body.get("timeout", 60.0)
        while time.monotonic() < deadline and not self._stopped.is_set():
            with self._lock:
                pg = self._pgs.get(body["pg_id"])
                if pg is None:
                    raise ValueError("unknown placement group")
                if pg.state == PGState.CREATED:
                    return {"state": "CREATED",
                            "node_ids": pg.node_ids,
                            "bundles": pg.bundles}
                if pg.state == PGState.REMOVED:
                    raise PlacementGroupSchedulingError("placement group removed")
            time.sleep(0.01)
        return {"state": "TIMEOUT"}

    def _h_remove_pg(self, body):
        pg_id = body["pg_id"]
        with self._lock:
            pg = self._pgs.get(pg_id)
            if pg is None or pg.state == PGState.REMOVED:
                return {"ok": True}
            pg.state = PGState.REMOVED
            self._persist_pg(pg)
            allocations = list(zip(pg.node_ids, pg.bundles))
        by_node: dict[NodeID, list] = {}
        for nid, b in allocations:
            by_node.setdefault(nid, []).append(b)
        for nid, bundles in by_node.items():
            node = self._nodes.get(nid)
            if node is None:
                continue
            try:
                self._pool.get(node.view.addr).call_with_retry(
                    "cancel_bundles", {"pg_id": pg_id}, timeout=10.0)
            except Exception:
                pass
        self._wake_scheduler()
        return {"ok": True}

    def _h_get_pg(self, body):
        with self._lock:
            pg = self._pgs.get(body["pg_id"])
            if pg is None:
                return None
            return {"pg_id": pg.pg_id, "state": pg.state.value, "bundles": pg.bundles,
                    "strategy": pg.strategy, "node_ids": pg.node_ids, "name": pg.name}

    def _h_list_pgs(self, body):
        with self._lock:
            return [{"pg_id": p.pg_id, "state": p.state.value, "strategy": p.strategy,
                     "bundles": p.bundles, "name": p.name} for p in self._pgs.values()]

    # ---- scheduling loop ----------------------------------------------
    def _scheduling_loop(self):
        while not self._stopped.is_set():
            try:
                progressed = self._schedule_pending_pgs()
                progressed |= self._schedule_pending_actors()
            except Exception:
                logger.exception("scheduling loop error")
                progressed = False
            if not progressed:
                with self._wake:
                    self._wake.wait(timeout=0.2)

    def _alive_views(self) -> list[NodeView]:
        """Placement candidates: ALIVE only — a DRAINING node still
        heartbeats (view.alive stays True) but must not receive new actors
        or placement-group bundles."""
        with self._lock:
            return [n.view for n in self._nodes.values()
                    if n.view.alive and n.state == "ALIVE"]

    def _schedule_pending_actors(self) -> bool:
        """Async fan-out actor placement (ref:
        GcsActorManager::SchedulePendingActors gcs_actor_manager.h:198 with
        the scheduler's async LeaseWorkerFromNode gcs_actor_scheduler.h:256):
        pick a node per pending actor, optimistically reserve against the
        cached view, and fire the lease RPC WITHOUT blocking the scheduling
        loop — the grant/rejection completes on the RPC callback. The old
        serial synchronous lease capped actor bringup at one lease RTT per
        actor (~2/s at 1,000-actor scale)."""
        self._expire_stale_leases()
        with self._lock:
            _SCHED_PENDING_GAUGE.set(
                len(self._pending_actors) + len(self._scheduling_pass))
            _SCHED_PLACING_GAUGE.set(len(self._placing_actors))
            if not self._pending_actors:
                return False
            pending, self._pending_actors = self._pending_actors, []
            # keep the in-pass snapshot visible to the autoscaler demand
            # poll (an infeasible actor is neither pending nor placing
            # mid-pass; without this the poll reads zero demand and the
            # autoscaler scales down)
            self._scheduling_pass = list(pending)
        progressed = False
        try:
            for aid in pending:
                with self._lock:
                    info = self._actors.get(aid)
                    if info is None or info.state not in (ActorState.PENDING, ActorState.RESTARTING):
                        continue
                    if len(self._placing_actors) >= self._max_inflight_leases:
                        self._pending_actors.append(aid)
                        continue
                if not self._begin_actor_lease(info):
                    with self._lock:
                        self._pending_actors.append(aid)
                else:
                    progressed = True
        finally:
            with self._lock:
                self._scheduling_pass = []
        return progressed

    def _expire_stale_leases(self):
        """Re-queue placements whose lease RPC never completed (hung agent
        whose TCP stays open); a late grant is detected as stale in the
        reply callback and its lease returned."""
        cfg = get_config()
        ttl = cfg.lease_timeout_s * (cfg.rpc_retries + 1) + 10.0
        now = time.monotonic()
        with self._lock:
            expired = [aid for aid, (_nid, ts) in self._placing_actors.items()
                       if now - ts > ttl]
            for aid in expired:
                del self._placing_actors[aid]
                self._pending_actors.append(aid)
        if expired:
            logger.warning("%d actor lease(s) expired; re-queued", len(expired))

    def _begin_actor_lease(self, info: ActorInfo) -> bool:
        """Dispatch one async lease for a pending actor; returns True when
        the RPC is in flight (completion in _on_actor_lease_reply)."""
        spec = info.spec
        views = self._alive_views()
        strategy = spec.strategy
        pg_id = getattr(strategy, "pg_id", None)
        resources = dict(spec.resources)
        if pg_id is not None:
            with self._lock:
                pg = self._pgs.get(pg_id)
            if pg is None or pg.state != PGState.CREATED:
                return False
            idx = getattr(strategy, "bundle_index", -1)
            candidates = pg.node_ids if idx < 0 else [pg.node_ids[idx]]
            views = [v for v in views if v.node_id in candidates]
            lease_body = {"resources": resources, "pg_id": pg_id,
                          "bundle_index": idx}
            # Bundle resources were subtracted from the node view at PG
            # commit; the actor draws from the bundle's reservation, so the
            # fit check here must not demand them from `available` again.
            resources = {}
        else:
            lease_body = {"resources": resources}
        node = pick_node(views, resources, strategy)
        if node is None:
            return False
        with self._lock:
            cp_node = self._nodes.get(node.node_id)
            if cp_node is None or not cp_node.view.alive:
                return False
            # optimistic reservation: concurrent placements must spread
            # instead of stampeding the node the stale view liked best; the
            # grant's authoritative snapshot (or any fresher agent report)
            # supersedes it, and a rejection re-adds it version-gated
            subtract(cp_node.view.available, resources)
            reserved_version = cp_node.res_version
            # the tuple object doubles as the attempt token: a late reply
            # from an EXPIRED attempt (TTL requeue, node death) must not pop
            # a newer re-dispatched attempt's entry
            token = (node.node_id, time.monotonic())
            self._placing_actors[info.actor_id] = token
        if spec.runtime_env:
            lease_body["runtime_env"] = spec.runtime_env
        lease_body.update({"for_actor": info.actor_id,
                           "job_id": spec.job_id.hex(),
                           "timeout": get_config().lease_timeout_s})
        node_id, node_addr = node.node_id, node.addr

        def on_reply(ok, reply):
            try:
                self._on_actor_lease_reply(
                    info, node_id, node_addr, resources, reserved_version,
                    token, ok, reply)
            except Exception:
                logger.exception("actor lease reply handling failed")

        try:
            self._pool.get(node_addr).call_async(
                "lease_worker", lease_body, callback=on_reply)
        except Exception as e:
            on_reply(False, e)
        return True

    def _release_stale_grant(self, node_addr, reply):
        try:
            self._pool.get(node_addr).call_async(
                "return_lease", {"lease_id": reply.get("lease_id")})
        except Exception:  # noqa: BLE001 — agent may be gone
            pass

    def _on_actor_lease_reply(self, info: ActorInfo, node_id, node_addr,
                              resources, reserved_version, token, ok, reply):
        granted = ok and isinstance(reply, dict) and reply.get("granted")
        _LEASE_LATENCY_HIST.observe(
            time.monotonic() - token[1],
            tags={"granted": str(bool(granted)).lower()})
        with self._lock:
            cp_node = self._nodes.get(node_id)
            current = self._placing_actors.get(info.actor_id) is token
            if current:
                # this attempt owns the entry: always release the in-flight
                # slot, even when the actor was killed mid-placement (a
                # leaked entry would wedge one of _max_inflight_leases
                # slots until the TTL sweep)
                del self._placing_actors[info.actor_id]
            # a reply from an expired/requeued attempt must leave any newer
            # attempt alone; a dead/killed actor's grant is returned below
            stale = not current or info.state not in (ActorState.PENDING,
                                                      ActorState.RESTARTING)
            if (not granted or stale) and cp_node is not None \
                    and cp_node.res_version == reserved_version:
                # lease didn't land (or landed too late): roll back the
                # optimistic reservation unless a fresher authoritative
                # snapshot already replaced the view
                add(cp_node.view.available, resources)
        if stale:
            if granted:
                self._release_stale_grant(node_addr, reply)
            return
        if not granted:
            if not ok:
                logger.warning("lease for actor %s on node %s failed: %s",
                               info.actor_id.hex()[:8], node_id.hex()[:8],
                               reply)
            with self._lock:
                self._pending_actors.append(info.actor_id)
            self._wake_scheduler()
            return
        worker_addr = tuple(reply["worker_addr"])
        spec = info.spec
        with self._lock:
            if reply.get("available") is not None:
                # agent's authoritative post-grant snapshot; subtracting here
                # instead would double-count when the agent's async resource
                # report raced ahead of this reply. Version-gated: a report
                # newer than this grant must not be regressed.
                if self._fresher(cp_node, reply):
                    cp_node.view.available = dict(reply["available"])
            info.node_id = node_id
            info.worker_id = reply["worker_id"]
        spec.attempt_number = info.num_restarts

        def on_created(ok, result):
            if ok and not result.get("error"):
                with self._lock:
                    info.state = ActorState.ALIVE
                    info.addr = worker_addr
                    self._persist_actor(info)
                self._publish(f"actor:{info.actor_id.hex()}",
                              {"state": "ALIVE", "addr": worker_addr})
            else:
                reason = str(result.get("error") if ok else result)
                logger.warning("actor %s creation failed: %s",
                               info.actor_id.hex()[:8], reason)
                self._on_actor_down(info.actor_id, f"creation failed: {reason}",
                                    clean=True, force_dead=True)
            self._wake_scheduler()

        try:
            self._pool.get(worker_addr).call_async(
                "push_task", {"spec": spec}, callback=on_created)
        except Exception as e:
            self._on_actor_down(info.actor_id, f"push failed: {e}", clean=False)

    def _schedule_pending_pgs(self) -> bool:
        with self._lock:
            if not self._pending_pgs:
                return False
            pending, self._pending_pgs = self._pending_pgs, []
            self._placing_pgs = list(pending)
        progressed = False
        try:
            for pg_id in pending:
                with self._lock:
                    pg = self._pgs.get(pg_id)
                    if pg is None or pg.state != PGState.PENDING:
                        continue
                if self._try_schedule_pg(pg):
                    progressed = True
                else:
                    with self._lock:
                        self._pending_pgs.append(pg_id)
        finally:
            with self._lock:
                self._placing_pgs = []
        return progressed

    def _try_schedule_pg(self, pg: PGInfo) -> bool:
        """2-phase prepare/commit across node agents
        (ref: gcs_placement_group_scheduler.cc; node_manager.proto:452-461)."""
        views = self._alive_views()
        if pg.strategy == "SLICE":
            placement = place_slice_bundles(views, pg.bundles)
        else:
            placement = place_bundles(views, pg.bundles, pg.strategy)
        if placement is None:
            return False
        by_node: dict[NodeID, list[tuple[int, dict]]] = {}
        for i, (nid, b) in enumerate(zip(placement, pg.bundles)):
            by_node.setdefault(nid, []).append((i, b))
        prepared: list[NodeID] = []
        ok = True
        for nid, items in by_node.items():
            node = self._nodes.get(nid)
            try:
                r = self._pool.get(node.view.addr).call_with_retry(
                    "prepare_bundles", {"pg_id": pg.pg_id, "bundles": items}, timeout=10.0)
                if not r.get("ok"):
                    ok = False
                    break
                prepared.append(nid)
            except Exception:
                ok = False
                break
        if not ok:
            for nid in prepared:
                node = self._nodes.get(nid)
                try:
                    self._pool.get(node.view.addr).call_with_retry(
                        "cancel_bundles", {"pg_id": pg.pg_id}, timeout=10.0)
                except Exception:
                    pass
            return False
        for nid in by_node:
            node = self._nodes.get(nid)
            try:
                self._pool.get(node.view.addr).call_with_retry(
                    "commit_bundles", {"pg_id": pg.pg_id}, timeout=10.0)
            except Exception:
                pass
        with self._lock:
            pg.node_ids = placement
            pg.state = PGState.CREATED
            self._persist_pg(pg)
            for nid, items in by_node.items():
                node = self._nodes.get(nid)
                for _, b in items:
                    subtract(node.view.available, b)
        self._publish(f"pg:{pg.pg_id.hex()}", {"state": "CREATED"})
        return True

    # ---- health checks -------------------------------------------------
    def _health_loop(self):
        """(ref: gcs_health_check_manager.h:45)"""
        cfg = get_config()
        while not self._stopped.is_set():
            time.sleep(cfg.health_check_period_s)
            with self._lock:
                nodes = list(self._nodes.values())
            for node in nodes:
                if not node.view.alive:
                    continue
                try:
                    # short connect window: a refused connect means the
                    # agent's port is gone — burning the full RPC connect
                    # retry budget per miss would stretch detection to
                    # threshold * connect_timeout (50s+)
                    self._pool.get(node.view.addr).call(
                        "ping", None, timeout=cfg.health_check_timeout_s,
                        connect_timeout=min(1.0, cfg.health_check_timeout_s))
                    node.missed_health_checks = 0
                except Exception:
                    node.missed_health_checks += 1
                    if node.missed_health_checks >= cfg.health_check_failure_threshold:
                        self._on_node_dead(node.view.node_id, "health check failed")

    def _on_node_dead(self, node_id: NodeID, reason: str):
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None or not node.view.alive:
                return
            node.view.alive = False
            node.state = "DRAINED" if reason == "drained" else "DEAD"
            victims = [i.actor_id for i in self._actors.values()
                       if i.node_id == node_id and i.state == ActorState.ALIVE]
            # placements whose lease RPC targeted the dead node will never
            # complete: re-queue them now (a late grant from a zombie agent
            # is handled as stale in the reply callback)
            placing = [aid for aid, (nid, _ts) in self._placing_actors.items()
                       if nid == node_id]
            for aid in placing:
                del self._placing_actors[aid]
                self._pending_actors.append(aid)
            # retract every metric series reported from the dead node (the
            # agent's own source plus each worker flusher that tagged its
            # payloads with this node)
            nhex = node_id.hex()
            gone = [s for s, n in self._source_nodes.items() if n == nhex]
            gone.append(f"node:{nhex}")
            for src in gone:
                self._retract_metrics_source(src)
                if not src.startswith("node:"):
                    self._dead_workers.add(src)
                    self._retract_slo_exemplars_locked(src)
            # every kv_tier entry spilled from this node is unservable
            self._retract_kv_tier_locked(nhex=nhex)
        logger.warning("node %s dead: %s", node_id.hex()[:8], reason)
        self._emit_cp_event(
            "node_dead", "INFO" if reason == "drained" else "ERROR",
            node=node_id.hex(), reason=reason)
        self._publish("node", {"event": "dead", "node_id": node_id})
        for aid in victims:
            self._on_actor_down(aid, f"node died: {reason}", clean=False)
        self._wake_scheduler()

    # ---- lifecycle ------------------------------------------------------
    def _h_ping(self, body):
        return {"ok": True}

    def _h_shutdown(self, body):
        threading.Thread(target=self.stop, daemon=True).start()
        return {"ok": True}

    def stop(self):
        self._stopped.set()
        # conditional: a restarted CP may already own the sink
        _events.clear_local_sink(self._event_sink)
        _metrics.stop_flusher(self._metrics_flusher, final=False)
        self._wake_scheduler()
        self._server.stop()
        self._pool.close_all()
        try:
            self._store.close()
        except Exception:
            pass
