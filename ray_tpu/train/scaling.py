"""Scaling policies: elastic resize decisions for the Train controller.

TPU-native analog of the reference's scaling policy layer
(/root/reference/python/ray/train/v2/_internal/execution/scaling_policy/
scaling_policy.py — ResizeDecision/NoopDecision, consumed by the controller
at controller.py:421-433; fixed.py is the default). On TPU a resize is
restart-the-world (SURVEY.md §7 hard part 4): JAX's distributed runtime
cannot resize in place, so every ResizeDecision tears the gang down and
restarts it at the new size with resume-from-latest-checkpoint.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass
class ResizeDecision:
    """Restart the worker group at `num_workers` ranks."""

    num_workers: int


class NoopDecision:
    """Keep running as-is."""


NOOP = NoopDecision()


class ScalingPolicy:
    """Decides gang sizing; subclass to make training elastic."""

    def make_decision_for_non_running_worker_group(
            self, requested_num_workers: int) -> int:
        """Size to start (or restart) the gang at."""
        return requested_num_workers

    def make_decision_for_running_worker_group(
            self, statuses, num_workers: int):
        """Called every poll while RUNNING; return NOOP or ResizeDecision."""
        return NOOP


class FixedScalingPolicy(ScalingPolicy):
    """Never resizes (reference fixed.py)."""


class FunctionScalingPolicy(ScalingPolicy):
    """Adapter: `fn(statuses, num_workers) -> Optional[int]` (new size or
    None). Convenient for tests and simple autoscaling hooks."""

    def __init__(self, fn):
        self._fn = fn

    def make_decision_for_running_worker_group(self, statuses,
                                               num_workers: int):
        target: Optional[int] = self._fn(statuses, num_workers)
        if target is None or target == num_workers:
            return NOOP
        return ResizeDecision(num_workers=target)
