"""Flash attention as a Pallas TPU kernel.

The hot op of the transformer stack (SURVEY.md TPU-native note: pallas for the
ops XLA can't fuse). Streaming-softmax tiling keeps the working set in VMEM and
the (block_q × block_k) score matmuls on the MXU; causal blocks that are fully
masked are skipped. Used by models/llama.py (attn_impl="flash") and as the
per-block kernel of parallel/ring_attention.py on TPU.

Falls back to a fused einsum implementation off-TPU; tests run the kernel in
interpreter mode on CPU (pl.pallas_call(interpret=True)).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30
_STATS_LANES = 128  # stats tiles are [block_q, 128] to satisfy TPU tiling


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                  *, sm_scale: float, causal: bool, block_q: int,
                  block_k: int, num_k_blocks: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q_start = qi * block_q
    k_start = ki * block_k
    # causal: the whole k-block is in the future of the whole q-block → skip
    needed = (not causal) or (k_start <= q_start + block_q - 1)

    @pl.when(needed)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # [bq, bk]
        if causal:
            rows = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        m_prev = m_scr[:, 0]  # [bq]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_scr[:, 0] = l_scr[:, 0] * alpha + jnp.sum(p, axis=-1)
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * alpha[:, None] + pv
        m_scr[:, 0] = m_new

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        l = l_scr[:, 0]
        l = jnp.where(l > 0, l, 1.0)
        o_ref[0] = (acc_scr[:] / l[:, None]).astype(o_ref.dtype)
        # log-sum-exp per row, consumed by the backward kernels (FA2).
        # Shape [bq, 1]: TPU block tiling wants the last two dims divisible
        # by (8, 128) or equal to the array dims — a trailing singleton
        # axis satisfies that and broadcasts cleanly in the backward.
        lse_ref[0] = (m_scr[:, 0] + jnp.log(l))[:, None]


def _flash_bh(q, k, v, *, causal: bool, sm_scale: float, block_q: int,
              block_k: int, interpret: bool):
    """q,k,v: [BH, T, D] → [BH, T, D]."""
    bh, t_q, d = q.shape
    t_k = k.shape[1]
    block_q = min(block_q, t_q)
    block_k = min(block_k, t_k)
    if t_q % block_q or t_k % block_k:
        raise ValueError(f"seq lens ({t_q},{t_k}) must divide blocks "
                         f"({block_q},{block_k})")
    num_q = t_q // block_q
    num_k = t_k // block_k
    grid = (bh, num_q, num_k)
    kernel = functools.partial(
        _flash_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, num_k_blocks=num_k)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, qi, ki: (b, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, qi, ki: (b, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, qi, ki: (b, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t_q, d), q.dtype),
            jax.ShapeDtypeStruct((bh, t_q, 1), jnp.float32),  # lse
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _STATS_LANES), jnp.float32),  # running max
            pltpu.VMEM((block_q, _STATS_LANES), jnp.float32),  # running sum
            pltpu.VMEM((block_q, d), jnp.float32),             # output acc
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v)


def _flash_bwd_dkdv_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                           dk_ref, dv_ref, dk_scr, dv_scr, *,
                           sm_scale: float, causal: bool, block_q: int,
                           block_k: int, num_q_blocks: int):
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    q_start = qi * block_q
    k_start = ki * block_k
    # causal: this whole q-block precedes the k-block → no contribution
    needed = (not causal) or (q_start + block_q - 1 >= k_start)

    @pl.when(needed)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        g = g_ref[0]
        s = jax.lax.dot_general(
            q.astype(jnp.float32), k.astype(jnp.float32),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # [bq, bk]
        if causal:
            rows = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        p = jnp.exp(s - lse_ref[0])                           # [bq, bk]
        # dv += pᵀ · dO
        dv_scr[:] += jax.lax.dot_general(
            p.astype(g.dtype), g, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # dp = dO · vᵀ ; ds = p (dp - delta) · scale
        dp = jax.lax.dot_general(
            g, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta_ref[0]) * sm_scale).astype(q.dtype)
        # dk += dsᵀ · q
        dk_scr[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == num_q_blocks - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                         dq_ref, dq_scr, *, sm_scale: float, causal: bool,
                         block_q: int, block_k: int, num_k_blocks: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    q_start = qi * block_q
    k_start = ki * block_k
    needed = (not causal) or (k_start <= q_start + block_q - 1)

    @pl.when(needed)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        g = g_ref[0]
        s = jax.lax.dot_general(
            q.astype(jnp.float32), k.astype(jnp.float32),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            rows = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        p = jnp.exp(s - lse_ref[0])
        dp = jax.lax.dot_general(
            g, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta_ref[0]) * sm_scale).astype(q.dtype)
        dq_scr[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _flash_bwd_bh(q, k, v, g, lse, delta, *, causal: bool, sm_scale: float,
                  block_q: int, block_k: int, interpret: bool):
    """Pallas flash backward over [BH, T, D] inputs → (dq, dk, dv).

    Two kernels (the canonical FA2 split): dk/dv accumulate over q blocks
    with the k block resident in VMEM; dq accumulates over k blocks. Both
    recompute p from (q, k, lse) — nothing [T, T]-shaped ever exists, and
    every matmul runs on the MXU in the input dtype with fp32 accumulation.
    Replaces a pure-JAX blockwise backward whose [B,H,T,block] fp32
    intermediates ran the train-step backward at ~2% MXU utilization (it
    was ~24% of the whole train step at 1.5B scale)."""
    bh, t_q, d = q.shape
    t_k = k.shape[1]
    block_q = min(block_q, t_q)
    block_k = min(block_k, t_k)
    if t_q % block_q or t_k % block_k:
        raise ValueError(f"seq lens ({t_q},{t_k}) must divide blocks "
                         f"({block_q},{block_k})")
    num_q = t_q // block_q
    num_k = t_k // block_k

    kv_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, ki, qi: (b, qi, 0)),   # q
        pl.BlockSpec((1, block_k, d), lambda b, ki, qi: (b, ki, 0)),   # k
        pl.BlockSpec((1, block_k, d), lambda b, ki, qi: (b, ki, 0)),   # v
        pl.BlockSpec((1, block_q, d), lambda b, ki, qi: (b, qi, 0)),   # g
        pl.BlockSpec((1, block_q, 1), lambda b, ki, qi: (b, qi, 0)),   # lse
        pl.BlockSpec((1, block_q, 1), lambda b, ki, qi: (b, qi, 0)),   # delta
    ]
    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkdv_kernel, sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k, num_q_blocks=num_q),
        grid=(bh, num_k, num_q),
        in_specs=kv_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, ki, qi: (b, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, ki, qi: (b, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t_k, d), k.dtype),
            jax.ShapeDtypeStruct((bh, t_k, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, g, lse, delta)

    dq_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),   # q
        pl.BlockSpec((1, block_k, d), lambda b, qi, ki: (b, ki, 0)),   # k
        pl.BlockSpec((1, block_k, d), lambda b, qi, ki: (b, ki, 0)),   # v
        pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),   # g
        pl.BlockSpec((1, block_q, 1), lambda b, qi, ki: (b, qi, 0)),   # lse
        pl.BlockSpec((1, block_q, 1), lambda b, qi, ki: (b, qi, 0)),   # delta
    ]
    dq = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k, num_k_blocks=num_k),
        grid=(bh, num_q, num_k),
        in_specs=dq_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, t_q, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, g, lse, delta)
    return dq, dk, dv


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    from jax.ad_checkpoint import checkpoint_name
    b, t, h, d = q.shape
    to_bh = lambda x: x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)
    out_bh, lse = _flash_bh(to_bh(q), to_bh(k), to_bh(v), causal=causal,
                            sm_scale=sm_scale, block_q=block_q,
                            block_k=block_k, interpret=interpret)
    out = out_bh.reshape(b, h, t, d).transpose(0, 2, 1, 3)
    # "attn_lse" lets remat policies save the softmax stats so the backward
    # does not re-run the forward kernel just to rebuild them (the output
    # residual aliases the primal, which callers tag "attn").
    lse = checkpoint_name(lse, "attn_lse")
    return out, (q, k, v, out, lse)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_attention_core(q, k, v, causal, sm_scale, block_q, block_k,
                          interpret):
    return _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k,
                      interpret)[0]


def _flash_bwd(causal, sm_scale, block_q, block_k, interpret, res, g):
    """Pallas flash-attention backward (FA2): p is recomputed per block from
    (q, k) + the forward's saved log-sum-exp; delta = rowsum(dO · O)."""
    q, k, v, out, lse = res
    b, t_q, h, d = q.shape
    t_k = k.shape[1]
    to_bh = lambda x: x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)
    g_bh = to_bh(g)
    delta = jnp.sum(g_bh.astype(jnp.float32) *
                    to_bh(out).astype(jnp.float32),
                    axis=-1, keepdims=True)  # [BH, Tq, 1]
    dq, dk, dv = _flash_bwd_bh(
        to_bh(q), to_bh(k), to_bh(v), g_bh, lse, delta,
        causal=causal, sm_scale=sm_scale, block_q=block_q, block_k=block_k,
        interpret=interpret)
    from_bh = lambda x, t: x.reshape(b, h, t, d).transpose(0, 2, 1, 3)
    return (from_bh(dq, t_q).astype(q.dtype),
            from_bh(dk, t_k).astype(k.dtype),
            from_bh(dv, t_k).astype(v.dtype))


_flash_attention_core.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, *, causal: bool = True, sm_scale: float | None = None,
                    block_q: int = 256, block_k: int = 256,
                    interpret: bool | None = None):
    """q,k,v: [B, T, H, D] (same H — expand GQA before calling).
    Differentiable: forward is the Pallas kernel, backward a blockwise
    recompute (no [T,T] materialization)."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash_attention_core(q, k, v, causal, sm_scale, block_q, block_k,
                                 interpret)


def reference_attention(q, k, v, *, causal: bool = True,
                        sm_scale: float | None = None):
    """Fused-einsum fallback (XLA fuses softmax into the matmuls well enough
    off-TPU)."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * sm_scale
    if causal:
        t_q, t_k = q.shape[1], k.shape[1]
        mask = jnp.arange(t_q)[:, None] >= jnp.arange(t_k)[None, :]
        logits = jnp.where(mask[None, None], logits, _NEG_INF)
    p = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)
