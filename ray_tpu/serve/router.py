"""Power-of-two-choices request router.

TPU-native analog of the reference's router
(/root/reference/python/ray/serve/_private/router.py — AsyncioRouter:457,
assign_request:838; request_router/pow_2_router.py): pick two random
replicas, probe cached queue lengths, route to the shorter queue. Queue
lengths are refreshed in the background; routing table updates come from the
controller via versioned polls (the reference uses long-poll, long_poll.py).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Optional

import ray_tpu


class ReplicaSet:
    """Cached view of one deployment's replicas + queue lengths."""

    def __init__(self):
        self.replicas: list = []           # actor handles
        self.version: int = -1
        self._qlen: dict[int, tuple[float, int]] = {}  # idx -> (ts, len)
        self._rr = 0

    def update(self, replicas: list, version: int):
        self.replicas = replicas
        self.version = version
        self._qlen = {}

    def _probe(self, idx: int, staleness_s: float = 0.5) -> int:
        now = time.monotonic()
        cached = self._qlen.get(idx)
        if cached and now - cached[0] < staleness_s:
            return cached[1]
        try:
            qlen = ray_tpu.get(self.replicas[idx].get_queue_len.remote(),
                               timeout=2.0)
        except Exception:  # noqa: BLE001 - dead replica looks busy
            qlen = 1 << 30
        self._qlen[idx] = (now, qlen)
        return qlen

    def choose(self) -> Optional[object]:
        n = len(self.replicas)
        if n == 0:
            return None
        if n == 1:
            return self.replicas[0]
        i, j = random.sample(range(n), 2)
        return self.replicas[i if self._probe(i) <= self._probe(j) else j]


class Router:
    """Routes requests for any deployment in one application."""

    def __init__(self, controller, app_name: str, poll_period_s: float = 0.5):
        self._controller = controller
        self._app = app_name
        self._sets: dict[str, ReplicaSet] = {}
        self._lock = threading.Lock()
        self._poll_period = poll_period_s
        self._last_poll = 0.0

    def _maybe_refresh(self, deployment: str, force: bool = False):
        now = time.monotonic()
        with self._lock:
            rs = self._sets.setdefault(deployment, ReplicaSet())
            if not force and rs.replicas and \
                    now - self._last_poll < self._poll_period:
                return rs
        table = ray_tpu.get(self._controller.get_routing_table.remote(
            self._app), timeout=10.0)
        with self._lock:
            self._last_poll = now
            for dep, (replicas, version) in table.items():
                cur = self._sets.setdefault(dep, ReplicaSet())
                if version != cur.version:
                    cur.update(replicas, version)
            return self._sets.setdefault(deployment, ReplicaSet())

    def assign(self, deployment: str, method: str, args: tuple,
               kwargs: dict, *, streaming: bool = False,
               timeout_s: float = 30.0):
        """Pick a replica and submit; returns the reply ObjectRef."""
        deadline = time.monotonic() + timeout_s
        while True:
            rs = self._maybe_refresh(deployment)
            replica = rs.choose()
            if replica is not None:
                if streaming:
                    return replica.handle_request_streaming.remote(
                        method, args, kwargs)
                return replica.handle_request.remote(method, args, kwargs)
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"no replicas available for deployment "
                    f"{deployment!r} after {timeout_s}s")
            self._maybe_refresh(deployment, force=True)
            time.sleep(0.1)
