"""Search spaces + search algorithms.

TPU-native analog of the reference's tune search layer
(/root/reference/python/ray/tune/search/ — sample.py domains,
basic_variant.py BasicVariantGenerator grid/random, plus the Searcher ABC
that optuna/hyperopt/etc. plug into). In-tree: grid + random (the
reference's default path) and a simple TPE-less `Searcher` hook point.
"""

from __future__ import annotations

import dataclasses
import itertools
import random as _random
from typing import Any, Callable, Optional


# ---- sampling domains ----------------------------------------------------


@dataclasses.dataclass
class Domain:
    def sample(self, rng: _random.Random) -> Any:
        raise NotImplementedError


@dataclasses.dataclass
class GridSearch:
    values: list

    # grid is not sampled; expanded by the variant generator


@dataclasses.dataclass
class Choice(Domain):
    values: list

    def sample(self, rng):
        return rng.choice(self.values)


@dataclasses.dataclass
class Uniform(Domain):
    low: float
    high: float

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


@dataclasses.dataclass
class LogUniform(Domain):
    low: float
    high: float

    def sample(self, rng):
        import math
        return math.exp(rng.uniform(math.log(self.low), math.log(self.high)))


@dataclasses.dataclass
class RandInt(Domain):
    low: int
    high: int

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


@dataclasses.dataclass
class SampleFrom(Domain):
    fn: Callable

    def sample(self, rng):
        return self.fn(None)


def grid_search(values: list) -> GridSearch:
    return GridSearch(list(values))


def choice(values: list) -> Choice:
    return Choice(list(values))


def uniform(low: float, high: float) -> Uniform:
    return Uniform(low, high)


def loguniform(low: float, high: float) -> LogUniform:
    return LogUniform(low, high)


def randint(low: int, high: int) -> RandInt:
    return RandInt(low, high)


def sample_from(fn: Callable) -> SampleFrom:
    return SampleFrom(fn)


# ---- variant generation --------------------------------------------------


class BasicVariantGenerator:
    """Grid axes are fully expanded; Domain axes are sampled num_samples
    times (reference basic_variant.py semantics: num_samples multiplies the
    grid)."""

    def __init__(self, param_space: dict, num_samples: int = 1,
                 seed: Optional[int] = None):
        self._space = param_space
        self._num_samples = num_samples
        self._rng = _random.Random(seed)

    def variants(self) -> list[dict]:
        grid_keys = {}
        flat = _flatten(self._space)
        for key, value in flat.items():
            if isinstance(value, GridSearch):
                grid_keys[key] = value.values
        grids = [dict(zip(grid_keys, combo))
                 for combo in itertools.product(*grid_keys.values())] or [{}]
        out = []
        for _ in range(self._num_samples):
            for grid in grids:
                cfg = {}
                for key, value in flat.items():
                    if key in grid:
                        cfg[key] = grid[key]
                    elif isinstance(value, Domain):
                        cfg[key] = value.sample(self._rng)
                    else:
                        cfg[key] = value
                out.append(_unflatten(cfg))
        return out


def _flatten(d: dict, prefix: str = "") -> dict:
    out = {}
    for k, v in d.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, key + "/"))
        else:
            out[key] = v
    return out


def _unflatten(d: dict) -> dict:
    out: dict = {}
    for k, v in d.items():
        parts = k.split("/")
        cur = out
        for p in parts[:-1]:
            cur = cur.setdefault(p, {})
        cur[parts[-1]] = v
    return out
