"""Elastic cache-warm serve fleet (ISSUE 17).

Pins the PR's acceptance invariants:

- signal-driven scaling: the queue-length policy folds in SLO attribution
  (violations + dominant p99-TTFT stage) and affinity heat — SLO-dominant
  queue/prefill windows upscale, a hot fleet refuses the downscale step,
  and every decision lands in the controller's flight recorder;
- cache-warm scale-up: `insert_digest_chain` registers restored pages
  under pre-computed chain digests, `warm_start()` pulls the fleet's
  hottest tier chains into a fresh engine BEFORE it takes traffic, and
  the warmed engine's greedy output is token-identical to cold prefill;
- warming gate atomicity: a scale-up replica is invisible to routers
  until its warm completes, and the table mutation + version bump are
  one atomic step — a polled table's version uniquely determines its
  replica set (no half-published view), and a stale lower-version table
  can never regress a router's cached set;
- graceful downscale: retiring a BUSY replica drains it kill-free — all
  in-flight SSE streams complete with every token exactly once, zero
  resumes, zero dropped frames;
- `replica_scale` chaos events retarget a deployment mid-traffic.
"""

import json
import threading
import time
import urllib.request
import uuid

import pytest

import ray_tpu


def _cfg(**kw):
    from ray_tpu.models import llama
    from ray_tpu.serve.llm import LLMConfig

    d = dict(model_config=llama.llama_tiny(vocab_size=512),
             max_batch_size=4, page_size=16, num_pages=64,
             max_prompt_len=96, max_seq_len=160, max_tokens=8)
    d.update(kw)
    return LLMConfig(**d)


PROMPT = "the quick brown fox jumps over the lazy dog"
LONG = PROMPT + " " + PROMPT                             # 87 -> 5 full pages

_WANT: dict = {}


def _want_tokens(prompt, max_tokens=8):
    from ray_tpu.serve.llm import LLMEngine

    key = (prompt, max_tokens)
    if key not in _WANT:
        off = LLMEngine(_cfg(prefix_cache_enabled=False), rng_seed=0)
        off.start()
        try:
            _WANT[key] = off.generate(prompt, max_tokens=max_tokens,
                                      temperature=0.0)["tokens"]
        finally:
            off.shutdown()
    return _WANT[key]


def _wait(pred, timeout=60.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return pred()


# ---------------------------------------------------------------------------
# unit: signal-driven scaling policy
# ---------------------------------------------------------------------------


def test_decide_signals_queue_fallback():
    """No signals = the original queue-length policy, reason-tagged."""
    from ray_tpu.serve.config import AutoscalingConfig

    asc = AutoscalingConfig(min_replicas=1, max_replicas=4,
                            target_ongoing_requests=2.0)
    assert asc.decide_signals(2, 8.0, {}) == (4, "queue_len")
    assert asc.decide_signals(2, 4.0, None) == (2, "steady")
    assert asc.decide_signals(3, 0.0, {}) == (1, "queue_idle")


def test_decide_signals_slo_upscale():
    """An SLO-violating window dominated by a scalable stage upscales one
    step even when raw queue depth sits under target; decode dominance
    (more replicas would not help) does not."""
    from ray_tpu.serve.config import AutoscalingConfig

    asc = AutoscalingConfig(min_replicas=1, max_replicas=4)
    sig = {"slo_violations": 3, "dominant_stage": "queue"}
    assert asc.decide_signals(2, 2.0, sig) == (3, "slo_queue")
    sig["dominant_stage"] = "prefill"
    assert asc.decide_signals(2, 2.0, sig) == (3, "slo_prefill")
    # capacity won't fix a decode-dominant tail
    sig["dominant_stage"] = "decode"
    assert asc.decide_signals(2, 2.0, sig)[1] != "slo_decode"
    # never past max_replicas (queue load steady at max, SLO pressing)
    sig["dominant_stage"] = "queue"
    assert asc.decide_signals(4, 8.0, sig) == (4, "steady")
    # zero violations = no SLO pressure (4.0 ongoing = steady at 2)
    assert asc.decide_signals(
        2, 4.0, {"slo_violations": 0, "dominant_stage": "queue"}) == \
        (2, "steady")
    off = AutoscalingConfig(slo_upscale_enabled=False)
    assert asc.decide_signals(2, 4.0, sig)[0] == 3
    assert off.decide_signals(2, 4.0, sig) == (2, "steady")


def test_decide_signals_heat_guard_blocks_downscale():
    """A broadly warm fleet refuses the queue-idle downscale; a cold one
    takes it. Guard disabled at 0."""
    from ray_tpu.serve.config import AutoscalingConfig

    asc = AutoscalingConfig(min_replicas=1, max_replicas=4,
                            heat_downscale_guard=0.5)
    warm = {"affinity_hit_share": 0.75}
    cold = {"affinity_hit_share": 0.25}
    assert asc.decide_signals(3, 0.0, warm) == (3, "heat_guard")
    assert asc.decide_signals(3, 0.0, cold) == (1, "queue_idle")
    off = AutoscalingConfig(min_replicas=1, max_replicas=4,
                            heat_downscale_guard=0.0)
    assert off.decide_signals(3, 0.0, warm) == (1, "queue_idle")


# ---------------------------------------------------------------------------
# unit: digest-chain registration (the warm-start allocator primitive)
# ---------------------------------------------------------------------------


def test_insert_digest_chain_registers_matchable_pages():
    from ray_tpu.serve.llm.kv_cache import PageAllocator

    alloc = PageAllocator(num_pages=16)
    pages = alloc.alloc(3)
    digs = ["aa" * 16, "bb" * 16, "cc" * 16]
    assert alloc.insert_digest_chain(digs, pages, [0, 1, 2]) == 3
    # registered under refcount 1; caller's free parks them cached
    alloc.free(pages)
    assert alloc.match_digest_chain(digs) == 3
    assert alloc.match_digest_chain(digs[:2] + ["dd" * 16]) == 2
    # duplicates and junk are skipped, not an error
    more = alloc.alloc(2)
    assert alloc.insert_digest_chain(
        ["aa" * 16, "not-hex"], more, [0, 1]) == 0
    alloc.free(more)
    # page 0 (trash page) can never be indexed
    assert alloc.insert_digest_chain(["ee" * 16], [0], [0]) == 0
    # the warm pages are evictable like any cached prefix
    assert alloc.cache_stats()["evictable_pages"] >= 3


# ---------------------------------------------------------------------------
# unit: router never regresses on a stale table
# ---------------------------------------------------------------------------


class _DeadController:
    """Controller stub whose RPCs always fail: the router's long-poll
    degrades and the test drives _apply_table directly."""

    class _M:
        def remote(self, *a, **k):
            raise RuntimeError("controller away")

    poll_routing_table = _M()
    get_routing_table = _M()


class _FakeReplica:
    def __init__(self, name):
        self._actor_id = name.encode()


def test_apply_table_ignores_stale_lower_version():
    """A late-delivered stale table (cold-start fetch racing the
    long-poll) must not resurrect a retired replica or hide a freshly
    published one."""
    from ray_tpu.serve.router import Router

    r = Router(_DeadController(), "app")
    try:
        r1, r2, r3 = (_FakeReplica("r1"), _FakeReplica("r2"),
                      _FakeReplica("r3"))
        r._apply_table({"d": ([r1, r2], 5, None)})
        assert {x._actor_id for x in r._sets["d"].replicas} == \
            {b"r1", b"r2"}
        # stale view from before r2 was published and r3 retired
        r._apply_table({"d": ([r1, r3], 4, None)})
        assert {x._actor_id for x in r._sets["d"].replicas} == \
            {b"r1", b"r2"}, "stale table regressed the replica set"
        assert r._sets["d"].version == 5
        # a genuinely newer table still applies
        r._apply_table({"d": ([r2], 6, None)})
        assert [x._actor_id for x in r._sets["d"].replicas] == [b"r2"]
        # a fresh controller's version-0 rebuild is allowed through
        r._apply_table({"d": ([r1], 0, None)})
        assert [x._actor_id for x in r._sets["d"].replicas] == [b"r1"]
    finally:
        r.stop()


# ---------------------------------------------------------------------------
# engine: cache-warm scale-up restores the fleet's chains before traffic
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def elastic_cluster(ray_start_module):
    yield ray_start_module


def test_warm_start_restores_tier_chains_token_identical(elastic_cluster):
    """Engine A spills a live chain into the tier; a FRESH engine B warm
    starts from the CP index and its first request is a prefix hit whose
    greedy output is token-identical to cold prefill."""
    from ray_tpu.serve.llm import LLMEngine

    want = _want_tokens(LONG, 8)
    cfg = _cfg(kv_tier_enabled=True)
    a = LLMEngine(cfg, rng_seed=0)
    a.start()
    b = None
    try:
        rid = a.submit(LONG, max_tokens=64, temperature=0.0)
        assert _wait(lambda: len(
            (a.request_progress(rid) or {}).get("generated") or ()) >= 2,
            timeout=120.0)
        assert a.spill_inflight() >= 5
        assert _wait(lambda: a.engine_stats()["spilled_pages"] >= 5)

        b = LLMEngine(cfg, rng_seed=0)
        b.start()
        res = b.warm_start()
        assert res["supported"] is True, res
        assert res["pages"] >= 5, res
        assert res["chains"] >= 1
        assert res["wire_bytes"] > 0
        st = b.engine_stats()
        assert st["warm_start_pages"] >= 5
        assert st["warm_start_ms"] > 0.0
        # the warm pages are a real prefix match for the first request,
        # and the decode over them is bit-identical to cold prefill
        out = b.generate(LONG, max_tokens=8, temperature=0.0)
        assert out["tokens"] == want, "warm-started decode diverged"
        st2 = b.engine_stats()
        assert st2["prefix_hit_tokens"] >= 5 * 16
        # idempotent-ish: a second warm start finds everything resident
        res2 = b.warm_start()
        assert res2["supported"] is True
        assert res2["pages"] == 0, "re-warm re-fetched resident chains"
        a.result(rid, timeout=180.0)
    finally:
        a.shutdown()
        if b is not None:
            b.shutdown()


def test_warm_start_off_paths():
    """Tier off or warm disabled = unsupported no-op (the controller
    then publishes the replica immediately)."""
    from ray_tpu.serve.llm import LLMEngine

    eng = LLMEngine(_cfg(), rng_seed=0)  # tier off
    try:
        assert eng.warm_start()["supported"] is False
    finally:
        eng.shutdown()
    eng = LLMEngine(_cfg(kv_tier_enabled=True, warm_start_enabled=False),
                    rng_seed=0)
    try:
        assert eng.warm_start()["supported"] is False
    finally:
        eng.shutdown()


# ---------------------------------------------------------------------------
# controller: warming gate + atomic publish
# ---------------------------------------------------------------------------


def test_warming_replica_invisible_until_atomic_publish(elastic_cluster):
    """A scale-up replica whose warm_start is slow stays OUT of the
    routing table (status shows it WARMING); when the warm lands, the
    replica and the version bump appear together — across every polled
    view, the version uniquely determines the replica set."""
    from ray_tpu import serve
    from ray_tpu.serve.controller import get_or_create_controller

    serve.shutdown()

    @serve.deployment(num_replicas=1, health_check_period_s=0.2)
    class SlowWarm:
        def __call__(self, x):
            return x

        def warm_start(self):
            time.sleep(2.0)
            return {"supported": True, "pages": 7, "chains": 1,
                    "wire_bytes": 512, "ms": 2000.0}

    serve.run(SlowWarm.bind(), name="el-warm", route_prefix="/el-warm")
    ctl = get_or_create_controller()
    full = "el-warm#SlowWarm"
    try:
        table0 = ray_tpu.get(ctl.get_routing_table.remote("el-warm"),
                             timeout=10.0)
        v0 = table0["SlowWarm"][1]
        n0 = len(table0["SlowWarm"][0])
        assert n0 == 1

        ray_tpu.get(ctl.set_target_replicas.remote(
            "el-warm", target=2, reason="test"), timeout=10.0)

        # poll continuously through the scale-up: the invariant is that a
        # version-v0 table NEVER contains 2 replicas, and any 2-replica
        # table carries a newer version (atomic publish)
        seen_warming = False
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            st = ray_tpu.get(ctl.status.remote(), timeout=10.0)[full]
            table = ray_tpu.get(ctl.get_routing_table.remote("el-warm"),
                                timeout=10.0)["SlowWarm"]
            if len(table[0]) >= 2:
                assert table[1] > v0, \
                    "2-replica table shipped under the old version"
                break
            assert table[1] == v0 and len(table[0]) == n0, \
                f"table changed without the new replica: {table[1]}"
            if st["warming"]:
                seen_warming = True
                assert len(table[0]) == 1, \
                    "warming replica leaked into the routing table"
            time.sleep(0.05)
        else:
            pytest.fail("scale-up never published the warmed replica")
        assert seen_warming, "replica never passed through WARMING"

        # the warm economy landed in the controller's books
        det = ray_tpu.get(ctl.detailed_status.remote(), timeout=30.0)[full]
        assert det["warm"]["replicas_warmed"] >= 1
        assert det["warm"]["pages"] >= 7
        assert det["scale_counters"].get("test") == 1
        assert any(d["reason"] == "test" and d["to"] == 2
                   for d in det["scale_decisions"])
    finally:
        serve.shutdown()


# ---------------------------------------------------------------------------
# controller: kill-free downscale of a BUSY replica
# ---------------------------------------------------------------------------


def _read_sse(base, path, payload, rid, events, done):
    try:
        req = urllib.request.Request(
            f"{base}{path}", data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json",
                     "X-Request-Id": rid})
        with urllib.request.urlopen(req, timeout=120.0) as r:
            hdr = dict(r.headers)
            for raw in r:
                line = raw.decode().strip()
                if line.startswith("event: "):
                    events.append(("event", line[len("event: "):]))
                elif line.startswith("data: "):
                    body = line[len("data: "):]
                    if body == "[DONE]":
                        break
                    events.append(("data", json.loads(body)))
        done.append(hdr)
    except Exception as e:  # noqa: BLE001 — the test asserts on this
        done.append(e)


@pytest.mark.slow
def test_downscale_busy_replica_completes_streams(elastic_cluster):
    """Drain-based downscale with in-flight streams on BOTH replicas:
    the retired replica finishes its streams before the kill — every
    token exactly once, zero resumes, zero dropped SSE frames — and the
    fleet lands on the new target."""
    from ray_tpu import serve
    from ray_tpu.serve.controller import get_or_create_controller
    from ray_tpu.util.chaos import FaultSchedule

    serve.shutdown()
    n_tokens = 16

    @serve.deployment(num_replicas=2, health_check_period_s=0.2,
                      graceful_shutdown_timeout_s=30.0)
    class Streamer:
        def __init__(self):
            self._uid = uuid.uuid4().hex[:8]

        def handle_http(self, path, method, payload):
            if isinstance(payload, dict) and payload.get("stream"):
                return self._gen(payload)
            return {"uid": self._uid}

        async def _gen(self, payload):
            import asyncio
            for i in range(int(payload.get("max_tokens") or n_tokens)):
                yield {"choices": [{"text": f"t{i};", "index": 0,
                                    "finish_reason": None}],
                       "rep": self._uid}
                await asyncio.sleep(0.15)
            yield {"choices": [{"text": "", "index": 0,
                                "finish_reason": "stop"}]}

    serve.run(Streamer.bind(), name="el-down", route_prefix="/el")
    proxy = serve.start_http_proxy(port=0)
    base = f"http://127.0.0.1:{proxy.port}"
    ctl = get_or_create_controller()
    full = "el-down#Streamer"
    streams = []
    try:
        # saturate both replicas (pow-2 splits two concurrent streams)
        for i in range(4):
            events, done = [], []
            t = threading.Thread(
                target=_read_sse,
                args=(base, "/el/stream",
                      {"stream": True, "max_tokens": n_tokens},
                      f"eldown{i:04d}", events, done), daemon=True)
            t.start()
            streams.append((t, events, done))
        assert _wait(lambda: all(
            sum(1 for k, v in list(ev) if k == "data") >= 2
            for _, ev, _d in streams), timeout=60.0)

        # mid-stream downscale through the chaos event (satellite 2)
        sched = FaultSchedule(None, [
            (0.0, "replica_scale", {"app": "el-down",
                                    "deployment": "Streamer",
                                    "target": 1})])
        sched.start()
        report = sched.join(timeout=30.0)
        assert report and report[0]["ok"], report

        for t, _ev, _d in streams:
            t.join(timeout=120.0)
            assert not t.is_alive(), "stream never finished under drain"
        for _t, events, done in streams:
            assert done and not isinstance(done[0], Exception), \
                f"stream failed during downscale: {done}"
            texts = [c["choices"][0]["text"] for k, c in events
                     if k == "data" and c.get("choices")]
            assert "".join(texts) == \
                "".join(f"t{i};" for i in range(n_tokens)), \
                f"downscale dropped/duplicated frames: {texts}"
        # kill-free: the victim drained, nothing needed to resume
        assert proxy.stats.get("stream_resumes", 0) == 0

        assert _wait(lambda: ray_tpu.get(
            ctl.status.remote(), timeout=10.0)[full]["replicas"] == 1,
            timeout=60.0)
        st = ray_tpu.get(ctl.status.remote(), timeout=10.0)[full]
        assert st["target"] == 1 and st["draining"] == 0
        det = ray_tpu.get(ctl.detailed_status.remote(), timeout=30.0)[full]
        assert det["scale_counters"].get("chaos") == 1
    finally:
        serve.shutdown()
