"""Serve public API: @deployment, run, shutdown, status, handles.

TPU-native analog of the reference's serve API
(/root/reference/python/ray/serve/api.py — @serve.deployment:333,
serve.run:685; _private/client.py deploy_applications). Applications are
graphs of deployments built with `.bind()` (the reference's DAG builder);
`serve.run` ships them to the controller which reconciles replica actors.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Optional

import cloudpickle

import ray_tpu
from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig
from ray_tpu.serve.controller import get_or_create_controller
from ray_tpu.serve.handle import DeploymentHandle, _reset_routers

_lock = threading.Lock()
_proxy = None  # (HTTPProxy, port) — primary ingress
# Multi-proxy ingress (ISSUE 17): additional HTTPProxy instances behind
# the same fleet (start_http_proxies). They share ONE router map — one
# controller long-poll per app for the whole ingress tier — and each
# serves its own /-/stats. All are stopped by shutdown().
_extra_proxies: list = []
_shared_routers: dict = {}


class Application:
    """A bound deployment graph node (reference: Application from
    Deployment.bind)."""

    def __init__(self, deployment: "Deployment", init_args, init_kwargs):
        self.deployment = deployment
        self.init_args = init_args
        self.init_kwargs = init_kwargs

    def _collect(self, out: list, seen: set) -> None:
        """Topo-collect all deployments reachable through bound args."""
        for arg in list(self.init_args) + list(self.init_kwargs.values()):
            if isinstance(arg, Application) and id(arg) not in seen:
                seen.add(id(arg))
                arg._collect(out, seen)
        if self not in out:
            out.append(self)


class Deployment:
    def __init__(self, func_or_class, name: str, config: DeploymentConfig,
                 route_prefix: Optional[str] = "/"):
        self.func_or_class = func_or_class
        self.name = name
        self.config = config
        self.route_prefix = route_prefix

    def options(self, *, name: Optional[str] = None,
                num_replicas: Optional[Any] = None,
                max_ongoing_requests: Optional[int] = None,
                user_config: Any = None,
                autoscaling_config: Optional[dict | AutoscalingConfig] = None,
                route_prefix: Optional[str] = "__unset__",
                ray_actor_options: Optional[dict] = None,
                health_check_period_s: Optional[float] = None,
                health_check_failure_threshold: Optional[int] = None,
                request_timeout_s: Optional[float] = None,
                slo_ttft_p99_ms: Optional[float] = None,
                slo_e2e_p99_ms: Optional[float] = None,
                slo_sample_rate: Optional[float] = None,
                graceful_shutdown_timeout_s: Optional[float] = None) -> "Deployment":
        import copy
        cfg = copy.deepcopy(self.config)
        if num_replicas is not None:
            if num_replicas == "auto":
                cfg.autoscaling_config = cfg.autoscaling_config or AutoscalingConfig()
            else:
                cfg.num_replicas = int(num_replicas)
        if max_ongoing_requests is not None:
            cfg.max_ongoing_requests = max_ongoing_requests
        if user_config is not None:
            cfg.user_config = user_config
        if autoscaling_config is not None:
            if isinstance(autoscaling_config, dict):
                autoscaling_config = AutoscalingConfig(**autoscaling_config)
            cfg.autoscaling_config = autoscaling_config
        if ray_actor_options is not None:
            cfg.ray_actor_options = ray_actor_options
        if health_check_period_s is not None:
            cfg.health_check_period_s = health_check_period_s
        if health_check_failure_threshold is not None:
            cfg.health_check_failure_threshold = health_check_failure_threshold
        if request_timeout_s is not None:
            cfg.request_timeout_s = request_timeout_s
        if slo_ttft_p99_ms is not None:
            cfg.slo_ttft_p99_ms = slo_ttft_p99_ms
        if slo_e2e_p99_ms is not None:
            cfg.slo_e2e_p99_ms = slo_e2e_p99_ms
        if slo_sample_rate is not None:
            cfg.slo_sample_rate = slo_sample_rate
        if graceful_shutdown_timeout_s is not None:
            cfg.graceful_shutdown_timeout_s = graceful_shutdown_timeout_s
        return Deployment(
            self.func_or_class, name or self.name, cfg,
            self.route_prefix if route_prefix == "__unset__" else route_prefix)

    def bind(self, *args, **kwargs) -> Application:
        return Application(self, args, kwargs)

    def __call__(self, *a, **k):
        raise RuntimeError(
            "deployments are not directly callable; use .bind() + serve.run "
            "then handle.remote()")


def deployment(_func_or_class=None, *, name: Optional[str] = None,
               num_replicas: Any = None, max_ongoing_requests: int = 100,
               user_config: Any = None,
               autoscaling_config: Optional[dict | AutoscalingConfig] = None,
               ray_actor_options: Optional[dict] = None,
               health_check_period_s: float = 2.0,
               health_check_timeout_s: float = 30.0,
               health_check_failure_threshold: int = 3,
               request_timeout_s: Optional[float] = None,
               slo_ttft_p99_ms: Optional[float] = None,
               slo_e2e_p99_ms: Optional[float] = None,
               slo_sample_rate: float = 0.01,
               graceful_shutdown_timeout_s: float = 20.0):
    """@serve.deployment decorator (reference api.py:333)."""

    def decorate(obj):
        cfg = DeploymentConfig(
            max_ongoing_requests=max_ongoing_requests,
            user_config=user_config,
            health_check_period_s=health_check_period_s,
            health_check_timeout_s=health_check_timeout_s,
            health_check_failure_threshold=health_check_failure_threshold,
            request_timeout_s=request_timeout_s,
            slo_ttft_p99_ms=slo_ttft_p99_ms,
            slo_e2e_p99_ms=slo_e2e_p99_ms,
            slo_sample_rate=slo_sample_rate,
            graceful_shutdown_timeout_s=graceful_shutdown_timeout_s,
            ray_actor_options=ray_actor_options or {})
        if num_replicas == "auto":
            cfg.autoscaling_config = AutoscalingConfig()
        elif num_replicas is not None:
            cfg.num_replicas = int(num_replicas)
        if autoscaling_config is not None:
            cfg.autoscaling_config = (
                AutoscalingConfig(**autoscaling_config)
                if isinstance(autoscaling_config, dict) else autoscaling_config)
        return Deployment(obj, name or obj.__name__, cfg)

    if _func_or_class is not None:
        return decorate(_func_or_class)
    return decorate


def run(app: Application, *, name: str = "default",
        route_prefix: Optional[str] = "/", blocking: bool = False,
        _local_testing_mode: bool = False) -> DeploymentHandle:
    """Deploy an application; returns a handle to the ingress deployment
    (reference serve.run api.py:685). With ``_local_testing_mode`` the
    whole application runs IN-PROCESS — no cluster, no controller, no
    replica actors (reference _private/local_testing_mode.py) — for unit
    tests and notebooks."""
    if _local_testing_mode:
        from ray_tpu.serve.local_testing import run_local
        return run_local(app, name)
    if not ray_tpu.is_initialized():
        ray_tpu.init()
    controller = get_or_create_controller()

    ordered: list[Application] = []
    app._collect(ordered, set())
    ingress = ordered[-1]

    specs = []
    for node in ordered:
        dep = node.deployment
        init_args, handle_args = [], []
        # bound sub-applications become handles at construction time
        def conv(v):
            if isinstance(v, Application):
                return DeploymentHandle(v.deployment.name, name)
            return v
        args = tuple(conv(a) for a in node.init_args)
        kwargs = {k: conv(v) for k, v in node.init_kwargs.items()}
        specs.append({
            "name": dep.name,
            "serialized_cls": cloudpickle.dumps(dep.func_or_class),
            "init_args": args, "init_kwargs": kwargs,
            "config": dep.config,
            "route_prefix": route_prefix if node is ingress else None,
            "is_ingress": node is ingress,
        })
    ok = ray_tpu.get(controller.deploy_application.remote(name, specs),
                     timeout=120.0)
    if not ok:
        raise RuntimeError(f"application {name!r} failed to deploy")
    _reset_routers()
    return DeploymentHandle(ingress.deployment.name, name)


def get_app_handle(name: str = "default") -> DeploymentHandle:
    controller = get_or_create_controller()
    routes = ray_tpu.get(controller.get_http_routes.remote(), timeout=10.0)
    for prefix, (app, dep) in routes.items():
        if app == name:
            return DeploymentHandle(dep, app)
    st = ray_tpu.get(controller.status.remote(), timeout=10.0)
    for full, info in st.items():
        if info["app"] == name:
            return DeploymentHandle(full.split("#", 1)[1], name)
    raise ValueError(f"no application named {name!r}")


def get_deployment_handle(deployment_name: str,
                          app_name: str = "default") -> DeploymentHandle:
    return DeploymentHandle(deployment_name, app_name)


def status() -> dict:
    controller = get_or_create_controller()
    return ray_tpu.get(controller.status.remote(), timeout=10.0)


def detailed_status() -> dict:
    """Per-deployment status incl. replica details and `latency_ms`
    p50/p95/p99 from the merged replica-processing histogram."""
    controller = get_or_create_controller()
    return ray_tpu.get(controller.detailed_status.remote(), timeout=30.0)


def delete(name: str = "default") -> None:
    controller = get_or_create_controller()
    ray_tpu.get(controller.delete_application.remote(name), timeout=60.0)
    _reset_routers()


def shutdown() -> None:
    global _proxy
    with _lock:
        if _proxy is not None:
            _proxy[0].stop()
            _proxy = None
        for p in _extra_proxies:
            try:
                p.stop()
            except Exception:  # noqa: BLE001 — already down
                pass
        _extra_proxies.clear()
        _shared_routers.clear()
    try:
        controller = ray_tpu.get_actor("_serve_controller", timeout=0.2)
        ray_tpu.get(controller.shutdown.remote(), timeout=30.0)
        ray_tpu.kill(controller)
    except Exception:  # noqa: BLE001 - not running
        pass
    from ray_tpu.serve.grpc_ingress import _reset_grpc_proxy
    _reset_grpc_proxy()
    _reset_routers()


def start_http_proxy(host: str = "127.0.0.1", port: int = 8000,
                     router_config=None):
    """Start the node's HTTP ingress (reference: one HTTPProxy actor per
    node, proxy.py:706; here one aiohttp server in the driver process).
    router_config overrides the proxy's RouterConfig (e.g. the affinity
    A/B in bench_serve.py); ignored if a proxy is already running."""
    global _proxy
    from ray_tpu.serve.proxy import HTTPProxy
    with _lock:
        if _proxy is None:
            p = HTTPProxy(get_or_create_controller(), host, port,
                          router_config=router_config)
            p.start()
            _proxy = (p, port)
        return _proxy[0]


def start_http_proxies(count: int, host: str = "127.0.0.1",
                       port: int = 8000, router_config=None) -> list:
    """Multi-proxy ingress (ISSUE 17): `count` HTTPProxy instances behind
    the SAME fleet. The first takes `port` (or joins an already-running
    primary), the rest take `port+1, port+2, ...` — pass ``port=0`` for
    OS-assigned ports on all of them. Every proxy shares one router map:
    one controller long-poll per app for the whole ingress tier, one
    shared retry budget and circuit breaker per app, while each proxy
    answers its own `/-/stats` (tagged with its name/port). Put any
    TCP-level balancer — or a client-side port list — in front; the
    proxies are stateless beyond their shared routing cache. Returns the
    proxy list (index 0 = primary). Idempotent: already-running proxies
    are reused, only the missing tail is started."""
    global _proxy
    from ray_tpu.serve.proxy import HTTPProxy
    out = []
    with _lock:
        controller = get_or_create_controller()
        if _proxy is None:
            p = HTTPProxy(controller, host, port,
                          router_config=router_config, name="proxy-0",
                          shared_routers=_shared_routers)
            p.start()
            _proxy = (p, p.port)
        out.append(_proxy[0])
        out.extend(_extra_proxies)
        while len(out) < max(1, int(count)):
            i = len(out)
            p = HTTPProxy(controller, host,
                          0 if port == 0 else port + i,
                          router_config=router_config, name=f"proxy-{i}",
                          shared_routers=_shared_routers)
            p.start()
            _extra_proxies.append(p)
            out.append(p)
    return out
