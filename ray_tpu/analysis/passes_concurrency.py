"""graftlint concurrency passes: lock-discipline and rpc-ack.

These target the two bug classes PR 7's review and PR 8 shipped fixes
for — disk/ref I/O held under the ``KVTierStore`` lock, and one-way
``notify()`` on the metrics/trace flusher paths where the backlog never
engaged because a half-closed socket swallows one-way writes without an
error.
"""

from __future__ import annotations

import ast

from ray_tpu.analysis import lockmodel
from ray_tpu.analysis.core import Finding, ModuleSource, Pass, register


def _def_line(fn: ast.AST) -> int:
    return getattr(fn, "lineno", 1)


@register
class LockDisciplinePass(Pass):
    """Blocking operations reachable while a threading lock is held.

    Flags RPC calls (`.call` / `.call_with_retry` / `.notify`), socket /
    pipe sends+recvs, file ``open()``, ``subprocess.*``, ``time.sleep``
    and Event-style ``.wait`` executed inside ``with self._lock:`` (or
    between ``acquire()``/``release()``), directly or via a same-class
    method that may block. Condition-variable waits/notifies on the held
    lock are the sanctioned pattern and exempt.
    """

    id = "lock-discipline"
    title = "blocking operation while holding a lock"
    hint = ("snapshot state under the lock, do the blocking work outside "
            "it (see KVTierStore._make_room), or pragma "
            "`# graftlint: disable=lock-discipline` with a justification")

    def run(self, module: ModuleSource) -> list:
        findings: list = []
        # class-level models first (method map + may-block fixpoint)
        models: dict[ast.ClassDef, lockmodel.ClassModel] = {}
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ClassDef):
                models[node] = lockmodel.ClassModel(node)

        from ray_tpu.analysis.core import iter_functions
        for fn, qualname, cls in iter_functions(module.tree):
            model = models.get(cls)

            def on_violation(call, tag, desc, lock, _fn=fn, _q=qualname):
                findings.append(self.emit(
                    module, call, _q,
                    f"{desc} while holding {lock}", tag,
                    extra_pragma_lines=(_def_line(_fn),)))

            lockmodel.LockWalker(model, getattr(fn, "name", ""),
                                 on_violation).walk_function(fn)
        return [f for f in findings if f is not None]


@register
class RpcAckPass(Pass):
    """One-way ``notify()`` RPC on paths that may depend on delivery.

    ``RpcClient.notify`` writes into the socket and returns — a write
    into a half-closed connection vanishes in the kernel buffer with no
    error (PR 8's metrics-backlog bug). Every RPC-shaped ``X.notify(
    "method", ...)`` call is flagged unless the site carries an explicit
    ``# graftlint: fire-and-forget`` pragma asserting the protocol
    tolerates silent loss (heartbeat self-heal, pubsub long-poll
    recovery, observability sinks), or is baselined with a written
    justification.
    """

    id = "rpc-ack"
    title = "unacknowledged one-way RPC"
    hint = ("use an acknowledged call() with a timeout when callers "
            "depend on delivery, or annotate the site with "
            "`# graftlint: fire-and-forget` and say why loss is safe")

    def run(self, module: ModuleSource) -> list:
        findings: list[Finding] = []
        from ray_tpu.analysis.core import iter_functions
        fn_spans = [(fn, q) for fn, q, _ in iter_functions(module.tree)]

        def enclosing(call) -> tuple:
            best = None
            for fn, q in fn_spans:
                if fn.lineno <= call.lineno <= (fn.end_lineno or fn.lineno):
                    if best is None or fn.lineno > best[0].lineno:
                        best = (fn, q)
            return best or (None, "<module>")

        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "notify"):
                continue
            # RPC shape: first positional arg is the method-name string.
            # Condition.notify() has no args; Condition.notify(n) has a
            # non-string arg.
            if not node.args or not (isinstance(node.args[0], ast.Constant)
                                     and isinstance(node.args[0].value, str)):
                continue
            method = node.args[0].value
            fn, qualname = enclosing(node)
            findings.append(self.emit(
                module, node, qualname,
                f"one-way notify({method!r}) — delivery is unacknowledged "
                f"and silently lost on a half-closed socket",
                f"notify:{method}",
                extra_pragma_lines=(_def_line(fn),) if fn is not None else ()))
        return [f for f in findings if f is not None]
