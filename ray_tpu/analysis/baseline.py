"""graftlint findings baseline: load / regenerate / drift-check.

The committed baseline (``GRAFTLINT_BASELINE.json`` at the repo root)
is the set of accepted findings, each with a one-line written
justification. The tier-1 gate compares a fresh full-package run against
it EXACTLY: a new un-baselined finding fails, and so does a stale entry
whose finding no longer exists (a fixed finding must leave the baseline
with the fix, or the file rots into an allowlist nobody trusts).

Keys are line-number-free (``pass::path::symbol::tag``) so unrelated
edits don't churn the file; regeneration (``ray-tpu lint --baseline``)
is deterministic — sorted keys, existing justifications preserved, new
entries get an empty justification that a reviewer must fill.
"""

from __future__ import annotations

import json
import os
from typing import Iterable, Optional

from ray_tpu.analysis.core import Finding, repo_root

BASELINE_NAME = "GRAFTLINT_BASELINE.json"
_VERSION = 1


def baseline_path(explicit: Optional[str] = None) -> str:
    return explicit or os.path.join(repo_root(), BASELINE_NAME)


def load(path: Optional[str] = None) -> dict[str, str]:
    """{finding_key: justification}; empty when no baseline exists."""
    p = baseline_path(path)
    if not os.path.exists(p):
        return {}
    with open(p, encoding="utf-8") as f:
        doc = json.load(f)
    entries = doc.get("entries", {})
    if not isinstance(entries, dict):
        raise ValueError(f"{p}: entries must be a key->justification map")
    return dict(entries)


def save(findings: Iterable[Finding], path: Optional[str] = None,
         previous: Optional[dict[str, str]] = None) -> str:
    """Write the baseline for ``findings``, keeping justifications of
    surviving entries from ``previous`` (default: the current file)."""
    p = baseline_path(path)
    if previous is None:
        previous = load(p) if os.path.exists(p) else {}
    entries = {f.key: previous.get(f.key, "") for f in findings}
    doc = {
        "version": _VERSION,
        "tool": "graftlint (ray-tpu lint --baseline)",
        "note": ("accepted findings; each entry carries a one-line "
                 "justification. The tier-1 gate fails on new findings "
                 "AND on stale entries — fixes must prune their entry."),
        "entries": {k: entries[k] for k in sorted(entries)},
    }
    with open(p, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=False)
        f.write("\n")
    return p


def diff(findings: Iterable[Finding], path: Optional[str] = None,
         ) -> tuple[list[Finding], list[str]]:
    """(new_findings, stale_keys) of ``findings`` vs the baseline."""
    base = load(path)
    found_keys = {f.key for f in findings}
    new = [f for f in findings if f.key not in base]
    stale = sorted(k for k in base if k not in found_keys)
    return new, stale
