"""Datasources: where blocks come from / go to.

TPU-native analog of the reference's datasource layer
(/root/reference/python/ray/data/datasource/datasource.py — Datasource +
ReadTask; _internal/datasource/* for the ~40 concrete impls). Each
`ReadTask` is a zero-arg callable returning an iterator of Blocks, executed
remotely by the Read physical operator; `estimate` powers parallelism
heuristics. In-tree impls cover the formats the test/bench suites need:
range, items, numpy, parquet, csv, json(l), binary, images, text.
"""

from __future__ import annotations

import dataclasses
import glob as globlib
import os
from typing import Any, Callable, Iterable, Iterator, Optional

import numpy as np
import pyarrow as pa

from ray_tpu.data.block import Block, BlockAccessor, block_from_dict, block_from_items


@dataclasses.dataclass
class ReadTask:
    """A unit of parallel read: runs remotely, yields blocks."""

    read_fn: Callable[[], Iterable[Block]]
    num_rows: Optional[int] = None
    size_bytes: Optional[int] = None
    input_files: list = dataclasses.field(default_factory=list)

    def __call__(self) -> Iterable[Block]:
        return self.read_fn()


class Datasource:
    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        raise NotImplementedError

    def estimate_inmemory_data_size(self) -> Optional[int]:
        return None

    @property
    def name(self) -> str:
        return type(self).__name__.replace("Datasource", "")


class RangeDatasource(Datasource):
    """ray_tpu.data.range(n) (reference: range_datasource)."""

    def __init__(self, n: int, column: str = "id"):
        self._n = n
        self._column = column

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        n, col = self._n, self._column
        parallelism = max(1, min(parallelism, n or 1))
        chunk = -(-n // parallelism) if n else 0
        tasks = []
        for start in range(0, n, chunk) if n else []:
            end = min(start + chunk, n)

            def make(s=start, e=end):
                def read():
                    yield block_from_dict(
                        {col: np.arange(s, e, dtype=np.int64)})
                return read

            tasks.append(ReadTask(make(), num_rows=end - start,
                                  size_bytes=(end - start) * 8))
        return tasks or [ReadTask(lambda: [block_from_dict({col: np.array([], np.int64)})],
                                  num_rows=0, size_bytes=0)]

    def estimate_inmemory_data_size(self):
        return self._n * 8


class ItemsDatasource(Datasource):
    def __init__(self, items: list):
        self._items = list(items)

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        items = self._items
        if not items:
            return [ReadTask(lambda: [block_from_items([])], num_rows=0)]
        parallelism = max(1, min(parallelism, len(items)))
        chunk = -(-len(items) // parallelism)
        tasks = []
        for start in range(0, len(items), chunk):
            part = items[start:start + chunk]

            def make(p=part):
                return lambda: [block_from_items(p)]

            tasks.append(ReadTask(make(), num_rows=len(part)))
        return tasks


class NumpyDatasource(Datasource):
    def __init__(self, arr: np.ndarray, column: str = "data"):
        self._arr = arr
        self._column = column

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        arr, col = self._arr, self._column
        parallelism = max(1, min(parallelism, len(arr) or 1))
        chunks = np.array_split(np.arange(len(arr)), parallelism)
        tasks = []
        for idx in chunks:
            if len(idx) == 0:
                continue
            part = arr[idx[0]:idx[-1] + 1]

            def make(p=part):
                return lambda: [block_from_dict({col: p})]

            tasks.append(ReadTask(make(), num_rows=len(part),
                                  size_bytes=part.nbytes))
        return tasks


def _expand_paths(paths) -> list[str]:
    if isinstance(paths, (str, os.PathLike)):
        paths = [paths]
    out: list[str] = []
    for p in paths:
        p = os.fspath(p)
        if os.path.isdir(p):
            for root, _, files in os.walk(p):
                out.extend(os.path.join(root, f) for f in sorted(files)
                           if not f.startswith("."))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(globlib.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files matched {paths!r}")
    return out


class FileDatasource(Datasource):
    """Base for per-file readers; one ReadTask per file group."""

    def __init__(self, paths, **reader_kwargs):
        self._paths = _expand_paths(paths)
        self._kwargs = reader_kwargs

    def _read_file(self, path: str) -> Iterator[Block]:
        raise NotImplementedError

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        parallelism = max(1, min(parallelism, len(self._paths)))
        groups = np.array_split(np.array(self._paths, dtype=object), parallelism)
        tasks = []
        for group in groups:
            files = [str(f) for f in group]
            if not files:
                continue

            def make(fs=files):
                def read():
                    for f in fs:
                        yield from self._read_file(f)
                return read

            size = sum(os.path.getsize(f) for f in files if os.path.exists(f))
            tasks.append(ReadTask(make(), size_bytes=size, input_files=files))
        return tasks

    def estimate_inmemory_data_size(self):
        return sum(os.path.getsize(f) for f in self._paths if os.path.exists(f))


class ParquetDatasource(FileDatasource):
    def _read_file(self, path: str) -> Iterator[Block]:
        import pyarrow.parquet as pq
        columns = self._kwargs.get("columns")
        yield pq.read_table(path, columns=columns)


class CSVDatasource(FileDatasource):
    def _read_file(self, path: str) -> Iterator[Block]:
        from pyarrow import csv as pacsv
        yield pacsv.read_csv(path)


class JSONDatasource(FileDatasource):
    def _read_file(self, path: str) -> Iterator[Block]:
        import json as jsonlib
        rows = []
        with open(path) as f:
            head = f.read(1)
            f.seek(0)
            if head == "[":
                rows = jsonlib.load(f)
            else:  # jsonl
                rows = [jsonlib.loads(line) for line in f if line.strip()]
        from ray_tpu.data.block import block_from_rows
        yield block_from_rows(rows)


class BinaryDatasource(FileDatasource):
    def _read_file(self, path: str) -> Iterator[Block]:
        with open(path, "rb") as f:
            data = f.read()
        yield block_from_dict({"bytes": [data], "path": [path]})


class TextDatasource(FileDatasource):
    def _read_file(self, path: str) -> Iterator[Block]:
        with open(path) as f:
            lines = [ln.rstrip("\n") for ln in f]
        yield block_from_dict({"text": lines})


class ImageDatasource(FileDatasource):
    """read_images (reference: _internal/datasource/image_datasource.py);
    decodes via PIL to HWC uint8 tensor columns."""

    def _read_file(self, path: str) -> Iterator[Block]:
        from PIL import Image
        size = self._kwargs.get("size")
        mode = self._kwargs.get("mode", "RGB")
        img = Image.open(path).convert(mode)
        if size is not None:
            img = img.resize(tuple(reversed(size)))
        arr = np.asarray(img)
        yield block_from_dict({"image": arr[None, ...], "path": [path]})


class WebDatasetDatasource(FileDatasource):
    """WebDataset shard reader (reference: read_api.py:2101
    read_webdataset): each shard is a tar whose members group into samples
    by basename — ``0001.jpg`` + ``0001.json`` + ``0001.cls`` form one row
    with columns keyed by extension, plus ``__key__``. One ReadTask per
    shard, the format's natural parallel unit."""

    def _read_file(self, path: str) -> Iterator[Block]:
        import json as jsonlib
        import tarfile

        from ray_tpu.data.block import block_from_rows
        samples: dict[str, dict] = {}
        order: list[str] = []
        with tarfile.open(path) as tf:
            for member in tf:
                if not member.isfile():
                    continue
                # key = path with the BASENAME's extension stripped: tar
                # members like './0001.jpg' or 'v1.0/0001.jpg' must not
                # split at the first dot of the full path (that would
                # collapse whole shards into one corrupted sample)
                name = member.name
                if name.startswith("./"):
                    name = name[2:]
                dirpart, _, fname = name.rpartition("/")
                stem, dot, ext = fname.partition(".")
                if not dot:
                    stem, ext = fname, "bin"
                base = f"{dirpart}/{stem}" if dirpart else stem
                data = tf.extractfile(member).read()
                if ext in ("json",):
                    value: Any = jsonlib.loads(data)
                elif ext in ("txt", "text", "cls"):
                    value = data.decode("utf-8").strip()
                else:
                    value = data  # images etc. stay bytes (decode is a map)
                if base not in samples:
                    samples[base] = {"__key__": base}
                    order.append(base)
                samples[base][ext] = value
        yield block_from_rows([samples[k] for k in order])


class SQLDatasource(Datasource):
    """SQL reader (reference: read_api read_sql / _internal/datasource/
    sql_datasource.py): ``connection_factory`` is a zero-arg callable
    returning a DB-API connection (shipped to the read task, so the
    connection is opened WHERE the read runs, never pickled)."""

    def __init__(self, sql: str, connection_factory: Callable[[], Any],
                 parallelism_column: Optional[str] = None):
        self._sql = sql
        self._factory = connection_factory
        self._mod_column = parallelism_column

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        sql, factory = self._sql, self._factory
        col = self._mod_column
        if not col or parallelism <= 1:
            def read():
                yield _sql_to_block(factory, sql, ())
            return [ReadTask(read)]
        # partition by hash-mod on a column: each task reads one residue
        # class (the reference shards with LIMIT/OFFSET or a partition
        # column the same way). The residues are INLINED, not bound
        # parameters — they are internally generated ints, and paramstyles
        # differ across DB-API drivers ('?' vs '%s'). Shard 0 also takes
        # NULL keys (NULL % n is NULL: not-true in every residue class —
        # without this, NULL-keyed rows would land in NO shard).
        tasks = []
        for shard in range(parallelism):
            null_arm = f" OR ({col}) IS NULL" if shard == 0 else ""
            # double-mod: SQL % preserves the dividend's sign, so negative
            # keys would land in NO residue class. The derived table needs
            # an alias (PostgreSQL/MySQL reject bare subqueries in FROM).
            n = int(parallelism)
            q = (f"SELECT * FROM ({sql}) AS _src WHERE "
                 f"((({col}) % {n}) + {n}) % {n} = {int(shard)}{null_arm}")

            def make(query=q):
                def read():
                    yield _sql_to_block(factory, query, ())
                return read
            tasks.append(ReadTask(make()))
        return tasks


def _sql_to_block(factory, sql: str, params: tuple) -> Block:
    conn = factory()
    try:
        cur = conn.cursor()
        if params:
            cur.execute(sql, params)
        else:
            cur.execute(sql)
        names = [d[0] for d in cur.description]
        rows = cur.fetchall()
    finally:
        conn.close()
    from ray_tpu.data.block import block_from_rows
    return block_from_rows([dict(zip(names, r)) for r in rows])


class TFRecordsDatasource(FileDatasource):
    """Minimal TFRecord reader (uncompressed) — parses tf.train.Example
    features into columns (reference: tfrecords_datasource.py). No TF
    dependency: the record framing + Example proto are decoded by hand."""

    def _read_file(self, path: str) -> Iterator[Block]:
        rows = [_parse_example(rec) for rec in _iter_tfrecords(path)]
        from ray_tpu.data.block import block_from_rows
        yield block_from_rows(rows)


def _iter_tfrecords(path: str) -> Iterator[bytes]:
    import struct
    with open(path, "rb") as f:
        while True:
            header = f.read(8)
            if len(header) < 8:
                return
            (length,) = struct.unpack("<Q", header)
            f.read(4)  # length crc
            data = f.read(length)
            f.read(4)  # data crc
            yield data


def _read_varint(buf: bytes, pos: int) -> tuple[int, int]:
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _parse_example(data: bytes) -> dict:
    """Parse the tf.train.Example wire format (features→map<string,Feature>)."""
    # Example { Features features = 1 }; Features { map<string, Feature> }
    out: dict[str, Any] = {}
    pos = 0
    while pos < len(data):
        tag, pos = _read_varint(data, pos)
        field, wire = tag >> 3, tag & 7
        if wire != 2:
            raise ValueError("unexpected wire type in Example")
        length, pos = _read_varint(data, pos)
        payload = data[pos:pos + length]
        pos += length
        if field == 1:  # features
            _parse_features(payload, out)
    return out


def _parse_features(data: bytes, out: dict) -> None:
    pos = 0
    while pos < len(data):
        tag, pos = _read_varint(data, pos)
        length, pos = _read_varint(data, pos)
        entry = data[pos:pos + length]
        pos += length
        # map entry: key=1 (string), value=2 (Feature)
        epos, key, feat = 0, None, None
        while epos < len(entry):
            etag, epos = _read_varint(entry, epos)
            elen, epos = _read_varint(entry, epos)
            epayload = entry[epos:epos + elen]
            epos += elen
            if etag >> 3 == 1:
                key = epayload.decode()
            else:
                feat = _parse_feature(epayload)
        if key is not None:
            out[key] = feat


def _parse_feature(data: bytes):
    pos = 0
    while pos < len(data):
        tag, pos = _read_varint(data, pos)
        field = tag >> 3
        length, pos = _read_varint(data, pos)
        payload = data[pos:pos + length]
        pos += length
        if field == 1:  # bytes_list
            return _parse_list(payload, "bytes")
        if field == 2:  # float_list
            return _parse_list(payload, "float")
        if field == 3:  # int64_list
            return _parse_list(payload, "int64")
    return None


def _parse_list(data: bytes, kind: str):
    import struct
    values = []
    pos = 0
    while pos < len(data):
        tag, pos = _read_varint(data, pos)
        wire = tag & 7
        if kind == "bytes":
            length, pos = _read_varint(data, pos)
            values.append(data[pos:pos + length])
            pos += length
        elif kind == "float":
            if wire == 2:  # packed
                length, pos = _read_varint(data, pos)
                values.extend(struct.unpack(f"<{length // 4}f",
                                            data[pos:pos + length]))
                pos += length
            else:
                values.append(struct.unpack("<f", data[pos:pos + 4])[0])
                pos += 4
        else:  # int64
            if wire == 2:
                length, pos = _read_varint(data, pos)
                end = pos + length
                while pos < end:
                    v, pos = _read_varint(data, pos)
                    values.append(v)
            else:
                v, pos = _read_varint(data, pos)
                values.append(v)
    if len(values) == 1:
        return values[0]
    return values


# ---- writers -------------------------------------------------------------


def write_block(block: Block, path_dir: str, fmt: str, index: int) -> str:
    os.makedirs(path_dir, exist_ok=True)
    path = os.path.join(path_dir, f"part-{index:06d}.{fmt}")
    acc = BlockAccessor.for_block(block)
    if fmt == "parquet":
        import pyarrow.parquet as pq
        pq.write_table(acc.table, path)
    elif fmt == "csv":
        from pyarrow import csv as pacsv
        pacsv.write_csv(acc.table, path)
    elif fmt == "json":
        import json as jsonlib
        with open(path, "w") as f:
            for row in acc.iter_rows():
                f.write(jsonlib.dumps(_json_safe(row)) + "\n")
    else:
        raise ValueError(f"unknown write format {fmt}")
    return path


def _json_safe(row: dict) -> dict:
    out = {}
    for k, v in row.items():
        if isinstance(v, np.generic):
            v = v.item()
        elif isinstance(v, np.ndarray):
            v = v.tolist()
        elif isinstance(v, bytes):
            v = v.decode("utf-8", "replace")
        out[k] = v
    return out
