"""Binary IDs with embedded lineage.

TPU-native analog of the reference's ID scheme (/root/reference/src/ray/common/id.h):
ObjectIDs embed the TaskID of the task that created them plus a return/put index,
TaskIDs embed the ActorID (if any) and JobID, so ownership and lineage can be
derived from an ID alone without a directory lookup.
"""

from __future__ import annotations

import hashlib
import os
import threading

JOB_ID_LEN = 4
ACTOR_ID_LEN = 12  # unique part (8) + job (4)
TASK_ID_LEN = 20   # unique part (8) + actor (12)
OBJECT_ID_LEN = 24  # task (20) + index (4)
NODE_ID_LEN = 16
WORKER_ID_LEN = 16
PG_ID_LEN = 16

_NIL = b"\xff"


class BaseID:
    LEN = 0
    __slots__ = ("_bin", "_hash")

    def __init__(self, binary: bytes):
        if len(binary) != self.LEN:
            raise ValueError(f"{type(self).__name__} requires {self.LEN} bytes, got {len(binary)}")
        self._bin = binary
        self._hash = None

    @classmethod
    def from_random(cls):
        return cls(os.urandom(cls.LEN))

    @classmethod
    def nil(cls):
        return cls(_NIL * cls.LEN)

    def is_nil(self) -> bool:
        return self._bin == _NIL * self.LEN

    def binary(self) -> bytes:
        return self._bin

    def hex(self) -> str:
        return self._bin.hex()

    def __eq__(self, other) -> bool:
        return type(other) is type(self) and other._bin == self._bin

    def __hash__(self) -> int:
        # cached: IDs key every hot-path dict (pending tasks, refcounts,
        # memory store) and are hashed many times per task
        h = self._hash
        if h is None:
            h = self._hash = hash((type(self).__name__, self._bin))
        return h

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self._bin.hex()})"

    def __reduce__(self):
        return (type(self), (self._bin,))


class JobID(BaseID):
    LEN = JOB_ID_LEN
    _counter = 0
    _lock = threading.Lock()

    @classmethod
    def from_int(cls, i: int) -> "JobID":
        return cls(i.to_bytes(JOB_ID_LEN, "little"))


class NodeID(BaseID):
    LEN = NODE_ID_LEN


class WorkerID(BaseID):
    LEN = WORKER_ID_LEN


class PlacementGroupID(BaseID):
    LEN = PG_ID_LEN


class ActorID(BaseID):
    LEN = ACTOR_ID_LEN

    @classmethod
    def of(cls, job_id: JobID) -> "ActorID":
        return cls(os.urandom(ACTOR_ID_LEN - JOB_ID_LEN) + job_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._bin[-JOB_ID_LEN:])


class TaskID(BaseID):
    LEN = TASK_ID_LEN

    @classmethod
    def for_task(cls, job_id: JobID, parent: "TaskID | None", counter: int) -> "TaskID":
        """Deterministically derive a child task id from its parent + counter
        (ref: id.h TaskID::ForNormalTask)."""
        h = hashlib.sha1()
        h.update(parent.binary() if parent else b"driver")
        h.update(counter.to_bytes(8, "little"))
        h.update(os.urandom(8))  # jobs may resubmit the same counter after restart
        unique = h.digest()[: TASK_ID_LEN - ACTOR_ID_LEN]
        return cls(unique + ActorID.nil().binary()[:-JOB_ID_LEN] + job_id.binary())

    @classmethod
    def for_actor_task(cls, job_id: JobID, actor_id: ActorID, counter: int) -> "TaskID":
        h = hashlib.sha1()
        h.update(actor_id.binary())
        h.update(counter.to_bytes(8, "little"))
        h.update(os.urandom(8))
        unique = h.digest()[: TASK_ID_LEN - ACTOR_ID_LEN]
        return cls(unique + actor_id.binary())

    @classmethod
    def for_driver(cls, job_id: JobID) -> "TaskID":
        unique = b"\x00" * (TASK_ID_LEN - ACTOR_ID_LEN)
        actor_part = b"\x01" * (ACTOR_ID_LEN - JOB_ID_LEN)
        return cls(unique + actor_part + job_id.binary())

    def actor_id(self) -> ActorID:
        return ActorID(self._bin[TASK_ID_LEN - ACTOR_ID_LEN:])

    def job_id(self) -> JobID:
        return JobID(self._bin[-JOB_ID_LEN:])


class ObjectID(BaseID):
    LEN = OBJECT_ID_LEN

    @classmethod
    def for_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        """index >= 1 for returns (ref: id.h ObjectID::FromIndex)."""
        return cls(task_id.binary() + index.to_bytes(4, "little"))

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int) -> "ObjectID":
        # puts use the high bit of the index to disambiguate from returns
        return cls(task_id.binary() + (put_index | 0x80000000).to_bytes(4, "little"))

    def task_id(self) -> TaskID:
        return TaskID(self._bin[:TASK_ID_LEN])

    def index(self) -> int:
        return int.from_bytes(self._bin[TASK_ID_LEN:], "little")

    def is_put(self) -> bool:
        return bool(self.index() & 0x80000000)

    def job_id(self) -> JobID:
        return self.task_id().job_id()
