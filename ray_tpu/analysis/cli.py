"""`ray-tpu lint` implementation (kept apart from scripts/cli.py so the
analyzer is importable without argparse plumbing, and vice versa).

Exit status: 0 when the run matches the committed baseline exactly;
1 on any new finding or stale baseline entry. ``--baseline`` rewrites
the baseline from the current run (deterministic; keeps justifications
of surviving entries) and exits 0.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Optional

from ray_tpu.analysis import baseline as baseline_mod
from ray_tpu.analysis.core import (all_passes, default_passes, package_dir,
                                   repo_root, run_passes)


def lint(paths: Optional[list[str]] = None, json_out: bool = False,
         write_baseline: bool = False, baseline_file: Optional[str] = None,
         include_tests: bool = False, out=None) -> int:
    out = out or sys.stdout
    passes = default_passes()
    parse_errors: list[str] = []
    on_error = lambda path, e: parse_errors.append(f"{path}: {e}")  # noqa: E731
    findings = run_passes(paths or [package_dir()], passes=passes,
                          on_error=on_error)
    if include_tests:
        # tests-scoped passes (tier1-marks) analyze test files, not the
        # package; the package passes deliberately skip test code (tests
        # accumulate state and fire one-way notifies on purpose)
        tests_passes = [p for p in all_passes().values()
                        if p.scope == "tests"]
        tests_dir = os.path.join(repo_root(), "tests")
        if tests_passes and os.path.isdir(tests_dir):
            passes = passes + tests_passes
            findings = sorted(
                findings + run_passes([tests_dir], passes=tests_passes,
                                      on_error=on_error),
                key=lambda f: (f.path, f.line, f.pass_id, f.tag))

    if write_baseline:
        p = baseline_mod.save(findings, baseline_file)
        if json_out:
            json.dump({"baseline": p, "entries": len(findings)}, out)
            out.write("\n")
        else:
            out.write(f"wrote {len(findings)} entries to {p}\n")
            missing = [f.key for f in findings
                       if not baseline_mod.load(p).get(f.key)]
            if missing:
                out.write(f"  ({len(missing)} entries need a justification "
                          f"— edit the file)\n")
        return 0

    new, stale = baseline_mod.diff(findings, baseline_file)
    base = baseline_mod.load(baseline_file)
    if json_out:
        json.dump({
            "findings": [f.to_dict() | {"baselined": f.key in base}
                         for f in findings],
            "new": [f.to_dict() for f in new],
            "stale_baseline_keys": stale,
            "parse_errors": parse_errors,
            "passes": sorted(p.id for p in passes),
        }, out, indent=2)
        out.write("\n")
    else:
        for f in findings:
            mark = " [baselined]" if f.key in base else ""
            out.write(f.format() + mark + "\n")
        for err in parse_errors:
            out.write(f"parse error: {err}\n")
        out.write(f"{len(findings)} finding(s): {len(new)} new, "
                  f"{len(findings) - len(new)} baselined; "
                  f"{len(stale)} stale baseline entr"
                  f"{'y' if len(stale) == 1 else 'ies'}\n")
        if new:
            out.write("new findings — fix them, pragma the site, or "
                      "`ray-tpu lint --baseline` + justify:\n")
            for f in new:
                out.write(f"  {f.key}\n")
        if stale:
            out.write("stale baseline entries (finding no longer exists "
                      "— prune via `ray-tpu lint --baseline`):\n")
            for k in stale:
                out.write(f"  {k}\n")
    return 1 if (new or stale or parse_errors) else 0
