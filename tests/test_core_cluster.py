"""Multi-node tests on the in-process simulated cluster.

Models the reference's cluster_utils-based tests (SURVEY.md §4 keystone (a)):
spillback scheduling, cross-node objects, node death, placement groups,
TPU slice gang scheduling with fake topology labels.
"""

import time

import pytest

import ray_tpu
from ray_tpu import exceptions


def _connect(cluster):
    return ray_tpu.init(address=cluster.address, _system_config={
        "health_check_period_s": 0.2,
        "health_check_failure_threshold": 3,
    })


def test_two_nodes_spillback(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=1)
    cluster.add_node(num_cpus=1)
    _connect(cluster)

    @ray_tpu.remote(num_cpus=1)
    def whoami():
        # long enough that the second task cannot just reuse the first lease
        # after it finishes — it must spill to the second node
        time.sleep(3.0)
        return ray_tpu.get_runtime_context().node_id.hex()

    refs = [whoami.remote() for _ in range(2)]
    nodes = set(ray_tpu.get(refs, timeout=60))
    assert len(nodes) == 2


def test_cross_node_object_transfer(ray_start_cluster):
    import numpy as np
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=1, resources={"a": 1})
    cluster.add_node(num_cpus=1, resources={"b": 1})
    _connect(cluster)

    @ray_tpu.remote(resources={"a": 1})
    def produce():
        return np.arange(500_000, dtype=np.float64)  # 4 MB -> shm

    @ray_tpu.remote(resources={"b": 1})
    def consume(arr):
        return float(arr.sum())

    ref = produce.remote()
    total = ray_tpu.get(consume.remote(ref), timeout=60)
    assert total == float(np.arange(500_000, dtype=np.float64).sum())


def test_node_death_detected(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=1)
    n2 = cluster.add_node(num_cpus=1, resources={"pin": 1})
    _connect(cluster)

    @ray_tpu.remote(resources={"pin": 1}, max_restarts=0)
    class Pinned:
        def ping(self):
            return "pong"

    a = Pinned.remote()
    assert ray_tpu.get(a.ping.remote(), timeout=30) == "pong"
    cluster.remove_node(n2)
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        alive = [n for n in ray_tpu.nodes() if n["alive"]]
        if len(alive) == 1:
            break
        time.sleep(0.2)
    assert len([n for n in ray_tpu.nodes() if n["alive"]]) == 1
    with pytest.raises((exceptions.TaskError, exceptions.ActorDiedError)):
        ray_tpu.get(a.ping.remote(), timeout=30)


def test_placement_group_spread(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2)
    _connect(cluster)
    pg = ray_tpu.placement_group([{"CPU": 1}, {"CPU": 1}], strategy="STRICT_SPREAD")
    assert pg.ready(timeout=30)
    node_ids = pg.bundle_node_ids()
    assert len(set(n.hex() for n in node_ids)) == 2

    @ray_tpu.remote(num_cpus=1)
    def whoami():
        return ray_tpu.get_runtime_context().node_id.hex()

    strat = ray_tpu.PlacementGroupSchedulingStrategy(pg, placement_group_bundle_index=0)
    out = ray_tpu.get(whoami.options(scheduling_strategy=strat).remote(), timeout=60)
    assert out == node_ids[0].hex()
    ray_tpu.remove_placement_group(pg)


def test_placement_group_infeasible_pends(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=1)
    _connect(cluster)
    pg = ray_tpu.placement_group([{"CPU": 8}], strategy="PACK")
    assert not pg.ready(timeout=1.0)


def test_tpu_slice_gang_scheduling(ray_start_cluster):
    """Atomic whole-slice placement with faked slice topology labels."""
    cluster = ray_start_cluster
    # two slices of 2 hosts each; one is busy on one host
    for wid in range(2):
        cluster.add_node(num_cpus=4, tpu_slice="slice-A", tpu_worker_id=wid)
    for wid in range(2):
        cluster.add_node(num_cpus=4, tpu_slice="slice-B", tpu_worker_id=wid)
    _connect(cluster)

    pg = ray_tpu.placement_group(
        [{"TPU": 4}, {"TPU": 4}], strategy="SLICE")
    assert pg.ready(timeout=30)
    node_ids = pg.bundle_node_ids()
    by_id = {n["node_id"]: n for n in ray_tpu.nodes()}
    slices = {by_id[nid]["labels"]["slice_name"] for nid in node_ids}
    assert len(slices) == 1  # all bundles on ONE slice
    workers = [by_id[nid]["labels"]["tpu_worker_id"] for nid in node_ids]
    assert workers == ["0", "1"]  # ordered by slice worker id

    # second gang takes the other slice
    pg2 = ray_tpu.placement_group([{"TPU": 4}, {"TPU": 4}], strategy="SLICE")
    assert pg2.ready(timeout=30)
    slices2 = {by_id[nid]["labels"]["slice_name"] for nid in pg2.bundle_node_ids()}
    assert len(slices2) == 1
    assert slices != slices2

    # no third slice available
    pg3 = ray_tpu.placement_group([{"TPU": 4}, {"TPU": 4}], strategy="SLICE")
    assert not pg3.ready(timeout=1.0)
    ray_tpu.remove_placement_group(pg2)
    # after removal, the gang can be placed again
    assert pg3.ready(timeout=30)


def test_node_label_scheduling(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=1, labels={"zone": "us-a"})
    cluster.add_node(num_cpus=1, labels={"zone": "us-b"})
    _connect(cluster)

    @ray_tpu.remote(num_cpus=1)
    def whoami():
        return ray_tpu.get_runtime_context().node_id.hex()

    strat = ray_tpu.NodeLabelStrategy(hard={"zone": "us-b"})
    out = ray_tpu.get(whoami.options(scheduling_strategy=strat).remote(), timeout=60)
    node = [n for n in ray_tpu.nodes() if n["node_id"].hex() == out][0]
    assert node["labels"]["zone"] == "us-b"
