"""Performance-introspection tests (observability/profiling.py): engine
phase timers, compile-event tracking, device-memory accounting, and the
cluster-wide XProf capture path — all on the cpu backend."""

import os
import re
import time

import pytest

import ray_tpu


@pytest.fixture(scope="module")
def ray_start_regular(ray_start_module):
    yield ray_start_module


def _tiny_cfg(**kw):
    from ray_tpu.models import llama
    from ray_tpu.serve.llm import LLMConfig

    d = dict(model_config=llama.llama_tiny(vocab_size=512),
             max_batch_size=4, page_size=16, num_pages=64,
             max_prompt_len=64, max_seq_len=128, max_tokens=8)
    d.update(kw)
    return LLMConfig(**d)


def _mk_engine(**kw):
    from ray_tpu.serve.llm import LLMEngine

    eng = LLMEngine(_tiny_cfg(**kw))
    eng.start()
    return eng


# ---- phase timers -----------------------------------------------------


def test_phase_timers_record_after_traffic():
    eng = _mk_engine()
    try:
        out = eng.generate("the quick brown fox jumps over", max_tokens=6)
        assert out["num_generated_tokens"] >= 1
        stats = eng.engine_stats()
        # every decode path phase must have samples; verify is spec-only
        for phase in ("admit", "prefill", "decode_dispatch", "harvest"):
            p50 = stats[f"phase_{phase}_p50_ms"]
            p95 = stats[f"phase_{phase}_p95_ms"]
            assert p50 is not None and p50 >= 0.0, phase
            assert p95 is not None and p95 >= p50, phase
        assert stats["phase_verify_dispatch_p50_ms"] is None
    finally:
        eng.shutdown()


def test_phase_timers_disabled_stay_empty():
    eng = _mk_engine(profiling_enabled=False)
    try:
        eng.generate("hello world one two three", max_tokens=4)
        stats = eng.engine_stats()
        for phase in ("admit", "prefill", "chunk_prefill",
                      "decode_dispatch", "verify_dispatch", "harvest"):
            assert stats[f"phase_{phase}_p50_ms"] is None, phase
        # compile tracking is NOT gated by profiling_enabled
        assert stats["compile_events"] >= 1
    finally:
        eng.shutdown()


def test_itl_recorded_per_request():
    eng = _mk_engine()
    try:
        out = eng.generate("a b c d e f g h", max_tokens=8)
        assert out["num_generated_tokens"] >= 2
        # per-request median ITL (host record-time gaps)
        assert out["itl_s"] is not None and out["itl_s"] >= 0.0
        assert eng.engine_stats()["itl_s"] is not None
    finally:
        eng.shutdown()


# ---- compile-event tracking -------------------------------------------


def test_compile_once_and_mid_traffic_counter():
    # prefix cache off: a cache hit would route the repeat through the
    # chunked suffix-prefill path and compile a chunk program — this test
    # wants shape-for-shape repeats
    eng = _mk_engine(prefix_cache_enabled=False)
    try:
        stats0 = eng.engine_stats()
        # warmup compiles (decode/verify tiers) are NOT mid-traffic
        assert stats0["compile_events"] >= 1
        assert stats0["mid_traffic_compiles"] == 0

        prompt = "one two three four five six"
        eng.generate(prompt, max_tokens=4)
        stats1 = eng.engine_stats()
        # first prompt hits an unwarmed prefill bucket -> mid-traffic
        assert stats1["mid_traffic_compiles"] >= 1
        assert stats1["compile_s"] > 0.0

        # repeating the same shapes must not compile again
        eng.generate(prompt, max_tokens=4)
        stats2 = eng.engine_stats()
        assert stats2["compile_events"] == stats1["compile_events"]
        assert stats2["mid_traffic_compiles"] == stats1["mid_traffic_compiles"]

        # a NEW prompt bucket mid-traffic is flagged (regression guard)
        long_prompt = " ".join(["tok"] * 40)  # 159 bytes -> bucket 64
        eng.generate(long_prompt, max_tokens=4)
        stats3 = eng.engine_stats()
        assert stats3["mid_traffic_compiles"] > stats2["mid_traffic_compiles"]
        assert stats3["compile_events"] > stats2["compile_events"]
    finally:
        eng.shutdown()


# ---- device-memory accounting -----------------------------------------


def test_memory_gauges_sane():
    from ray_tpu.observability import profiling as prof

    eng = _mk_engine()
    try:
        stats = eng.engine_stats()
        assert stats["weights_bytes"] == prof.tree_bytes(eng.params)
        assert stats["kv_pool_bytes"] == prof.tree_bytes(eng.kv)
        assert stats["weights_bytes"] > 0
        assert stats["kv_pool_bytes"] > 0
        assert 0.0 <= stats["kv_page_occupancy"] <= 1.0
        eng.generate("occupy some pages please now", max_tokens=4)
        # finished requests free their pages; occupancy stays a fraction
        assert 0.0 <= eng.engine_stats()["kv_page_occupancy"] <= 1.0
    finally:
        eng.shutdown()


def test_save_device_memory_profile_local(tmp_path):
    from ray_tpu.observability import profiling as prof

    path = str(tmp_path / "mem.prof")
    out = prof.save_device_memory_profile(path)
    assert out == path
    assert os.path.getsize(path) > 0


# ---- XProf capture ----------------------------------------------------


def test_capture_round_trip_local(tmp_path):
    """start/stop produce a non-empty XPlane trace dir on cpu backend."""
    import jax.numpy as jnp

    from ray_tpu.observability import profiling as prof

    logdir = str(tmp_path / "xprof")
    info = prof.start_capture(logdir)
    assert info["logdir"] == logdir
    assert prof.capture_status()["active"]
    # double-start is refused while a capture is live
    with pytest.raises(RuntimeError):
        prof.start_capture(str(tmp_path / "other"))
    (jnp.ones((64, 64)) @ jnp.ones((64, 64))).block_until_ready()
    out = prof.stop_capture()
    assert out["logdir"] == logdir
    assert out["duration_s"] > 0.0
    assert not prof.capture_status()["active"]
    # the profiler writes <logdir>/plugins/profile/<run>/...
    plugin_dir = os.path.join(logdir, "plugins", "profile")
    assert os.path.isdir(plugin_dir)
    runs = os.listdir(plugin_dir)
    assert runs and os.listdir(os.path.join(plugin_dir, runs[0]))


def test_cluster_capture_end_to_end(ray_start_regular, tmp_path):
    """state.capture_xprof drives CP -> node agent -> worker and registers
    a downloadable artifact."""
    from ray_tpu.util import state

    @ray_tpu.remote
    def burn():
        import jax.numpy as jnp
        return float((jnp.ones((32, 32)) @ jnp.ones((32, 32))).sum())

    assert ray_tpu.get(burn.remote()) > 0  # a worker exists and runs jax

    # default logdir: per-worker /tmp/ray_tpu_xprof/<ts>-<pid> (an explicit
    # shared dir would collide when several workers share a host)
    out = state.capture_xprof(duration=1.0)
    assert out["nodes"], "no nodes reached"
    arts = out["artifacts"]
    assert arts, f"no artifacts registered: {out}"
    for art in arts:
        assert art["kind"] == "xplane"
        assert art["duration_s"] > 0.0
        assert os.path.isdir(art["logdir"])

    listed = state.list_profile_artifacts()
    ids = {a["id"] for a in listed}
    assert all(a["id"] in ids for a in arts)

    # second capture works (per-process controller resets cleanly)
    out2 = state.capture_xprof(duration=0.5)
    assert out2["artifacts"]


def test_cluster_memory_profile(ray_start_regular, tmp_path):
    from ray_tpu.util import state

    @ray_tpu.remote
    def touch():
        return 1

    assert ray_tpu.get(touch.remote()) == 1
    out = state.save_device_memory_profile(
        path=str(tmp_path / "cluster-mem.prof"))
    workers = [w for n in out["nodes"].values() if isinstance(n, dict)
               for w in (n.get("workers") or {}).values()]
    assert workers
    assert any(isinstance(w, dict) and w.get("ok") for w in workers), out


# ---- README drift guard -----------------------------------------------


def test_readme_engine_stats_table_matches_live_keys():
    """Every key engine_stats() emits must be documented in README's
    engine-telemetry table, and every documented key must exist — with
    prefix cache, spec decoding, and profiling all on."""
    eng = _mk_engine(prefix_cache_enabled=True, spec_decode_enabled=True,
                     spec_draft_len=2, kv_tier_enabled=True)
    try:
        eng.generate("drift guard prompt one two three", max_tokens=6)
        live = set(eng.engine_stats().keys())
    finally:
        eng.shutdown()

    readme = open(os.path.join(os.path.dirname(__file__), "..",
                               "README.md")).read()
    section = readme.split("### Engine telemetry (`engine_stats()`)")[1]
    table = section.split("\n## ")[0]
    documented = set()
    for row in re.findall(r"^\|([^|]+)\|", table, flags=re.M):
        documented.update(re.findall(r"`([a-z0-9_]+)`", row))

    missing_docs = live - documented
    assert not missing_docs, \
        f"engine_stats keys missing from README table: {sorted(missing_docs)}"
    stale_docs = documented - live
    assert not stale_docs, \
        f"README documents keys engine_stats no longer emits: {sorted(stale_docs)}"


# ---- dashboard panel --------------------------------------------------


def test_dashboard_profiling_routes(ray_start_regular):
    import json
    import urllib.request

    from ray_tpu.dashboard import start_dashboard

    dash = start_dashboard(port=0)
    try:
        base = f"http://127.0.0.1:{dash.port}"
        with urllib.request.urlopen(base + "/profiling", timeout=30) as r:
            assert r.status == 200
            assert b"engine profiling" in r.read()
        with urllib.request.urlopen(base + "/api/profile/artifacts",
                                    timeout=30) as r:
            assert isinstance(json.loads(r.read()), list)
        # unknown artifact id -> 404, not a crash
        try:
            urllib.request.urlopen(
                base + "/api/profile/download/nope", timeout=30)
            raised = False
        except urllib.error.HTTPError as e:
            raised = e.code == 404
        assert raised
    finally:
        dash.stop()
