"""graftlint acceptance: per-pass positive/negative fixtures, pragma
suppression, baseline exact-drift (both directions), CLI JSON, and
regression fixtures for the production findings this PR fixed.

Everything here is pure-AST analysis of inline source strings or of the
repo itself — no cluster, no JAX import, sub-second per test. The one
full-package run doubles as the tier-1 gate: it must match the committed
GRAFTLINT_BASELINE.json exactly and finish well inside 15 seconds.
"""

import io
import json
import textwrap
import time

from ray_tpu.analysis import (baseline_diff, load_baseline, run_passes,
                              save_baseline)
from ray_tpu.analysis.baseline import baseline_path
from ray_tpu.analysis.cli import lint
from ray_tpu.analysis.core import ModuleSource
from ray_tpu.analysis.passes_concurrency import LockDisciplinePass, RpcAckPass
from ray_tpu.analysis.passes_growth import UnboundedGrowthPass
from ray_tpu.analysis.passes_jax import HostSyncPass, JitHygienePass
from ray_tpu.analysis.passes_tests import Tier1MarksPass


def _run(pass_, src, relpath="ray_tpu/core/mod.py"):
    module = ModuleSource("/repo/" + relpath, relpath,
                          textwrap.dedent(src))
    return pass_.run(module)


# ---------------------------------------------------------------------------
# lock-discipline


def test_lock_discipline_flags_rpc_under_with_lock():
    findings = _run(LockDisciplinePass(), """
        class A:
            def f(self):
                with self._lock:
                    self.cp.call("ping", None, timeout=1.0)
        """)
    assert len(findings) == 1
    f = findings[0]
    assert f.pass_id == "lock-discipline" and f.symbol == "A.f"
    assert "call" in f.message and "_lock" in f.message


def test_lock_discipline_clean_when_rpc_moves_outside_lock():
    findings = _run(LockDisciplinePass(), """
        class A:
            def f(self):
                with self._lock:
                    msg = self._q.pop()
                self.cp.call("ping", msg, timeout=1.0)
        """)
    assert findings == []


def test_lock_discipline_propagates_through_self_calls():
    findings = _run(LockDisciplinePass(), """
        class A:
            def _emit(self):
                self.cp.notify("report", {})
            def f(self):
                with self._lock:
                    self._emit()
        """)
    assert len(findings) == 1
    assert "self._emit()" in findings[0].message


def test_lock_discipline_flags_acquire_release_style():
    findings = _run(LockDisciplinePass(), """
        class A:
            def f(self):
                self._mu.acquire()
                time.sleep(1.0)
                self._mu.release()
        """)
    assert len(findings) == 1
    assert "time.sleep" in findings[0].message


def test_lock_discipline_allows_condition_wait_and_notify():
    # the sanctioned CV pattern: wait/notify on the held condition
    findings = _run(LockDisciplinePass(), """
        class A:
            def f(self):
                with self._cv:
                    while not self._ready:
                        self._cv.wait(1.0)
                    self._cv.notify()
        """)
    assert findings == []


def test_lock_discipline_pragma_suppresses():
    findings = _run(LockDisciplinePass(), """
        class A:
            def f(self):
                with self._lock:
                    # graftlint: disable=lock-discipline
                    self.cp.call("ping", None)
        """)
    assert findings == []


def test_lock_discipline_def_line_pragma_covers_whole_function():
    findings = _run(LockDisciplinePass(), """
        class A:
            def f(self):  # graftlint: disable=lock-discipline
                with self._lock:
                    self.cp.call("a", None)
                    self.cp.call("b", None)
        """)
    assert findings == []


def test_metrics_flusher_regression_fixture():
    # the exact pre-fix shape of MetricsFlusher.flush (PR 8 bug class):
    # the injected send callable — an RPC — invoked inside _flush_lock
    findings = _run(LockDisciplinePass(), """
        class MetricsFlusher:
            def flush(self):
                with self._flush_lock:
                    while self._backlog:
                        try:
                            self._send(self._backlog[0])
                        except Exception:
                            break
                        self._backlog.pop(0)
        """, relpath="ray_tpu/util/metrics.py")
    assert len(findings) == 1
    assert findings[0].tag == "_send"


def test_metrics_flusher_production_fix_holds():
    # the committed fix keeps every _send outside _flush_lock — a fresh
    # run over the real file must produce no lock-discipline finding
    import ray_tpu.util as u
    import os
    path = os.path.join(os.path.dirname(u.__file__), "metrics.py")
    findings = [f for f in run_passes([path],
                                      passes=[LockDisciplinePass()])
                if f.symbol.startswith("MetricsFlusher")]
    assert findings == []


# ---------------------------------------------------------------------------
# rpc-ack


def test_rpc_ack_flags_one_way_notify():
    findings = _run(RpcAckPass(), """
        class Agent:
            def _on_worker_dead(self, info):
                self._pool.get(self.cp_addr).notify(
                    "worker_died", {"worker_id": info.worker_id})
        """)
    assert len(findings) == 1
    f = findings[0]
    assert f.tag == "notify:worker_died"
    assert f.symbol == "Agent._on_worker_dead"


def test_rpc_ack_object_moved_regression_fixture():
    # pre-fix _h_drain_objects shape: the owner's location table depends
    # on this message, yet it went out as a droppable one-way notify
    findings = _run(RpcAckPass(), """
        class Agent:
            def _h_drain_objects(self, body):
                self._pool.get(owner).notify(
                    "object_moved", {"object_id": oid})
        """)
    assert [f.tag for f in findings] == ["notify:object_moved"]


def test_rpc_ack_clean_for_acked_call_and_condition_notify():
    findings = _run(RpcAckPass(), """
        class Agent:
            def f(self):
                self._pool.get(addr).call("worker_died", {}, timeout=5.0)
                with self._cv:
                    self._cv.notify()
                self._cv.notify_all()
        """)
    assert findings == []


def test_rpc_ack_fire_and_forget_pragma():
    findings = _run(RpcAckPass(), """
        class Agent:
            def f(self):
                # graftlint: fire-and-forget
                self.cp.notify("report_resources", {})
        """)
    assert findings == []


# ---------------------------------------------------------------------------
# host-sync


def test_host_sync_flags_np_asarray_in_hot_method():
    findings = _run(HostSyncPass(), """
        class Engine:
            def _decode_step(self):
                toks = np.asarray(self._dev_toks)
                return toks
        """, relpath="ray_tpu/serve/llm/engine.py")
    assert len(findings) == 1
    assert findings[0].tag == "np.asarray"


def test_host_sync_exempts_harvest_and_other_modules():
    harvest = _run(HostSyncPass(), """
        class Engine:
            def _harvest_one(self):
                return np.asarray(self._dev_toks)
        """, relpath="ray_tpu/serve/llm/engine.py")
    other_module = _run(HostSyncPass(), """
        class Engine:
            def _decode_step(self):
                return np.asarray(x)
        """, relpath="ray_tpu/core/worker.py")
    assert harvest == [] and other_module == []


def test_host_sync_flags_item_and_block_until_ready():
    findings = _run(HostSyncPass(), """
        class Engine:
            def _step(self):
                v = logits.item()
                out.block_until_ready()
        """, relpath="ray_tpu/serve/llm/engine.py")
    assert sorted(f.tag for f in findings) == [".item()",
                                               "block_until_ready"]


# ---------------------------------------------------------------------------
# jit-hygiene


def test_jit_hygiene_flags_mutable_self_attr_read():
    findings = _run(JitHygienePass(), """
        import jax
        class Eng:
            def __init__(self):
                self._decode = jax.jit(self._decode_impl)
            def _decode_impl(self, x):
                return x + self._offset
            def bump(self):
                self._offset = 1
        """)
    assert [f.tag for f in findings] == ["self._offset"]


def test_jit_hygiene_flags_mutable_global_read():
    findings = _run(JitHygienePass(), """
        import jax
        cfg = {"scale": 2}
        @jax.jit
        def f(a):
            return a * cfg["scale"]
        """)
    assert [f.tag for f in findings] == ["global:cfg"]


def test_jit_hygiene_flags_python_branch_on_traced_param():
    findings = _run(JitHygienePass(), """
        import jax
        @jax.jit
        def f(a, flag):
            if flag:
                return a
            return -a
        """)
    assert [f.tag for f in findings] == ["branch:flag"]


def test_jit_hygiene_static_argnums_and_shape_checks_are_clean():
    findings = _run(JitHygienePass(), """
        import jax
        g = jax.jit(lambda a, flag: a if flag else -a, static_argnums=(1,))
        @jax.jit
        def h(a):
            if a.shape[0] > 4:
                return a
            return -a
        """)
    assert findings == []


def test_jit_hygiene_init_only_attrs_are_clean():
    findings = _run(JitHygienePass(), """
        import jax
        class Eng:
            def __init__(self):
                self._dim = 8
                self._decode = jax.jit(self._decode_impl)
            def _decode_impl(self, x):
                return x + self._dim
        """)
    assert findings == []


# ---------------------------------------------------------------------------
# unbounded-growth


def test_unbounded_growth_flags_handler_fed_dict():
    findings = _run(UnboundedGrowthPass(), """
        class CP:
            def __init__(self):
                self._series = {}
            def _h_report(self, body):
                self._series[body["k"]] = body["v"]
        """)
    assert [f.tag for f in findings] == ["self._series"]
    assert "never caps" in findings[0].message


def test_unbounded_growth_clean_with_retraction_or_cap():
    retracted = _run(UnboundedGrowthPass(), """
        class CP:
            def __init__(self):
                self._series = {}
            def _h_report(self, body):
                self._series[body["k"]] = body["v"]
            def _on_worker_dead(self, wid):
                self._series.pop(wid, None)
        """)
    capped = _run(UnboundedGrowthPass(), """
        class CP:
            def __init__(self):
                self._log = []
            def _h_append(self, body):
                self._log.append(body)
                del self._log[:-200]
        """)
    assert retracted == [] and capped == []


def test_unbounded_growth_one_hop_reachability():
    findings = _run(UnboundedGrowthPass(), """
        class CP:
            def __init__(self):
                self._seen = set()
            def _h_event(self, body):
                self._record(body)
            def _record(self, body):
                self._seen.add(body["id"])
        """)
    assert [f.symbol for f in findings] == ["CP._record"]


def test_unbounded_growth_non_handler_growth_is_clean():
    findings = _run(UnboundedGrowthPass(), """
        class Builder:
            def __init__(self):
                self._parts = []
            def add_part(self, p):
                self._parts.append(p)
        """)
    assert findings == []


# ---------------------------------------------------------------------------
# tier1-marks (semantics beyond what test_tier1_guard.py asserts)


def test_tier1_marks_fixture_semantics():
    src = """
        import pytest

        def test_uses_chaos(cluster):
            k = NodeKiller(cluster)
            k.start()

        @pytest.mark.slow
        def test_marked_chaos(cluster):
            NodeKiller(cluster).start()

        def test_worker_killer_max_kills():
            pass

        def test_three_nodes(c):
            c.add_node(); c.add_node(); c.add_node()

        def test_two_nodes(c):
            c.add_node(); c.add_node()
        """
    module = ModuleSource("/repo/tests/test_x.py", "tests/test_x.py",
                          textwrap.dedent(src))
    findings = Tier1MarksPass().run(module)
    assert sorted((f.symbol, f.tag) for f in findings) == [
        ("test_three_nodes", "multi-node"),
        ("test_uses_chaos", "chaos"),
    ]
    # non-test files are out of scope entirely
    other = ModuleSource("/repo/tests/conftest.py", "tests/conftest.py",
                         textwrap.dedent(src))
    assert Tier1MarksPass().run(other) == []


# ---------------------------------------------------------------------------
# finding shape + baseline keys


def test_finding_format_and_dict():
    (f,) = _run(RpcAckPass(), """
        class A:
            def f(self):
                self.cp.notify("x", {})
        """)
    line = f.format()
    assert line.startswith(f"{f.path}:{f.line}: [rpc-ack] A.f:")
    assert "(fix: " in line
    d = f.to_dict()
    assert d["pass"] == "rpc-ack" and d["symbol"] == "A.f"
    assert d["line"] == f.line and d["key"] == f.key


def test_baseline_keys_are_line_number_free():
    src = """
        class A:
            def f(self):
                self.cp.notify("x", {})
        """
    (a,) = _run(RpcAckPass(), src)
    (b,) = _run(RpcAckPass(), "\n\n\n" + textwrap.dedent(src))
    assert a.line != b.line and a.key == b.key


def test_baseline_drift_both_directions(tmp_path):
    base_file = str(tmp_path / "baseline.json")
    findings = _run(RpcAckPass(), """
        class A:
            def f(self):
                self.cp.notify("x", {})
        """)
    save_baseline(findings, base_file)
    new, stale = baseline_diff(findings, base_file)
    assert new == [] and stale == []
    # direction 1: an un-baselined finding is new
    new, stale = baseline_diff([], base_file)
    assert new == [] and stale == [findings[0].key]
    # direction 2: a baselined-but-fixed finding is stale
    save_baseline([], base_file)
    new, stale = baseline_diff(findings, base_file)
    assert [f.key for f in new] == [findings[0].key] and stale == []


def test_baseline_save_preserves_justifications(tmp_path):
    base_file = str(tmp_path / "baseline.json")
    findings = _run(RpcAckPass(), """
        class A:
            def f(self):
                self.cp.notify("x", {})
        """)
    save_baseline(findings, base_file)
    doc = json.loads(open(base_file).read())
    key = findings[0].key
    doc["entries"][key] = "because reasons"
    with open(base_file, "w") as fh:
        json.dump(doc, fh)
    save_baseline(findings, base_file)
    assert load_baseline(base_file)[key] == "because reasons"


# ---------------------------------------------------------------------------
# the tier-1 gate: full package vs the committed baseline, under budget


def test_package_run_matches_committed_baseline_exactly():
    t0 = time.monotonic()
    findings = run_passes()
    elapsed = time.monotonic() - t0
    assert elapsed < 15.0, f"graftlint full-package run took {elapsed:.1f}s"
    new, stale = baseline_diff(findings)
    assert not new, (
        "new graftlint findings — fix them, pragma the site with a "
        "justification, or `ray-tpu lint --baseline` and justify:\n  "
        + "\n  ".join(f.format() for f in new))
    assert not stale, (
        "stale GRAFTLINT_BASELINE.json entries (finding fixed but entry "
        "kept) — prune via `ray-tpu lint --baseline`:\n  "
        + "\n  ".join(stale))


def test_committed_baseline_entries_are_justified():
    base = load_baseline()
    assert base, f"missing baseline at {baseline_path()}"
    unjustified = [k for k, why in base.items() if not why.strip()]
    assert not unjustified, (
        "baseline entries need a one-line justification:\n  "
        + "\n  ".join(unjustified))


# ---------------------------------------------------------------------------
# CLI


def test_cli_json_document(tmp_path):
    out = io.StringIO()
    rc = lint(json_out=True, out=out)
    doc = json.loads(out.getvalue())
    assert rc == 0
    assert doc["new"] == [] and doc["stale_baseline_keys"] == []
    assert doc["parse_errors"] == []
    assert set(doc["passes"]) == {"lock-discipline", "rpc-ack", "host-sync",
                                  "jit-hygiene", "unbounded-growth"}
    for f in doc["findings"]:
        assert f["baselined"] is True


def test_cli_fails_on_new_finding(tmp_path):
    bad = tmp_path / "mod.py"
    bad.write_text(textwrap.dedent("""
        class A:
            def f(self):
                self.cp.notify("x", {})
        """))
    base_file = str(tmp_path / "baseline.json")
    out = io.StringIO()
    rc = lint(paths=[str(bad)], baseline_file=base_file, out=out)
    assert rc == 1 and "1 new" in out.getvalue()
    # --baseline accepts it; the next run is green against that file
    rc = lint(paths=[str(bad)], baseline_file=base_file,
              write_baseline=True, out=io.StringIO())
    assert rc == 0
    rc = lint(paths=[str(bad)], baseline_file=base_file, out=io.StringIO())
    assert rc == 0
