"""Tests for ray_tpu.util: ActorPool, Queue, collective, state API, metrics."""

import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu.util import ActorPool, Queue
from ray_tpu.util import collective as col
from ray_tpu.util import metrics, state


@pytest.fixture(scope="module")
def ray_start_regular(ray_start_module):
    yield ray_start_module



@ray_tpu.remote
class Doubler:
    def double(self, v):
        return v * 2


def test_actor_pool_ordered(ray_start_regular):
    pool = ActorPool([Doubler.remote(), Doubler.remote()])
    out = list(pool.map(lambda a, v: a.double.remote(v), [1, 2, 3, 4]))
    assert out == [2, 4, 6, 8]


def test_actor_pool_unordered(ray_start_regular):
    pool = ActorPool([Doubler.remote(), Doubler.remote()])
    out = sorted(pool.map_unordered(lambda a, v: a.double.remote(v),
                                    [1, 2, 3]))
    assert out == [2, 4, 6]


def test_queue_basic(ray_start_regular):
    q = Queue(maxsize=2)
    q.put("a")
    q.put("b")
    assert q.qsize() == 2
    assert q.full()
    with pytest.raises(Exception):
        q.put("c", block=False)
    assert q.get() == "a"
    assert q.get() == "b"
    assert q.empty()
    with pytest.raises(Exception):
        q.get(block=False)
    q.shutdown()


def test_queue_cross_process(ray_start_regular):
    q = Queue()

    @ray_tpu.remote
    def producer(q):
        for i in range(5):
            q.put(i)
        return True

    ray_tpu.get(producer.remote(q))
    assert [q.get(timeout=10) for _ in range(5)] == [0, 1, 2, 3, 4]
    q.shutdown()


def test_collective_allreduce_broadcast(ray_start_regular):
    @ray_tpu.remote
    def worker(rank, world):
        col.init_collective_group(world, rank, group_name="g1")
        reduced = col.allreduce(np.full((4,), float(rank + 1)),
                                group_name="g1")
        gathered = col.allgather(np.array([rank]), group_name="g1")
        bcast = col.broadcast(
            np.array([42.0]) if rank == 0 else None, src_rank=0,
            group_name="g1")
        col.barrier(group_name="g1")
        return reduced.tolist(), [g.tolist() for g in gathered], bcast.tolist()

    out = ray_tpu.get([worker.remote(r, 2) for r in range(2)], timeout=60)
    for reduced, gathered, bcast in out:
        assert reduced == [3.0, 3.0, 3.0, 3.0]
        assert gathered == [[0], [1]]
        assert bcast == [42.0]


def test_collective_send_recv(ray_start_regular):
    @ray_tpu.remote
    def worker(rank, world):
        col.init_collective_group(world, rank, group_name="g2")
        if rank == 0:
            col.send(np.array([7.0]), dst_rank=1, group_name="g2")
            return None
        return col.recv(src_rank=0, group_name="g2").tolist()

    out = ray_tpu.get([worker.remote(r, 2) for r in range(2)], timeout=60)
    assert out[1] == [7.0]


def test_state_api(ray_start_regular):
    @ray_tpu.remote
    class Named:
        def ping(self):
            return "pong"

    a = Named.options(name="state-test-actor").remote()
    assert ray_tpu.get(a.ping.remote()) == "pong"
    actors = state.list_actors()
    names = [x["name"] for x in actors]
    assert "state-test-actor" in names
    nodes = state.list_nodes()
    assert len(nodes) >= 1

    @ray_tpu.remote
    def noop():
        return 1

    ray_tpu.get([noop.remote() for _ in range(3)])
    import ray_tpu.core.api as core_api
    core_api._get_runtime().flush_task_events()
    tasks = state.list_tasks()
    assert any("noop" in t["name"] for t in tasks)


def test_timeline(ray_start_regular, tmp_path):
    @ray_tpu.remote
    def traced():
        return 1

    ray_tpu.get(traced.remote())
    import ray_tpu.core.api as core_api
    core_api._get_runtime().flush_task_events()
    p = tmp_path / "trace.json"
    state.timeline(str(p))
    import json
    trace = json.loads(p.read_text())
    assert isinstance(trace, list)


def test_metrics():
    c = metrics.Counter("reqs_total", "requests", tag_keys=("route",))
    c.inc(1, tags={"route": "/a"})
    c.inc(2, tags={"route": "/a"})
    g = metrics.Gauge("inflight", "in flight")
    g.set(5)
    h = metrics.Histogram("latency_s", "latency", boundaries=[0.1, 1.0])
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = metrics.collect_prometheus()
    assert "reqs_total" in text and 'route="/a"' in text and "3.0" in text
    assert "inflight 5.0" in text
    assert 'latency_s_bucket{le="+Inf"} 3' in text
    with pytest.raises(ValueError):
        c.inc(0)
    with pytest.raises(ValueError):
        c.inc(1, tags={"bad": "x"})


def test_metrics_exposition_text_format():
    """Validate render_exposition output line-by-line against the
    Prometheus text format over two simulated workers' payloads covering
    tagged and untagged counters, gauges, and histograms (ISSUE 4: the
    ad-hoc emitters used to produce `name{}` and duplicate HELP/TYPE)."""
    import re

    def worker_payload(route, lat_buckets):
        return [
            {"name": "w_reqs_total", "kind": "counter",
             "description": "requests", "tag_keys": ["route"],
             "series": [{"tags": [route], "value": 2.0}]},
            {"name": "w_restarts_total", "kind": "counter",
             "description": "restarts", "tag_keys": [],
             "series": [{"tags": [], "value": 1.0}]},
            {"name": "w_inflight", "kind": "gauge",
             "description": "in flight", "tag_keys": [],
             "series": [{"tags": [], "value": 3.0}]},
            {"name": "w_queue_depth", "kind": "gauge",
             "description": "queued", "tag_keys": ["route"],
             "series": [{"tags": [route], "value": 4.0}]},
            {"name": "w_latency_s", "kind": "histogram",
             "description": "latency", "tag_keys": [],
             "boundaries": [0.1, 1.0],
             "series": [{"tags": [], "buckets": lat_buckets,
                         "sum": 1.5, "count": sum(lat_buckets)}]},
            {"name": "w_step_s", "kind": "histogram",
             "description": "step", "tag_keys": ["route"],
             "boundaries": [0.1, 1.0],
             "series": [{"tags": [route], "buckets": [1, 0, 0],
                         "sum": 0.05, "count": 1}]},
        ]

    text = metrics.render_exposition(
        worker_payload("/a", [1, 2, 0]) + worker_payload("/b", [0, 1, 1]))

    help_re = re.compile(r"^# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* .+$")
    sample_re = re.compile(
        r'^[a-zA-Z_:][a-zA-Z0-9_:]*'                      # metric name
        r'(\{[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"'              # first label
        r'(,[a-zA-Z_][a-zA-Z0-9_]*="[^"]*")*\})?'         # more labels
        r' [0-9.+\-eEInf]+$')                             # value
    helps, types = {}, {}
    for ln in text.splitlines():
        if not ln:
            continue
        if ln.startswith("#"):
            assert help_re.match(ln), f"bad comment line: {ln!r}"
            kind, name = ln.split()[1], ln.split()[2]
            seen = helps if kind == "HELP" else types
            assert name not in seen, f"duplicate # {kind} for {name}"
            seen[name] = ln
        else:
            assert sample_re.match(ln), f"bad sample line: {ln!r}"
            assert "{}" not in ln, f"empty label set rendered: {ln!r}"
    assert set(helps) == set(types)  # every metric gets exactly one of each

    # untagged series render bare names and merge across the two workers
    assert "w_restarts_total 2.0" in text
    assert 'w_latency_s_bucket{le="+Inf"} 5' in text
    assert "w_latency_s_count 5" in text
    # tagged series stay distinct
    assert 'w_reqs_total{route="/a"} 2.0' in text
    assert 'w_reqs_total{route="/b"} 2.0' in text
    assert 'w_step_s_bucket{le="0.1",route="/a"} 1' in text


def test_profiling_trace_and_annotation(tmp_path):
    """XPlane trace capture (SURVEY §5.1 — the TPU-native profiler path)."""
    import jax.numpy as jnp

    from ray_tpu.util import annotate, profile_trace

    logdir = str(tmp_path / "prof")
    with profile_trace(logdir):
        with annotate("matmul-region"):
            x = jnp.ones((64, 64))
            (x @ x).block_until_ready()
    dumped = []
    for root, _dirs, files in os.walk(logdir):
        dumped += [f for f in files if f.endswith(".xplane.pb")]
    assert dumped, "no xplane trace written"


def test_multiprocessing_pool_shim(ray_start_regular):
    """Drop-in multiprocessing.Pool over cluster actors (reference:
    python/ray/util/multiprocessing/pool.py surface)."""
    from ray_tpu.util.multiprocessing import Pool

    def square(x):
        return x * x

    def add(a, b):
        return a + b

    with Pool(processes=2) as pool:
        assert pool.map(square, range(10)) == [x * x for x in range(10)]
        assert pool.starmap(add, [(1, 2), (3, 4)]) == [3, 7]
        assert pool.apply(add, (5, 6)) == 11
        ar = pool.map_async(square, range(5))
        ar.wait(timeout=60)
        assert ar.ready() and ar.successful()
        assert ar.get(timeout=60) == [0, 1, 4, 9, 16]
        assert list(pool.imap(square, range(6), chunksize=2)) == \
            [0, 1, 4, 9, 16, 25]
        assert sorted(pool.imap_unordered(square, range(6), chunksize=2)) \
            == [0, 1, 4, 9, 16, 25]


def test_joblib_backend(ray_start_regular):
    """joblib parallel loops run as cluster tasks (reference:
    python/ray/util/joblib/)."""
    import math

    joblib = pytest.importorskip("joblib")
    from ray_tpu.util.joblib_backend import register_ray

    register_ray()
    with joblib.parallel_backend("ray_tpu", n_jobs=2):
        out = joblib.Parallel()(
            joblib.delayed(math.sqrt)(i ** 2) for i in range(10))
    assert out == [float(i) for i in range(10)]


def test_multiprocessing_pool_semantics(ray_start_regular):
    """mp.Pool parity details: original exception types re-raise, lazy
    imap over generators, close()+join() completes in-flight work."""
    import itertools

    from ray_tpu.util.multiprocessing import Pool

    def boom(x):
        raise ValueError(f"bad {x}")

    def slow_square(x):
        import time
        time.sleep(0.05)
        return x * x

    pool = Pool(processes=2)
    try:
        with pytest.raises(ValueError, match="bad 3"):
            pool.apply(boom, (3,))
        # lazy imap: an infinite generator yields incrementally
        it = pool.imap(slow_square, itertools.count(), chunksize=1)
        assert [next(it) for _ in range(5)] == [0, 1, 4, 9, 16]
    finally:
        pool.terminate()

    pool = Pool(processes=2)
    ar = pool.map_async(slow_square, range(8))
    pool.close()
    pool.join()  # must wait for the map, not kill it
    assert ar.get(timeout=60) == [x * x for x in range(8)]


def test_faultschedule_validates_and_fires_rpc_faults():
    """FaultSchedule unit semantics (no cluster needed): unknown kinds are
    rejected up front; rpc_delay flips `testing_rpc_failure` for its
    duration and RESTORES the previous value; the report records each
    event with its offset."""
    import time as _time

    from ray_tpu.core.config import get_config
    from ray_tpu.util.chaos import FaultSchedule

    with pytest.raises(ValueError):
        FaultSchedule(None, [(0.0, "bogus_kind", {})])

    cfg = get_config()
    prev = cfg.testing_rpc_failure
    sched = FaultSchedule(None, [
        (0.05, "rpc_delay", {"spec": "*:0:0:0.01", "duration_s": 0.4}),
    ], seed=1)
    sched.start()
    _time.sleep(0.25)
    assert cfg.testing_rpc_failure == "*:0:0:0.01"  # fault window active
    report = sched.join(timeout=10.0)
    assert cfg.testing_rpc_failure == prev          # restored after window
    assert len(report) == 1
    assert report[0]["kind"] == "rpc_delay"
    assert report[0]["ok"] is True
    assert report[0]["t"] == 0.05

    # stop() mid-schedule cancels pending events (deterministic teardown)
    sched2 = FaultSchedule(None, [
        (30.0, "rpc_drop", {"spec": "*:1.0", "duration_s": 1.0}),
    ], seed=2)
    sched2.start()
    assert sched2.stop() == []
    assert cfg.testing_rpc_failure == prev
