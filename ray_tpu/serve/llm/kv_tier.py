"""Cluster-wide tiered KV cache: spill evicted prefix pages, restore
anywhere.

PR 3's prefix cache is per-replica: a page chain evicted under pool
pressure is simply freed, and a cold replica re-prefills prefixes a
sibling already computed. This module keeps those chains alive in two
lower tiers and publishes them cluster-wide (Mooncake's KV-cache-centric
store, CacheGen's cache-across-machines result — see PAPERS.md):

- **shm tier**: spilled page chains are ``put()`` into the node's shm
  object plane (the same blob layout disagg's KV handoff ships:
  ``[L, Hkv, pages, page, D]`` per k/v). The store holds the ObjectRef,
  so the bytes stay pinned in shared memory until demoted or expired.
  Outside a cluster (unit tests, standalone engines) the tier degrades
  to an in-process dict with identical accounting.
- **disk tier**: a bounded local directory backs shm under pressure —
  the LRU shm blob demotes to disk instead of dying. Disk blobs are
  local-only: their cluster-index entries lose the object ref, so
  remote replicas skip them while the owner can still restore.
- **cluster index**: every spilled page registers a CP KV entry
  ``kv_tier:<chain-digest-hex>`` -> JSON {owner, node, ref, blob, off,
  tokens, nbytes, tier, ts, ttl_s}. The chain digest encodes the entire
  token prefix (kv_cache._chain_digest), so an index hit IS a token
  match. Entries are retracted when the owning worker or node dies
  (control_plane worker_died/_on_node_dead, exactly like the
  metrics-store GC) and lazily on TTL expiry (``ray-tpu kvtier --gc``).

Both caps are byte caps enforced at put time; eviction within a tier is
LRU; every entry carries a TTL. All failure paths degrade: a failed
spill leaves eviction a plain free, a failed restore is a plain cache
miss.
"""

from __future__ import annotations

import json
import logging
import os
import pickle
import threading
import time
import uuid
from collections import OrderedDict
from typing import Optional

import numpy as np

logger = logging.getLogger(__name__)

_KEY_PREFIX = "kv_tier:"


def _now() -> float:
    return time.time()


class KVTierStore:
    """Local spill store (shm + disk tiers) plus cluster-index client.

    One instance per engine. All device work stays in the engine — this
    class only ever sees host numpy blobs. Thread-safe; the engine loop
    is the only writer, stats/CLI readers may probe concurrently.
    """

    def __init__(self, max_bytes: int, disk_dir: Optional[str],
                 disk_max_bytes: int, ttl_s: float, page_size: int):
        self.max_bytes = int(max_bytes)
        self.disk_dir = disk_dir
        self.disk_max_bytes = int(disk_max_bytes)
        self.ttl_s = float(ttl_s)
        self.page_size = int(page_size)
        # distinct from the worker id: several engines (serve replicas,
        # tests) can share one worker process, and "is this entry mine"
        # must mean THIS store, while death-GC keys on the worker
        self.store_id = uuid.uuid4().hex[:12]
        self._lock = threading.Lock()
        # blob_id -> record; OrderedDict is the shm-tier LRU (disk-tier
        # records stay members but carry tier="disk")
        self._blobs: OrderedDict[str, dict] = OrderedDict()
        self._by_digest: dict[str, tuple[str, int]] = {}  # digest -> (blob, off)
        self._shm_bytes = 0
        self._disk_bytes = 0
        self.counters = {"put_blobs": 0, "put_pages": 0, "demoted_blobs": 0,
                         "dropped_blobs": 0, "expired_blobs": 0,
                         "local_hits": 0, "remote_hits": 0}

    # ---- runtime plumbing ----------------------------------------------
    @staticmethod
    def _runtime():
        from ray_tpu.core import api
        return api._try_get_runtime()

    def _cp_call(self, method: str, body, timeout: float = 5.0):
        rt = self._runtime()
        if rt is None:
            return None
        return rt.cp_client.call(method, body, timeout=timeout)

    # ---- spill ----------------------------------------------------------
    def put(self, k_np: np.ndarray, v_np: np.ndarray,
            digests: list[str], tokens: list[int]) -> int:
        """Store one spilled chain batch. ``k_np``/``v_np`` are host
        arrays shaped [L, Hkv, n, page, D]; ``digests[i]``/``tokens[i]``
        are page i's chain digest (hex) and its cumulative token length.
        Returns how many pages were registered (0 when the batch doesn't
        fit the shm cap at all)."""
        nbytes = int(k_np.nbytes) + int(v_np.nbytes)
        if nbytes > self.max_bytes or not digests:
            return 0
        blob = {"k": k_np, "v": v_np, "page_size": self.page_size,
                "digests": list(digests), "tokens": list(tokens)}
        bid = uuid.uuid4().hex[:16]
        rt = self._runtime()
        ref = rt.put(blob) if rt is not None else None
        rec = {"id": bid, "nbytes": nbytes, "tier": "shm", "ts": _now(),
               "digests": list(digests), "tokens": list(tokens),
               "ref": ref, "data": blob if ref is None else None,
               "path": None}
        with self._lock:
            self._expire_locked()
            while self._shm_bytes + nbytes > self.max_bytes:
                if not self._demote_oldest_locked():
                    break
            self._blobs[bid] = rec
            self._shm_bytes += nbytes
            for i, d in enumerate(digests):
                self._by_digest[d] = (bid, i)
            self.counters["put_blobs"] += 1
            self.counters["put_pages"] += len(digests)
        self._register_cp(rec)
        return len(digests)

    def _register_cp(self, rec: dict) -> None:
        """Publish every page of one blob into the CP ``kv_tier:``
        namespace. Best-effort — index registration must never break
        serving (an unregistered spill is still locally restorable)."""
        rt = self._runtime()
        if rt is None:
            return
        try:
            whex = rt.worker_id.hex()
            nhex = rt.node_id.hex() if rt.node_id is not None else ""
            ref_hex = (pickle.dumps(rec["ref"]).hex()
                       if rec["tier"] == "shm" and rec["ref"] is not None
                       else None)
            per_page = rec["nbytes"] // max(1, len(rec["digests"]))
            for i, d in enumerate(rec["digests"]):
                entry = {"owner": whex, "node": nhex,
                         "store": self.store_id, "blob": rec["id"],
                         "off": i, "tokens": rec["tokens"][i],
                         "nbytes": per_page, "tier": rec["tier"],
                         "ts": rec["ts"], "ttl_s": self.ttl_s,
                         "ref": ref_hex}
                self._cp_call("kv_put", {
                    "key": _KEY_PREFIX + d,
                    "value": json.dumps(entry).encode(),
                    "overwrite": True})
        except Exception:
            logger.debug("kv-tier: CP index registration failed",
                         exc_info=True)

    def _retract_cp(self, rec: dict) -> None:
        for d in rec["digests"]:
            try:
                self._cp_call("kv_del", {"key": _KEY_PREFIX + d},
                              timeout=2.0)
            except Exception:
                break  # CP gone; worker-death GC will sweep

    # ---- tier maintenance (lock held) -----------------------------------
    def _expire_locked(self) -> None:
        if self.ttl_s <= 0:
            return
        cutoff = _now() - self.ttl_s
        dead = [b for b, r in self._blobs.items() if r["ts"] < cutoff]
        for bid in dead:
            self._drop_locked(bid, reason="expired")

    def _demote_oldest_locked(self) -> bool:
        """Move the LRU shm blob down to the disk tier (or drop it when
        the disk tier is off/full-of-smaller-things)."""
        oldest = next((b for b, r in self._blobs.items()
                       if r["tier"] == "shm"), None)
        if oldest is None:
            return False
        rec = self._blobs[oldest]
        if (self.disk_dir is None
                or rec["nbytes"] > self.disk_max_bytes):
            self._drop_locked(oldest, reason="dropped")
            return True
        try:
            blob = self._load_blob_locked(rec)
            os.makedirs(self.disk_dir, exist_ok=True)
            path = os.path.join(self.disk_dir, rec["id"] + ".kvt")
            with open(path, "wb") as f:
                pickle.dump(blob, f)
        except Exception:
            logger.warning("kv-tier: demotion to disk failed; dropping",
                           exc_info=True)
            self._drop_locked(oldest, reason="dropped")
            return True
        while self._disk_bytes + rec["nbytes"] > self.disk_max_bytes:
            victim = next((b for b, r in self._blobs.items()
                           if r["tier"] == "disk"), None)
            if victim is None:
                break
            self._drop_locked(victim, reason="dropped")
        rec.update(tier="disk", path=path, ref=None, data=None)
        self._shm_bytes -= rec["nbytes"]
        self._disk_bytes += rec["nbytes"]
        self.counters["demoted_blobs"] += 1
        # remote replicas must stop trying to fetch the gone object ref
        threading.Thread(target=self._register_cp, args=(rec,),
                         daemon=True).start()
        return True

    def _drop_locked(self, bid: str, reason: str) -> None:
        rec = self._blobs.pop(bid, None)
        if rec is None:
            return
        if rec["tier"] == "shm":
            self._shm_bytes -= rec["nbytes"]
        else:
            self._disk_bytes -= rec["nbytes"]
            if rec["path"]:
                try:
                    os.unlink(rec["path"])
                except OSError:
                    pass
        for d in rec["digests"]:
            if self._by_digest.get(d, (None,))[0] == bid:
                del self._by_digest[d]
        self.counters["%s_blobs" % reason] += 1
        threading.Thread(target=self._retract_cp, args=(rec,),
                         daemon=True).start()

    def _load_blob_locked(self, rec: dict) -> dict:
        if rec["data"] is not None:
            return rec["data"]
        if rec["path"] is not None:
            with open(rec["path"], "rb") as f:
                return pickle.load(f)
        rt = self._runtime()
        if rt is None:
            raise RuntimeError("kv-tier blob held by ref but no runtime")
        return rt.get([rec["ref"]], timeout=10.0)[0]

    # ---- restore ---------------------------------------------------------
    def fetch_chain(self, digests: list[str], start: int):
        """Longest restorable run of chain pages beginning at ``start``.

        ``digests`` are the prompt's full-page chain digests (hex),
        position 0 first. Local tiers are probed before the cluster
        index; a local run and a remote run are never mixed. Returns
        ``(t, k_np, v_np)`` with the arrays shaped [L, Hkv, t, page, D],
        or ``(0, None, None)``."""
        run: list[tuple[str, int]] = []
        with self._lock:
            self._expire_locked()
            i = start
            while i < len(digests):
                loc = self._by_digest.get(digests[i])
                if loc is None:
                    break
                run.append(loc)
                i += 1
            if run:
                # touch for LRU recency, then assemble under the lock so
                # a concurrent demotion can't pull a blob out from under
                # the reads
                parts_k, parts_v = [], []
                blobs: dict[str, dict] = {}
                for bid, off in run:
                    if bid not in blobs:
                        self._blobs.move_to_end(bid)
                        blobs[bid] = self._load_blob_locked(self._blobs[bid])
                    parts_k.append(blobs[bid]["k"][:, :, off:off + 1])
                    parts_v.append(blobs[bid]["v"][:, :, off:off + 1])
                self.counters["local_hits"] += len(run)
                return (len(run), np.concatenate(parts_k, axis=2),
                        np.concatenate(parts_v, axis=2))
        return self._fetch_remote(digests, start)

    def _fetch_remote(self, digests: list[str], start: int):
        rt = self._runtime()
        if rt is None:
            return 0, None, None
        resp = self._cp_call("kv_tier_match", {"digests": digests[start:]})
        raw = (resp or {}).get("entries") or []
        entries = []
        for v in raw:
            try:
                e = json.loads(v.decode() if isinstance(v, bytes) else v)
            except (ValueError, AttributeError):
                break
            # disk-tier entries are owner-local; our own stale entries
            # (already missed the local probe above) are unusable too
            if e.get("tier") != "shm" or not e.get("ref") \
                    or e.get("store") == self.store_id:
                break
            entries.append(e)
        if not entries:
            return 0, None, None
        refs: dict[str, object] = {}
        for e in entries:
            if e["ref"] not in refs:
                refs[e["ref"]] = pickle.loads(bytes.fromhex(e["ref"]))
        fetched = rt.get(list(refs.values()), timeout=15.0)
        blobs = dict(zip(refs.keys(), fetched))
        parts_k, parts_v = [], []
        for e in entries:
            blob = blobs[e["ref"]]
            off = int(e["off"])
            parts_k.append(blob["k"][:, :, off:off + 1])
            parts_v.append(blob["v"][:, :, off:off + 1])
        with self._lock:
            self.counters["remote_hits"] += len(entries)
        return (len(entries), np.concatenate(parts_k, axis=2),
                np.concatenate(parts_v, axis=2))

    # ---- observability / lifecycle --------------------------------------
    def stats(self) -> dict:
        with self._lock:
            shm = sum(1 for r in self._blobs.values() if r["tier"] == "shm")
            return {**self.counters,
                    "shm_bytes": self._shm_bytes,
                    "disk_bytes": self._disk_bytes,
                    "blobs_shm": shm,
                    "blobs_disk": len(self._blobs) - shm,
                    "indexed_pages": len(self._by_digest)}

    def close(self) -> None:
        """Drop every blob and retract our index entries (clean engine
        shutdown; crash cleanup is the CP's worker-death GC)."""
        with self._lock:
            for bid in list(self._blobs):
                self._drop_locked(bid, reason="dropped")
