"""Exception hierarchy.

TPU-native analog of the reference's exception surface
(/root/reference/python/ray/exceptions.py).
"""

from __future__ import annotations

import traceback


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """Wraps an exception raised inside a remote task. Getting the object
    re-raises at the caller (ref: exceptions.py RayTaskError)."""

    def __init__(self, cause: BaseException | None = None, task_repr: str = "",
                 formatted: str | None = None):
        self.cause = cause
        self.task_repr = task_repr
        if formatted is None and cause is not None:
            formatted = "".join(
                traceback.format_exception(type(cause), cause, cause.__traceback__)
            )
        self.formatted = formatted or ""
        super().__init__(f"task {task_repr} failed:\n{self.formatted}")

    def __reduce__(self):
        # The cause may not be picklable; keep the formatted traceback.
        try:
            import cloudpickle
            cloudpickle.dumps(self.cause)
            cause = self.cause
        except Exception:
            cause = None
        return (type(self), (cause, self.task_repr, self.formatted))


class WorkerCrashedError(RayTpuError):
    """The worker process executing the task died (ref: WorkerCrashedError)."""


class ActorDiedError(RayTpuError):
    """The actor is dead; pending and future calls fail
    (ref: exceptions.py ActorDiedError / RayActorError)."""

    def __init__(self, msg: str = "The actor died.", actor_id=None):
        super().__init__(msg)
        self.actor_id = actor_id


class ActorUnavailableError(RayTpuError):
    """The actor is temporarily unreachable (restarting)."""


class ObjectLostError(RayTpuError):
    """Object was lost (all copies evicted/failed) and could not be
    reconstructed (ref: ObjectLostError)."""

    def __init__(self, object_id_hex: str = "", msg: str = ""):
        super().__init__(msg or f"Object {object_id_hex} was lost.")
        self.object_id_hex = object_id_hex


class ObjectStoreFullError(RayTpuError):
    """The local shared-memory store is out of memory."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """ray_tpu.get timed out."""


class DeadlineExceededError(RayTpuError, TimeoutError):
    """The request's end-to-end deadline passed (core/deadline.py).

    Raised when work is refused at admission because its deadline already
    expired, or when a wait bounded by the remaining deadline ran out.
    Carried inside TaskError when an executor sheds an expired TaskSpec."""


class TaskCancelledError(RayTpuError):
    """The task was cancelled (ref: TaskCancelledError)."""


class PendingCallsLimitExceeded(RayTpuError):
    """Actor max_pending_calls exceeded."""


class RuntimeEnvSetupError(RayTpuError):
    """Runtime environment failed to set up."""


class NodeDiedError(RayTpuError):
    """A node (agent) died."""


class PlacementGroupSchedulingError(RayTpuError):
    """Placement group could not be scheduled (infeasible)."""


class OutOfMemoryError(RayTpuError):
    """Worker was killed by the memory monitor (ref: OutOfMemoryError)."""
