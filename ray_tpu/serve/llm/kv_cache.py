"""Paged KV cache + paged attention steps for continuous batching.

The TPU-native analog of vLLM's PagedAttention (the reference delegates to
it — python/ray/llm/_internal/serve/deployments/llm/vllm/vllm_engine.py:101):
KV lives in a fixed pool of fixed-size pages in HBM; each decode slot owns a
page table mapping logical sequence positions to pool pages. All shapes are
static (slot count, page count, pages-per-slot), so the decode step compiles
ONCE and every iteration reuses the same XLA program — the crucial property
on TPU, where recompilation would dwarf the step itself.

Design choices:
- attention over the paged pool dispatches through ONE backend switch
  (``LLMConfig.attention_kernel``, resolved once by
  :func:`resolve_attention_backend`): ``"pallas"`` runs the fused kernel
  family in ray_tpu/ops/paged_attention.py — decode, multi-query verify,
  and chunked prefill all read K/V pages directly from the pool via the
  slot page table (scalar-prefetch block index maps; no materialized
  gather per layer per step) and reproduce the gather path's dense-softmax
  numerics bit-exactly; ``"gather"`` materializes the full per-slot view
  + dense softmax (measured 84 ms/step vs a paged kernel's 25 ms for a
  1.2B model at B=32). Auto resolution picks pallas on TPU (when the
  kernel's tiling accepts the shapes) and gather elsewhere; tests force
  the pallas backend in interpreter mode on CPU;
- writes are scatters at (page, offset) index pairs; inactive slots write to
  a reserved trash page (page 0), keeping the step free of dynamic shapes
  and `lax.cond`s;
- full (non-chunked) prefill stays dense within the prompt: it runs at
  B=1 per admission with no cached prefix to read back;
- tensor parallelism (ISSUE 20): every step function takes an optional
  ``mesh``. With a live "tensor" axis the pool is sharded per-KV-head
  (axis 1) and the q heads split into exactly the matching kv-head
  groups (GQA head order is kv-major), so per-head attention has ZERO
  cross-shard communication; only the wo/w_down row-parallel psums and
  the vocab-sharded argmax cross chips. The gather backend partitions
  under plain GSPMD/pjit; the Pallas kernels are opaque to GSPMD and run
  under ``shard_map`` — each shard's kernel invocation is shape-wise
  identical to the single-chip call on a pool with Hkv/tp heads.

Page 0 is RESERVED as the trash page; the allocator never hands it out.
"""

from __future__ import annotations

import hashlib
import logging
import threading
from collections import OrderedDict

import numpy as np

import jax
import jax.numpy as jnp

from ray_tpu.models.llama import (
    LlamaConfig,
    _gqa_expand,
    apply_rope,
    rms_norm,
    rope_freqs,
)

logger = logging.getLogger(__name__)


def init_paged_cache(cfg: LlamaConfig, num_pages: int, page_size: int):
    """KV pool: [n_layers, n_kv_heads, num_pages, page_size, head_dim].

    The head-major page layout is what the Pallas paged-attention decode
    kernel consumes directly (jax.experimental.pallas.ops.tpu.paged_attention
    — per layer [Hkv, P, page, D]), so decode on TPU runs the kernel with no
    relayout; the CPU fallback gathers through the same pool."""
    shape = (cfg.n_layers, cfg.n_kv_heads, num_pages, page_size, cfg.head_dim)
    return {"k": jnp.zeros(shape, cfg.dtype), "v": jnp.zeros(shape, cfg.dtype)}


def page_raw_nbytes(cfg: LlamaConfig, page_size: int) -> int:
    """Pre-codec bytes ONE pool page holds across all layers, k + v —
    the unit the tier spills and the restore stream lands. Derived from
    the pool spec (not a live array) so byte-budget callers (stream
    prefetch window, chunk sizing) can size before any page exists."""
    per = cfg.n_layers * cfg.n_kv_heads * page_size * cfg.head_dim
    return 2 * per * np.dtype(cfg.dtype).itemsize


def _chain_digest(parent: bytes, chunk) -> bytes:
    """Hash-chain node key for one FULL page of prompt tokens: digest of
    (parent page's digest, this page's token ids). Chaining makes the key
    encode the entire token prefix, so equal digests mean equal prefixes —
    the flat-dict equivalent of a radix-tree path (SGLang RadixAttention;
    vLLM's hash-based prefix caching uses the same chained-hash trick).
    blake2b-128 so a collision (which would silently serve the wrong KV)
    is cryptographically excluded rather than merely unlikely."""
    return hashlib.blake2b(
        parent + np.asarray(chunk, np.int32).tobytes(),
        digest_size=16).digest()


class PageAllocator:
    """Host-side free list + prefix cache over the page pool (page 0
    reserved as trash).

    Mirrors vLLM's BlockAllocator role; plain Python because allocation
    happens between steps, never inside the compiled program.

    Page counts here are WHOLE-REPLICA logical pages: under tensor
    parallelism (ISSUE 20) each page physically spans every shard
    (1/tp_degree of its bytes per chip), but the allocator, the page
    tables and every occupancy/free gauge derived from them count the
    logical page once. Per-shard byte views (dashboards sizing one
    chip's HBM) divide the replica's pool bytes by tp_degree — the
    engine exports that as ``kv_shard_pool_bytes``.

    Prefix caching: pages are REFCOUNTED, and full pages of prompt tokens
    can be registered in a hash-chained index (one node per full page,
    keyed on the chain digest of every token up to the page's end). A page
    whose refcount drops to zero while indexed is not returned to the free
    list — it parks in an LRU of cached pages, its KV content intact, and
    is either resurrected by a later ``match_prefix`` (refcount 1 again,
    shared) or evicted back to the free list under pool pressure. Because
    only refcount-zero pages are evictable, eviction can never free a page
    a live slot's page table still references.

    ``cache_pages`` caps how many refcount-zero cached pages are retained
    (0 = bounded only by the pool itself).

    Spilling (serve/llm/kv_tier.py): ``spill_hook``, when set, receives
    every ``(page, digest, chain_pos)`` evicted during one ``alloc()`` /
    ``free()`` call — after the allocator lock is released but BEFORE
    control returns to the caller, i.e. before the caller can dispatch
    device writes that reuse the freed pages (the hook's gather lands
    first on the ordered device stream). A raising hook is swallowed:
    the eviction has already completed, so behavior degrades to a plain
    free — no page leaks, no deadlock, just no spill.
    """

    def __init__(self, num_pages: int, cache_pages: int = 0):
        self._free = list(range(num_pages - 1, 0, -1))  # stack; never page 0
        self._lock = threading.Lock()
        self.num_pages = num_pages
        self._cache_cap = int(cache_pages)
        self._ref: dict[int, int] = {}          # live page -> refcount
        self._index: dict[bytes, int] = {}      # chain digest -> page
        self._page_key: dict[int, bytes] = {}   # indexed page -> digest
        self._page_pos: dict[int, int] = {}     # indexed page -> chain pos
        self._lru: OrderedDict[int, None] = OrderedDict()  # ref-0 cached
        self.spill_hook = None
        self.counters = {"hit_pages": 0, "miss_pages": 0, "evicted": 0,
                         "inserted": 0}
        # monotone index version: bumps whenever the set of indexed
        # digests changes (insert or eviction). Lets prefix_summary()
        # callers skip re-reading an unchanged index — the affinity
        # summary export (ISSUE 10) polls this.
        self._version = 0

    # ---- allocation ----------------------------------------------------
    def _evict_one_locked(self, spilled: list | None = None) -> bool:
        """Drop the least-recently-used refcount-zero cached page back to
        the free list (its index node dies with it). Lock held. When a
        spill hook is installed, the page's (page, digest, chain_pos) is
        appended to ``spilled`` for the post-lock hook call."""
        if not self._lru:
            return False
        page, _ = self._lru.popitem(last=False)
        key = self._page_key.pop(page)
        pos = self._page_pos.pop(page, None)
        if self._index.get(key) == page:
            del self._index[key]
            self._version += 1
        if spilled is not None and self.spill_hook is not None:
            spilled.append((page, key, pos))
        self._free.append(page)
        self.counters["evicted"] += 1
        return True

    def _fire_spill_hook(self, spilled: list) -> None:
        hook = self.spill_hook
        if hook is None or not spilled:
            return
        try:
            hook(spilled)
        except Exception:  # noqa: BLE001 - spill is best-effort by contract
            logger.warning(
                "kv-tier spill hook failed; %d pages evicted without "
                "spilling", len(spilled), exc_info=True)

    def alloc(self, n: int) -> list[int] | None:
        """n fresh pages at refcount 1, evicting cached pages LRU-first
        under pressure; None when free + evictable can't cover n."""
        spilled: list = []
        with self._lock:
            if len(self._free) + len(self._lru) < n:
                return None  # can't be satisfied — don't evict for nothing
            while len(self._free) < n:
                self._evict_one_locked(spilled)
            out = [self._free.pop() for _ in range(n)]
            for p in out:
                self._ref[p] = 1
        self._fire_spill_hook(spilled)
        return out

    def free(self, pages: list[int]) -> None:
        """Decref; a page reaching zero parks in the cached LRU if indexed
        (content stays valid for later matches), else rejoins the free
        list. Safe against double-free of already-dead pages."""
        spilled: list = []
        with self._lock:
            for p in pages:
                if p == 0:
                    continue
                cur = self._ref.get(p)
                if cur is None:
                    # already dead: a double free must not re-append the
                    # page (duplicate free-list entries would hand one
                    # page to two requests)
                    continue
                if cur > 1:
                    self._ref[p] = cur - 1
                    continue
                del self._ref[p]
                if p in self._page_key:
                    self._lru[p] = None
                    self._lru.move_to_end(p)
                    while self._cache_cap > 0 \
                            and len(self._lru) > self._cache_cap:
                        self._evict_one_locked(spilled)
                else:
                    self._free.append(p)
        self._fire_spill_hook(spilled)

    def incref(self, pages: list[int]) -> None:
        with self._lock:
            for p in pages:
                if p != 0:
                    self._ref[p] = self._ref.get(p, 0) + 1

    def available(self) -> int:
        """Pages an alloc() could obtain: strictly-free + evictable
        cached. NOT the same as ``cache_stats()["free_pages"]`` — an
        evictable page still holds restorable KV content (and, with the
        kv tier on, spills on eviction); see cache_stats() for the
        three-way occupancy breakdown. Whole-replica logical pages
        (shard-count-independent; see the class docstring)."""
        with self._lock:
            return len(self._free) + len(self._lru)

    def refcount(self, page: int) -> int:
        """Current refcount of one page (0 = free or parked in the cached
        LRU). Inspection only — used by tests that pin allocator
        invariants, e.g. that a speculative verify-k rollback never
        releases a reference on a shared prefix page (rollback is pure
        seq-len accounting in the engine; no allocator call sites)."""
        with self._lock:
            return self._ref.get(page, 0)

    # ---- prefix index --------------------------------------------------
    def match_prefix(self, tokens, page_size: int) -> list[int]:
        """Longest indexed chain of FULL token pages that prefixes
        ``tokens``, capped so at least one token is left to prefill (the
        suffix pass is what produces the first sampled token). Matched
        pages are increffed (cached ref-0 pages resurrect from the LRU) —
        the caller owns one reference and releases it via free()."""
        limit = (len(tokens) - 1) // page_size
        out: list[int] = []
        if limit <= 0:
            return out
        with self._lock:
            digest = b""
            for i in range(limit):
                digest = _chain_digest(
                    digest, tokens[i * page_size:(i + 1) * page_size])
                page = self._index.get(digest)
                if page is None:
                    self.counters["miss_pages"] += 1
                    break
                out.append(page)
            for p in out:
                if p in self._lru:
                    del self._lru[p]
                self._ref[p] = self._ref.get(p, 0) + 1
            self.counters["hit_pages"] += len(out)
        return out

    def insert_prefix(self, tokens, pages: list[int],
                      page_size: int) -> int:
        """Register a request's FULL prompt pages in the index (pages[i]
        holds tokens [i*page_size, (i+1)*page_size)). First writer wins: a
        chunk whose digest is already indexed keeps the existing page (the
        duplicate page simply stays un-indexed and frees normally).
        Returns how many new nodes were added."""
        added = 0
        with self._lock:
            digest = b""
            for i in range(min(len(tokens) // page_size, len(pages))):
                digest = _chain_digest(
                    digest, tokens[i * page_size:(i + 1) * page_size])
                if digest in self._index:
                    continue
                page = pages[i]
                if page == 0 or page in self._page_key:
                    continue
                self._index[digest] = page
                self._page_key[page] = digest
                # chain position: the spill path needs each evicted
                # page's token length ((pos+1) * page_size) to register
                # it in the cluster index
                self._page_pos[page] = i
                added += 1
            self.counters["inserted"] += added
            if added:
                self._version += 1
        return added

    def insert_digest_chain(self, digests_hex: list[str], pages: list[int],
                            positions: list[int]) -> int:
        """Register pages under pre-computed chain digests — the warm-start
        twin of ``insert_prefix`` for restores that carry digests but no
        token ids (the CP ``kv_tier:`` index stores digests only; the
        tokens that produced them live on whatever replica spilled them).
        A digest uniquely determines the full token prefix it closes
        (``_chain_digest`` chains over every token), so a digest-keyed
        node is exactly as trustworthy as a token-keyed one.

        ``positions[i]`` is the page's chain position (tokens/page_size-1
        from the tier entry) — needed so prefix_summary's low-position-
        wins cut and the re-spill path see the right depth. First writer
        wins, same as insert_prefix; pages the caller alloc'd stay at
        refcount 1 and park in the cached LRU on the caller's free().
        Returns how many new index nodes were added."""
        added = 0
        with self._lock:
            for d_hex, page, pos in zip(digests_hex, pages, positions):
                try:
                    digest = bytes.fromhex(d_hex)
                except (ValueError, TypeError):
                    continue
                if digest in self._index:
                    continue
                if page == 0 or page in self._page_key:
                    continue
                self._index[digest] = page
                self._page_key[page] = digest
                self._page_pos[page] = int(pos)
                added += 1
            self.counters["inserted"] += added
            if added:
                self._version += 1
        return added

    def index_version(self) -> int:
        with self._lock:
            return self._version

    def prefix_summary(self, max_pages: int = 0) -> tuple[int, list[str]]:
        """(version, resident page-chain digests as hex) — the bounded
        summary the affinity router consumes (ISSUE 10). When the index
        exceeds ``max_pages`` (0 = unbounded), LOW chain positions win the
        cut: a leading page is what lets the router match any prefix at
        all, while a deep page is only reachable through the pages before
        it. Every digest here names a page whose KV is resident (live or
        parked in the cached LRU) — both are served by match_prefix."""
        with self._lock:
            ver = self._version
            items = list(self._page_key.items())  # (page, digest)
            if max_pages and len(items) > max_pages:
                items.sort(key=lambda it: self._page_pos.get(it[0], 0))
                items = items[:max_pages]
            return ver, [d.hex() for _, d in items]

    def match_digest_chain(self, digests_hex: list[str]) -> int:
        """Leading run of ``digests_hex`` resident in the index (no
        incref, no LRU touch — pure inspection, used to size a tier
        prefetch so it skips pages already local)."""
        n = 0
        with self._lock:
            for d in digests_hex:
                try:
                    if bytes.fromhex(d) not in self._index:
                        break
                except ValueError:
                    break
                n += 1
        return n

    def cache_stats(self) -> dict:
        """Snapshot for engine stats / metrics export.

        All counts are WHOLE-REPLICA logical pages: a TP engine's page
        spans every shard, but it is one page here — free/evictable/live
        never multiply (or divide) by tp_degree. Dashboards wanting one
        chip's view scale the engine's byte gauges, not these counts.

        Three distinct occupancy numbers — dashboards must not conflate
        them (eviction is non-destructive once spilling is on):

        - ``free_pages``: strictly free — on the free list, content dead,
          allocation costs nothing.
        - ``evictable_pages``: refcount-zero but cached — content is
          live, restorable KV; allocating them evicts (and, with the kv
          tier on, spills) first.
        - live/referenced pages: ``num_pages - 1 - free - evictable``
          (page 0 is the reserved trash page) — pinned by active slots,
          never evictable.

        ``available()`` = free_pages + evictable_pages.
        """
        with self._lock:
            return {**self.counters,
                    "free_pages": len(self._free),
                    "cached_pages": len(self._page_key),
                    "evictable_pages": len(self._lru),
                    "shared_pages": sum(1 for c in self._ref.values()
                                        if c > 1)}


# ---------------------------------------------------------------------------
# compiled steps
# ---------------------------------------------------------------------------


def _write_token_kv(k_cache, v_cache, k_new, v_new, page_idx, offset):
    """Scatter one token's k/v per slot into the layer's page pool.

    k_cache: [Hkv, P, page, D]; k_new: [B, Hkv, D]; page_idx/offset: [B].
    Slots write distinct pages (or the shared trash page), so the scatter is
    conflict-free for real slots.
    """
    k_cache = k_cache.at[:, page_idx, offset].set(
        jnp.swapaxes(k_new, 0, 1).astype(k_cache.dtype))
    v_cache = v_cache.at[:, page_idx, offset].set(
        jnp.swapaxes(v_new, 0, 1).astype(v_cache.dtype))
    return k_cache, v_cache


def _use_pallas_decode(cfg=None, page_size: int = 0) -> bool:
    """Kernel path gate: TPU backend + shapes the Pallas paged-attention
    kernels' tiling accepts (head_dim a multiple of 128, page a multiple of
    8). Tiny test models (head_dim 16-64) fall back to the gather path on
    real TPUs; in interpreter mode (CPU) every shape runs."""
    if jax.default_backend() != "tpu":
        return False
    if cfg is None:
        return True
    return cfg.head_dim % 128 == 0 and page_size % 8 == 0


def resolve_attention_backend(choice, cfg=None, page_size: int = 0) -> str:
    """Resolve ``LLMConfig.attention_kernel`` to a concrete backend.

    ``"auto"`` (default) picks ``"pallas"`` on TPU when the kernel tiling
    accepts the model's shapes and ``"gather"`` everywhere else (the
    interpreter-mode kernels are a correctness vehicle, not a CPU win).
    An explicit ``"pallas"`` is honored off-TPU (interpret mode — how
    tests gate the kernels on CPU) but degrades to ``"gather"`` on a TPU
    whose shapes the kernel can't tile, with a warning — serving a model
    beats serving an error."""
    if choice in (None, "", "auto"):
        return "pallas" if _use_pallas_decode(cfg, page_size) else "gather"
    if choice not in ("gather", "pallas"):
        raise ValueError(
            f"attention_kernel must be 'auto', 'gather' or 'pallas', "
            f"got {choice!r}")
    if choice == "pallas" and jax.default_backend() == "tpu" \
            and not _use_pallas_decode(cfg, page_size):
        logger.warning(
            "attention_kernel='pallas' requested but head_dim=%s/"
            "page_size=%s don't satisfy the kernel tiling; falling back "
            "to the gather backend", getattr(cfg, "head_dim", "?"),
            page_size)
        return "gather"
    return choice


def tp_degree(mesh) -> int:
    """Live tensor-parallel degree of a serving mesh (1 = no TP: no mesh,
    or a mesh whose "tensor" axis is size 1 — both compile the exact
    single-chip program)."""
    if mesh is None or "tensor" not in mesh.axis_names:
        return 1
    return int(mesh.shape["tensor"])


def _tp_pallas(fn, mesh, in_specs, out_specs):
    """Wrap a Pallas paged-attention call for a TP mesh: GSPMD cannot
    partition an opaque pallas_call, so the kernel family runs under
    ``shard_map`` with the pool split per-KV-head and q split into the
    matching kv-head groups. check=False: the kernel writes nothing
    replicated, and rep inference can't see through pallas anyway."""
    from ray_tpu.parallel.sharding import shard_map_compat
    return shard_map_compat(fn, mesh=mesh, in_specs=in_specs,
                            out_specs=out_specs, check=False)


def _decode_attention(q, k_cache, v_cache, page_tables, pos, cfg, page_size,
                      attn_backend: str = "gather", mesh=None):
    """Single-token attention over the paged KV for all slots.

    q: [B, H, D]; k_cache/v_cache: [Hkv, P, page, D]; pos: [B] (the new
    token's position — attend over 0..pos inclusive). The pallas backend
    runs the fused paged kernel (ops/paged_attention.py — reads only each
    sequence's live pages through the page table, same dense-softmax
    numerics as the gather path); the gather backend materializes the full
    [B, max_len] view — measured 84 ms/step for a 1.2B model at B=32 on
    one v5e (~17 GB/step of HBM traffic), which is why the kernel path
    exists."""
    b = q.shape[0]
    max_pages = page_tables.shape[1]
    max_len = max_pages * page_size
    if attn_backend == "pallas":
        from ray_tpu.ops import paged_attention as paged_ops

        def kernel(q, k_cache, v_cache, page_tables, pos):
            return paged_ops.paged_decode_attention(
                q, k_cache, v_cache, page_tables, pos,
                sm_scale=cfg.head_dim ** -0.5)

        if tp_degree(mesh) > 1:
            # q's H axis splits into whole kv-head groups (kv-major GQA
            # order), so each shard's kernel sees a self-contained
            # (Hkv/tp heads, n_rep q-heads each) problem — no collective
            in_specs, out_spec = paged_ops.tp_shard_specs(
                q_rank=3, n_replicated=2)
            return _tp_pallas(kernel, mesh, in_specs, out_spec)(
                q, k_cache, v_cache, page_tables, pos)
        return kernel(q, k_cache, v_cache, page_tables, pos)
    n_rep = q.shape[1] // k_cache.shape[0]
    sm = cfg.head_dim ** -0.5
    # gather: [Hkv, B, MP, page, D] -> [B, MP, page, Hkv, D] -> [B, L, Hkv, D]
    k_seq = jnp.moveaxis(
        jnp.take(k_cache, page_tables, axis=1), 0, 3).reshape(
        b, max_len, k_cache.shape[0], cfg.head_dim)
    v_seq = jnp.moveaxis(
        jnp.take(v_cache, page_tables, axis=1), 0, 3).reshape(
        b, max_len, v_cache.shape[0], cfg.head_dim)
    k_full = _gqa_expand(k_seq, n_rep)
    v_full = _gqa_expand(v_seq, n_rep)
    valid = jnp.arange(max_len)[None, :] <= pos[:, None]          # [B, L]
    logits = jnp.einsum("bhd,bkhd->bhk", q, k_full).astype(
        jnp.float32) * sm
    logits = jnp.where(valid[:, None, :], logits, -1e30)
    p = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhk,bkhd->bhd", p, v_full)


def paged_decode_step(params, kv, page_tables, seq_lens, tokens,
                      cfg: LlamaConfig, page_size: int,
                      attn_backend: str = "gather", mesh=None):
    """One fused decode step for all slots.

    tokens: [B] current token ids; seq_lens: [B] tokens already in cache
    (the new token lands at position seq_lens[b]); page_tables:
    [B, max_pages] pool page ids (trash page 0 for unused entries).
    Returns (logits [B, vocab], new_kv, new_seq_lens). Inactive slots should
    carry seq_lens pointing at trash-page positions; their logits are junk
    and the engine ignores them.
    """
    x = params["embed"][tokens[:, None]].astype(cfg.dtype)       # [B,1,D]
    cos, sin = rope_freqs(cfg, seq_lens[:, None])                # position = len
    pos = seq_lens
    page_idx = jnp.take_along_axis(
        page_tables, (pos // page_size)[:, None], axis=1)[:, 0]  # [B]
    offset = pos % page_size

    def body(carry, inputs):
        (x,) = carry
        layer, k_cache, v_cache = inputs
        h = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        q = jnp.einsum("btd,dhk->bthk", h, layer["attn"]["wq"])
        k = jnp.einsum("btd,dhk->bthk", h, layer["attn"]["wk"])
        v = jnp.einsum("btd,dhk->bthk", h, layer["attn"]["wv"])
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        k_cache, v_cache = _write_token_kv(
            k_cache, v_cache, k[:, 0], v[:, 0], page_idx, offset)
        attn = _decode_attention(
            q[:, 0], k_cache, v_cache, page_tables, pos, cfg,
            page_size, attn_backend, mesh)                        # [B,H,D]
        x = x + jnp.einsum("bhk,hkd->bd", attn, layer["attn"]["wo"])[:, None]
        h2 = rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
        gate = jax.nn.silu(h2 @ layer["mlp"]["w_gate"])
        up = h2 @ layer["mlp"]["w_up"]
        x = x + (gate * up) @ layer["mlp"]["w_down"]
        return (x,), (k_cache, v_cache)

    (x,), (new_k, new_v) = jax.lax.scan(
        body, (x,), (params["layers"], kv["k"], kv["v"]))
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = (x[:, 0] @ params["lm_head"]).astype(jnp.float32)
    return logits, {"k": new_k, "v": new_v}, seq_lens + 1


def paged_verify_step(params, kv, page_tables, seq_lens, tokens,
                      cfg: LlamaConfig, page_size: int,
                      attn_backend: str = "gather", mesh=None):
    """Speculative verify: T tokens per slot in ONE fused pass.

    tokens: [B, T] — slot b's current token followed by its T-1 drafted
    tokens; tokens[b, t] lands at position seq_lens[b] + t. All T
    positions are computed together (causal within the span, full
    attention over the paged cache), so the per-layer cache read happens
    ONCE per round instead of once per token — the decode pass is
    memory-bound, which is where verifying k drafts gets cheaper than k
    decode steps. logits[b, t] equals what paged_decode_step would
    produce after consuming tokens[b, :t+1] sequentially, which is what
    makes greedy speculative acceptance bit-identical to baseline decode.

    The pallas backend runs the fused MULTI-QUERY paged kernel — all k+1
    query positions per slot in one kernel launch, causal within the
    span, pages read through the page table (the TPU follow-up the
    single-query stock kernel deferred since PR 5). The gather backend
    materializes the [B, T, L] view — T times the decode fallback's
    traffic, bounded by small T (draft_len+1).
    Returns (logits [B, T, vocab], new_kv, seq_lens + T).
    """
    b, t = tokens.shape
    max_pages = page_tables.shape[1]
    max_len = max_pages * page_size

    x = params["embed"][tokens].astype(cfg.dtype)                 # [B,T,D]
    pos = seq_lens[:, None] + jnp.arange(t)[None, :]              # [B,T]
    cos, sin = rope_freqs(cfg, pos)
    page_idx = jnp.take_along_axis(page_tables, pos // page_size,
                                   axis=1)                        # [B,T]
    offset = pos % page_size
    kpos = jnp.arange(max_len)                                    # [L]
    # position t sees cache + the span's tokens 0..t (its own write)
    valid = kpos[None, None, :] <= pos[:, :, None]                # [B,T,L]
    sm = cfg.head_dim ** -0.5
    n_rep = cfg.n_heads // cfg.n_kv_heads

    def body(carry, inputs):
        (x,) = carry
        layer, k_cache, v_cache = inputs
        h = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        q = jnp.einsum("btd,dhk->bthk", h, layer["attn"]["wq"])
        k = jnp.einsum("btd,dhk->bthk", h, layer["attn"]["wk"])
        v = jnp.einsum("btd,dhk->bthk", h, layer["attn"]["wv"])
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        # write all T tokens' k/v, then attend through the paged view —
        # same write-then-gather shape as paged_prefill_chunk, batched.
        # Distinct slots write distinct pages and distinct t distinct
        # offsets, so the scatter is conflict-free for real slots.
        k_cache = k_cache.at[:, page_idx, offset].set(
            jnp.moveaxis(k, 2, 0).astype(k_cache.dtype))
        v_cache = v_cache.at[:, page_idx, offset].set(
            jnp.moveaxis(v, 2, 0).astype(v_cache.dtype))
        if attn_backend == "pallas":
            from ray_tpu.ops import paged_attention as paged_ops

            def kernel(q, k_cache, v_cache, page_tables, seq_lens):
                return paged_ops.paged_verify_attention(
                    q, k_cache, v_cache, page_tables, seq_lens,
                    sm_scale=sm)

            if tp_degree(mesh) > 1:
                in_specs, out_spec = paged_ops.tp_shard_specs(
                    q_rank=4, n_replicated=2)
                attn = _tp_pallas(kernel, mesh, in_specs, out_spec)(
                    q, k_cache, v_cache, page_tables, seq_lens)
            else:
                attn = kernel(q, k_cache, v_cache, page_tables, seq_lens)
        else:
            k_seq = jnp.moveaxis(
                jnp.take(k_cache, page_tables, axis=1), 0, 3).reshape(
                b, max_len, cfg.n_kv_heads, cfg.head_dim)
            v_seq = jnp.moveaxis(
                jnp.take(v_cache, page_tables, axis=1), 0, 3).reshape(
                b, max_len, cfg.n_kv_heads, cfg.head_dim)
            k_full = _gqa_expand(k_seq, n_rep)
            v_full = _gqa_expand(v_seq, n_rep)
            logits = jnp.einsum("bqhd,bkhd->bhqk", q, k_full).astype(
                jnp.float32) * sm
            logits = jnp.where(valid[:, None], logits, -1e30)
            p = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
            attn = jnp.einsum("bhqk,bkhd->bqhd", p, v_full)
        x = x + jnp.einsum("bthk,hkd->btd", attn, layer["attn"]["wo"])
        h2 = rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
        gate = jax.nn.silu(h2 @ layer["mlp"]["w_gate"])
        up = h2 @ layer["mlp"]["w_up"]
        x = x + (gate * up) @ layer["mlp"]["w_down"]
        return (x,), (k_cache, v_cache)

    (x,), (new_k, new_v) = jax.lax.scan(
        body, (x,), (params["layers"], kv["k"], kv["v"]))
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = (x @ params["lm_head"]).astype(jnp.float32)          # [B,T,V]
    return logits, {"k": new_k, "v": new_v}, seq_lens + t


def paged_prefill(params, kv, page_table, tokens, true_len,
                  cfg: LlamaConfig, page_size: int):
    """Prefill ONE slot's prompt into its pages.

    tokens: [1, T] (bucket-padded); page_table: [max_pages] for this slot;
    true_len: scalar actual prompt length. Returns (last-token logits
    [vocab], new_kv). Padding positions (>= true_len) write to the trash
    page via index clamping, so junk never lands in real pages.
    """
    t = tokens.shape[1]
    x = params["embed"][tokens].astype(cfg.dtype)                 # [1,T,D]
    positions = jnp.arange(t)[None, :]
    cos, sin = rope_freqs(cfg, positions)
    pos = jnp.arange(t)
    in_range = pos < true_len
    page_idx = jnp.where(in_range, jnp.take(page_table, pos // page_size), 0)
    offset = pos % page_size
    # causal mask for the in-prompt attention
    causal = pos[:, None] >= pos[None, :]
    sm = cfg.head_dim ** -0.5
    n_rep = cfg.n_heads // cfg.n_kv_heads

    def body(carry, inputs):
        (x,) = carry
        layer, k_cache, v_cache = inputs
        h = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        q = jnp.einsum("btd,dhk->bthk", h, layer["attn"]["wq"])
        k = jnp.einsum("btd,dhk->bthk", h, layer["attn"]["wk"])
        v = jnp.einsum("btd,dhk->bthk", h, layer["attn"]["wv"])
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        # dense causal attention within the prompt (prefill is compute-bound
        # and contiguous — no need to read back through pages)
        k_full = _gqa_expand(k, n_rep)
        v_full = _gqa_expand(v, n_rep)
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k_full).astype(
            jnp.float32) * sm
        logits = jnp.where(causal[None, None], logits, -1e30)
        p = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
        attn = jnp.einsum("bhqk,bkhd->bqhd", p, v_full)
        x = x + jnp.einsum("bthk,hkd->btd", attn, layer["attn"]["wo"])
        h2 = rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
        gate = jax.nn.silu(h2 @ layer["mlp"]["w_gate"])
        up = h2 @ layer["mlp"]["w_up"]
        x = x + (gate * up) @ layer["mlp"]["w_down"]
        # scatter the prompt's k/v into this slot's pages
        k_cache = k_cache.at[:, page_idx, offset].set(
            jnp.swapaxes(k[0], 0, 1).astype(k_cache.dtype))
        v_cache = v_cache.at[:, page_idx, offset].set(
            jnp.swapaxes(v[0], 0, 1).astype(v_cache.dtype))
        return (x,), (k_cache, v_cache)

    (x,), (new_k, new_v) = jax.lax.scan(
        body, (x,), (params["layers"], kv["k"], kv["v"]))
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    last = jnp.take_along_axis(
        x, jnp.maximum(true_len - 1, 0)[None, None, None], axis=1)[:, 0]
    logits = (last @ params["lm_head"]).astype(jnp.float32)[0]
    return logits, {"k": new_k, "v": new_v}


def paged_prefill_chunk(params, kv, page_table, tokens, start, true_len,
                        cfg: LlamaConfig, page_size: int,
                        attn_backend: str = "gather", mesh=None):
    """One CHUNK of a long prompt's prefill (chunked prefill: the engine
    interleaves prompt chunks with decode blocks so a long admission never
    stalls active generations for the whole prompt pass — the scheduling
    intent the reference delegates to vLLM's chunked-prefill/priority
    scheduler, vllm_engine.py:101).

    tokens: [1, C] the chunk (bucket-padded); start: scalar position of the
    chunk's first token; true_len: scalar total prompt length. The chunk's
    queries attend to every cached position < start (earlier chunks, read
    back through the page pool) plus causally within the chunk. Under the
    pallas backend the cached prefix is read page-by-page inside the fused
    chunk kernel instead of gathering the full paged view every chunk —
    the long-prompt suffix-prefill-after-tier-restore hot path. Returns
    (last-token logits [vocab] — meaningful only on the final chunk, new_kv).
    """
    b = 1
    c = tokens.shape[1]
    max_pages = page_table.shape[0]
    max_len = max_pages * page_size

    x = params["embed"][tokens].astype(cfg.dtype)                 # [1,C,D]
    pos = start + jnp.arange(c)                                   # [C]
    cos, sin = rope_freqs(cfg, pos[None, :])
    in_range = pos < true_len
    page_idx = jnp.where(in_range, jnp.take(page_table, pos // page_size), 0)
    offset = pos % page_size
    # keys: the whole paged view (earlier chunks + this one after write)
    kpos = jnp.arange(max_len)                                    # [L]
    valid = (kpos[None, :] <= pos[:, None]) & (kpos[None, :] < true_len)
    sm = cfg.head_dim ** -0.5
    n_rep = cfg.n_heads // cfg.n_kv_heads

    def body(carry, inputs):
        (x,) = carry
        layer, k_cache, v_cache = inputs
        h = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        q = jnp.einsum("btd,dhk->bthk", h, layer["attn"]["wq"])
        k = jnp.einsum("btd,dhk->bthk", h, layer["attn"]["wk"])
        v = jnp.einsum("btd,dhk->bthk", h, layer["attn"]["wv"])
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        # write the chunk's k/v first, then attend through the paged view —
        # the same write-then-gather shape as the decode fallback, so the
        # chunk sees earlier chunks AND itself causally. B=1 here, so the
        # gathered view is small (unlike batched decode, where the
        # materialized gather is why the Pallas kernel exists).
        k_cache = k_cache.at[:, page_idx, offset].set(
            jnp.swapaxes(k[0], 0, 1).astype(k_cache.dtype))
        v_cache = v_cache.at[:, page_idx, offset].set(
            jnp.swapaxes(v[0], 0, 1).astype(v_cache.dtype))
        if attn_backend == "pallas":
            from ray_tpu.ops import paged_attention as paged_ops

            def kernel(q, k_cache, v_cache, page_table, start, true_len):
                return paged_ops.paged_chunk_attention(
                    q, k_cache, v_cache, page_table, start, true_len,
                    sm_scale=sm)

            if tp_degree(mesh) > 1:
                in_specs, out_spec = paged_ops.tp_shard_specs(
                    q_rank=4, n_replicated=3)
                attn = _tp_pallas(kernel, mesh, in_specs, out_spec)(
                    q, k_cache, v_cache, page_table, start, true_len)
            else:
                attn = kernel(q, k_cache, v_cache, page_table, start,
                              true_len)
        else:
            k_seq = jnp.swapaxes(
                jnp.take(k_cache, page_table, axis=1).reshape(
                    cfg.n_kv_heads, max_len, cfg.head_dim), 0, 1)[None]
            v_seq = jnp.swapaxes(
                jnp.take(v_cache, page_table, axis=1).reshape(
                    cfg.n_kv_heads, max_len, cfg.head_dim), 0, 1)[None]
            k_full = _gqa_expand(k_seq, n_rep)
            v_full = _gqa_expand(v_seq, n_rep)
            logits = jnp.einsum("bqhd,bkhd->bhqk", q, k_full).astype(
                jnp.float32) * sm
            logits = jnp.where(valid[None, None], logits, -1e30)
            p = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
            attn = jnp.einsum("bhqk,bkhd->bqhd", p, v_full)
        x = x + jnp.einsum("bthk,hkd->btd", attn, layer["attn"]["wo"])
        h2 = rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
        gate = jax.nn.silu(h2 @ layer["mlp"]["w_gate"])
        up = h2 @ layer["mlp"]["w_up"]
        x = x + (gate * up) @ layer["mlp"]["w_down"]
        return (x,), (k_cache, v_cache)

    (x,), (new_k, new_v) = jax.lax.scan(
        body, (x,), (params["layers"], kv["k"], kv["v"]))
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    # last REAL token's position relative to this chunk's start
    rel = jnp.clip(true_len - 1 - start, 0, c - 1)
    last = jnp.take_along_axis(x, rel[None, None, None], axis=1)[:, 0]
    logits = (last @ params["lm_head"]).astype(jnp.float32)[0]
    return logits, {"k": new_k, "v": new_v}


def sample_tokens(logits, rng, temperature, top_k: int = 0):
    """Greedy/temperature/top-k sampling on device. logits: [B, V];
    temperature: [B] (0 → greedy)."""
    greedy = jnp.argmax(logits, axis=-1)
    if top_k and top_k > 0:
        vals, idx = jax.lax.top_k(logits, top_k)
        scaled = vals / jnp.maximum(temperature[:, None], 1e-6)
        choice = jax.random.categorical(rng, scaled, axis=-1)
        sampled = jnp.take_along_axis(idx, choice[:, None], axis=-1)[:, 0]
    else:
        scaled = logits / jnp.maximum(temperature[:, None], 1e-6)
        sampled = jax.random.categorical(rng, scaled, axis=-1)
    return jnp.where(temperature > 0, sampled, greedy)
