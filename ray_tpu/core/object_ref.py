"""ObjectRef — the distributed future handle.

TPU-native analog of the reference's ObjectRef (/root/reference/python/ray/includes/
object_ref.pxi and _raylet.pyx). Serializing a ref into a task argument or another
object registers a borrow with the owner via the runtime's reference counter
(ref: reference_count.cc borrowing protocol).
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Any

from ray_tpu.core.ids import ObjectID, WorkerID

if TYPE_CHECKING:
    from concurrent.futures import Future


class ObjectRef:
    __slots__ = ("_id", "_owner", "_owner_addr", "_skip_refcount", "__weakref__")

    def __init__(self, object_id: ObjectID, owner: WorkerID | None = None,
                 owner_addr: tuple[str, int] | None = None, *, _skip_refcount: bool = False):
        self._id = object_id
        self._owner = owner
        self._owner_addr = owner_addr
        self._skip_refcount = _skip_refcount
        if not _skip_refcount:
            _runtime_add_local_ref(self)

    def id(self) -> ObjectID:
        return self._id

    def hex(self) -> str:
        return self._id.hex()

    def binary(self) -> bytes:
        return self._id.binary()

    @property
    def owner(self) -> WorkerID | None:
        return self._owner

    @property
    def owner_addr(self) -> tuple[str, int] | None:
        return self._owner_addr

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, ObjectRef) and other._id == self._id

    def __hash__(self) -> int:
        return hash(self._id)

    def __repr__(self) -> str:
        return f"ObjectRef({self._id.hex()})"

    def __del__(self):
        if not self._skip_refcount:
            _runtime_remove_local_ref(self)

    def future(self) -> "Future":
        """Return a concurrent.futures.Future resolving to the value."""
        from ray_tpu.core import api
        return api._get_runtime().as_future(self)

    def __await__(self):
        import asyncio
        return asyncio.wrap_future(self.future()).__await__()

    def __reduce__(self):
        # Plain pickling (outside the runtime's serializer) round-trips the
        # identity without touching refcounts.
        return (_deserialize_ref_plain, (self._id, self._owner, self._owner_addr))


def _deserialize_ref_plain(object_id, owner, owner_addr):
    return ObjectRef(object_id, owner, owner_addr, _skip_refcount=True)


def _runtime_add_local_ref(ref: ObjectRef) -> None:
    from ray_tpu.core import api
    rt = api._try_get_runtime()
    if rt is not None:
        rt.reference_counter.add_local_ref(ref.id())


def _runtime_remove_local_ref(ref: ObjectRef) -> None:
    """__del__ side of refcounting — DEFERRED, never synchronous.

    A destructor runs wherever the garbage collector fires, i.e. inside
    ANY allocation — including while the current thread holds framework
    locks. A synchronous remove_local_ref from here re-enters the
    reference counter → on-zero → task manager/memory store on the same
    thread and self-deadlocks on their non-reentrant locks (observed: GC
    during TaskManager.add_pending's dict insert → release_lineage on the
    already-held lock wedged the whole process; the round-2 suite hang).
    So __del__ only enqueues the id; the runtime drains the queue from
    plain API call stacks that hold no locks.
    """
    try:
        from ray_tpu.core import api
        rt = api._try_get_runtime()
        if rt is None:
            return
        defer = getattr(rt, "defer_release", None)
        if defer is not None:
            defer(ref.id())
        else:
            # client-mode runtime: its ref counter only batches a release
            # RPC (no framework locks), so the synchronous path is safe
            rt.reference_counter.remove_local_ref(ref.id())
    except Exception:
        # interpreter shutdown or runtime already gone
        pass
