"""gRPC ingress for serve.

TPU-native analog of the reference's gRPCProxy
(/root/reference/python/ray/serve/_private/proxy.py:530 gRPCProxy; wire
protocol src/ray/protobuf/serve.proto:354): a generic-handler gRPC server —
no compiled service stubs needed — that routes unary calls to deployment
handles. The fully-qualified method name selects the handler method, and
request metadata selects the application / deployment / multiplexed model,
mirroring the reference's metadata keys.

Payloads are opaque bytes end-to-end (the reference passes user-defined
protobufs the same way): the deployment method receives the raw request
bytes and returns bytes/str (str is utf-8 encoded; other values are
pickled). `grpc.health.v1.Health/Check` answers SERVING for probes.
"""

from __future__ import annotations

import logging
import threading
from concurrent.futures import ThreadPoolExecutor

logger = logging.getLogger(__name__)

_HEALTH = "/grpc.health.v1.Health/Check"
# one-byte protobuf encoding of HealthCheckResponse{status: SERVING}
_HEALTH_SERVING = b"\x08\x01"


def _encode(out) -> bytes:
    if isinstance(out, bytes):
        return out
    if isinstance(out, bytearray):
        return bytes(out)
    if isinstance(out, str):
        return out.encode()
    import pickle
    return pickle.dumps(out)


class GrpcProxy:
    """(ref: gRPCProxy — one per node; here one server in this process)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 default_app: str = "default"):
        import grpc

        self._grpc = grpc
        self._default_app = default_app
        self._server = grpc.server(
            ThreadPoolExecutor(max_workers=16,
                               thread_name_prefix="grpc-ingress"))
        self._server.add_generic_rpc_handlers([_GenericHandler(self)])
        self.port = self._server.add_insecure_port(f"{host}:{port}")
        self._started = False
        self._lock = threading.Lock()

    def start(self) -> "GrpcProxy":
        with self._lock:
            if not self._started:
                self._server.start()
                self._started = True
        return self

    def stop(self) -> None:
        with self._lock:
            if self._started:
                self._server.stop(grace=1.0)
                self._started = False

    # -- routing --------------------------------------------------------
    def handle_unary(self, method: str, request: bytes, metadata: dict,
                     timeout_s: float = 60.0):
        """method: '/pkg.Service/Method' — Method maps to the deployment's
        handler method; metadata keys follow the reference proxy:
        application, deployment (optional: defaults to the app ingress),
        multiplexed_model_id, method_name (overrides the path's Method)."""
        from ray_tpu import serve

        if method == _HEALTH:
            return _HEALTH_SERVING
        app = metadata.get("application", self._default_app)
        call_method = metadata.get("method_name") \
            or method.rsplit("/", 1)[-1]
        deployment = metadata.get("deployment")
        if deployment:
            handle = serve.get_deployment_handle(deployment, app_name=app)
        else:
            handle = serve.get_app_handle(app)
        handle = handle.options(method_name=call_method)
        mux = metadata.get("multiplexed_model_id")
        if mux:
            handle = handle.options(multiplexed_model_id=mux)
        out = handle.remote(request).result(timeout_s=timeout_s)
        return _encode(out)


class _GenericHandler:
    """grpc.GenericRpcHandler accepting every unary method name."""

    def __init__(self, proxy: GrpcProxy):
        self._proxy = proxy

    def service(self, handler_call_details):
        import grpc

        method = handler_call_details.method
        metadata = {k: v for k, v in
                    (handler_call_details.invocation_metadata or ())}

        def unary_unary(request: bytes, context):
            try:
                # respect the client's deadline so hung deployments don't
                # pin server threads past the point anyone is listening
                # (and starve health checks); cap at 120s otherwise
                remaining = context.time_remaining()
                timeout_s = min(remaining, 120.0) if remaining is not None \
                    else 60.0
                return self._proxy.handle_unary(method, request, metadata,
                                                timeout_s=timeout_s)
            except KeyError as e:
                context.abort(grpc.StatusCode.NOT_FOUND, str(e))
            except Exception as e:  # noqa: BLE001 — surface to the client
                logger.exception("grpc ingress failure for %s", method)
                context.abort(grpc.StatusCode.INTERNAL, str(e))

        return grpc.unary_unary_rpc_method_handler(
            unary_unary,
            request_deserializer=None,   # raw bytes through
            response_serializer=None)


_grpc_proxy: GrpcProxy | None = None
_grpc_lock = threading.Lock()


def start_grpc_proxy(host: str = "127.0.0.1", port: int = 0,
                     default_app: str = "default") -> GrpcProxy:
    """Start (or return) the process's gRPC ingress."""
    global _grpc_proxy
    with _grpc_lock:
        if _grpc_proxy is None:
            _grpc_proxy = GrpcProxy(host, port, default_app).start()
        return _grpc_proxy


def _reset_grpc_proxy() -> None:
    global _grpc_proxy
    with _grpc_lock:
        if _grpc_proxy is not None:
            _grpc_proxy.stop()
            _grpc_proxy = None
