"""DeploymentHandle / DeploymentResponse.

TPU-native analog of the reference's handle API
(/root/reference/python/ray/serve/handle.py — DeploymentHandle:692,
DeploymentResponse:375): `handle.remote(...)` routes through the pow-2
router and returns a response future; responses can be passed as args to
other handles (composition) and awaited/`.result()`ed.
"""

from __future__ import annotations

import threading
from typing import Any, Optional

import ray_tpu
from ray_tpu.serve.router import Router

_routers: dict[str, Router] = {}
_routers_lock = threading.Lock()


def _router_for(app_name: str) -> Router:
    with _routers_lock:
        r = _routers.get(app_name)
        if r is None:
            from ray_tpu.serve.controller import get_or_create_controller
            r = Router(get_or_create_controller(), app_name)
            _routers[app_name] = r
        return r


def _reset_routers():
    with _routers_lock:
        for r in _routers.values():
            r.stop()  # kills the long-poll thread; orphans would spin forever
        _routers.clear()


class DeploymentResponse:
    """Future for one request (reference DeploymentResponse)."""

    def __init__(self, ref, streaming: bool = False):
        self._ref = ref
        self._streaming = streaming

    def result(self, timeout_s: Optional[float] = None) -> Any:
        out = ray_tpu.get(self._ref, timeout=timeout_s)
        return out

    def __await__(self):
        return self._ref.__await__()

    @property
    def ref(self):
        return self._ref


class DeploymentResponseGenerator:
    def __init__(self, ref):
        self._ref = ref

    def __iter__(self):
        if hasattr(self._ref, "__next__"):
            # streaming-generator call: chunks land as the replica yields
            for item_ref in self._ref:
                yield ray_tpu.get(item_ref)
            return
        chunks = ray_tpu.get(self._ref)  # legacy list-returning replicas
        yield from chunks


class DeploymentHandle:
    """Callable handle to a deployment (reference DeploymentHandle:692)."""

    def __init__(self, deployment_name: str, app_name: str,
                 method_name: str = "__call__", *, stream: bool = False,
                 _timeout_s: float = 30.0, _multiplexed_model_id: str = "",
                 _prefix_digests: Optional[list] = None):
        self.deployment_name = deployment_name
        self.app_name = app_name
        self._method = method_name
        self._stream = stream
        self._timeout_s = _timeout_s
        self._multiplexed_model_id = _multiplexed_model_id
        self._prefix_digests = _prefix_digests

    def options(self, *, method_name: Optional[str] = None,
                stream: Optional[bool] = None,
                timeout_s: Optional[float] = None,
                multiplexed_model_id: Optional[str] = None,
                prefix_digests: Optional[list] = None) -> "DeploymentHandle":
        return DeploymentHandle(
            self.deployment_name, self.app_name,
            method_name if method_name is not None else self._method,
            stream=self._stream if stream is None else stream,
            _timeout_s=self._timeout_s if timeout_s is None else timeout_s,
            _multiplexed_model_id=(self._multiplexed_model_id
                                   if multiplexed_model_id is None
                                   else multiplexed_model_id),
            _prefix_digests=(self._prefix_digests
                             if prefix_digests is None else prefix_digests))

    def __getattr__(self, name: str) -> "DeploymentHandle":
        if name.startswith("_"):
            raise AttributeError(name)
        return self.options(method_name=name)

    def _resolve_args(self, args, kwargs):
        """Allow DeploymentResponse composition: pass the underlying ref so
        the arg resolves to the upstream result without blocking here."""
        def conv(v):
            if isinstance(v, DeploymentResponse):
                return v.ref
            return v
        return tuple(conv(a) for a in args), {k: conv(v)
                                              for k, v in kwargs.items()}

    def remote(self, *args, **kwargs):
        args, kwargs = self._resolve_args(args, kwargs)
        router = _router_for(self.app_name)
        if self._multiplexed_model_id:
            kwargs = {**kwargs,
                      "_multiplexed_model_id": self._multiplexed_model_id}
        if self._prefix_digests:
            # affinity routing for handle traffic (composition/bench): the
            # replica reuses these for its tier restore, same as HTTP
            kwargs = {**kwargs, "_prefix_digests": list(self._prefix_digests)}
        ref = router.assign(self.deployment_name, self._method, args, kwargs,
                            streaming=self._stream,
                            timeout_s=self._timeout_s,
                            multiplexed_model_id=self._multiplexed_model_id,
                            prefix_digests=self._prefix_digests)
        if self._stream:
            return DeploymentResponseGenerator(ref)
        return DeploymentResponse(ref)

    def __reduce__(self):
        return (DeploymentHandle,
                (self.deployment_name, self.app_name, self._method),
                {"_stream": self._stream, "_timeout_s": self._timeout_s,
                 "_multiplexed_model_id": self._multiplexed_model_id,
                 "_prefix_digests": self._prefix_digests})

    def __setstate__(self, state):
        self._stream = state["_stream"]
        self._timeout_s = state["_timeout_s"]
        self._multiplexed_model_id = state.get("_multiplexed_model_id", "")
        self._prefix_digests = state.get("_prefix_digests")
