"""Autoscaler tests (reference: autoscaler/v2 + fake_multi_node provider —
scale-up on unplaceable demand, scale-down on idle timeout, all without a
cloud)."""

import time

import pytest

import ray_tpu


def test_autoscaler_scale_up_and_down():
    from ray_tpu.autoscaler import Autoscaler, AutoscalerConfig, FakeNodeProvider
    from ray_tpu.core.cluster import Cluster

    ray_tpu.shutdown()
    cluster = Cluster()
    cluster.add_node(num_cpus=1)  # head-ish node, stays
    ray_tpu.init(address=cluster.address)
    provider = FakeNodeProvider(cluster.control_plane.addr)
    scaler = Autoscaler(
        cluster.control_plane.addr, provider,
        AutoscalerConfig(min_workers=0, max_workers=2,
                         node_resources={"CPU": 1, "accel": 1},
                         idle_timeout_s=1.0))
    try:
        # demand an actor needing a resource only autoscaled nodes provide
        @ray_tpu.remote(resources={"accel": 1})
        class A:
            def m(self):
                return "on-accel-node"

        a = A.remote()
        time.sleep(0.3)  # let the actor become pending demand
        scaler.update()
        assert provider.non_terminated_nodes(), "no node launched"
        assert ray_tpu.get(a.m.remote(), timeout=60) == "on-accel-node"
        assert scaler.num_launched == 1

        # release the demand; node should terminate after idle timeout
        ray_tpu.kill(a)
        deadline = time.monotonic() + 30
        while provider.non_terminated_nodes() and time.monotonic() < deadline:
            time.sleep(0.5)
            scaler.update()
        assert not provider.non_terminated_nodes(), "idle node not reclaimed"
        assert scaler.num_terminated == 1
    finally:
        scaler.stop()
        ray_tpu.shutdown()
        cluster.shutdown()


def test_autoscaler_respects_max_workers():
    from ray_tpu.autoscaler import Autoscaler, AutoscalerConfig, FakeNodeProvider
    from ray_tpu.core.cluster import Cluster

    ray_tpu.shutdown()
    cluster = Cluster()
    cluster.add_node(num_cpus=1)
    ray_tpu.init(address=cluster.address)
    provider = FakeNodeProvider(cluster.control_plane.addr)
    scaler = Autoscaler(
        cluster.control_plane.addr, provider,
        AutoscalerConfig(max_workers=1, node_resources={"CPU": 1, "gp": 1}))
    try:
        @ray_tpu.remote(resources={"gp": 1})
        class B:
            def m(self):
                return 1

        actors = [B.remote() for _ in range(4)]  # demand for 4 nodes
        time.sleep(0.3)
        for _ in range(3):
            scaler.update()
        assert len(provider.non_terminated_nodes()) == 1  # capped
        assert ray_tpu.get(actors[0].m.remote(), timeout=60) == 1
    finally:
        scaler.stop()
        ray_tpu.shutdown()
        cluster.shutdown()
