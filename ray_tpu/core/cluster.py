"""In-process simulated multi-node cluster for tests.

TPU-native analog of the reference's cluster_utils
(/root/reference/python/ray/cluster_utils.py — Cluster:135, add_node:202,
remove_node:286): N real node agents (each with its own shm store and real
worker subprocesses) against one control plane, all on one host — so
distributed scheduling and fault-tolerance tests run without hardware
(SURVEY.md §4 keystone (a)). TPU slice topologies are faked via node labels,
giving the fake slice-topology provider SURVEY.md §4 calls for.
"""

from __future__ import annotations

from ray_tpu.core.control_plane import ControlPlane
from ray_tpu.core.ids import NodeID
from ray_tpu.core.node_agent import NodeAgent


class Cluster:
    def __init__(self):
        self.control_plane = ControlPlane()
        self.nodes: list[NodeAgent] = []

    @property
    def address(self) -> str:
        return f"{self.control_plane.addr[0]}:{self.control_plane.addr[1]}"

    def add_node(self, *, num_cpus: float = 1.0, resources: dict | None = None,
                 labels: dict | None = None,
                 object_store_memory: int | None = None,
                 tpu_slice: str | None = None, tpu_worker_id: int = 0,
                 tpu_chips: int = 4, pod_type: str = "v5p-16") -> NodeAgent:
        """Add a node. ``tpu_slice`` fakes TPU slice membership via labels."""
        res = dict(resources or {})
        res.setdefault("CPU", float(num_cpus))
        lab = dict(labels or {})
        if tpu_slice is not None:
            res.setdefault("TPU", float(tpu_chips))
            lab.update({"slice_name": tpu_slice, "tpu_worker_id": str(tpu_worker_id),
                        "pod_type": pod_type, "topology": ""})
        agent = NodeAgent(self.control_plane.addr, resources=res, labels=lab,
                          object_store_memory=object_store_memory)
        self.nodes.append(agent)
        return agent

    def remove_node(self, agent: NodeAgent, graceful: bool = False):
        """Kill a node (ref: cluster_utils.py:286). Non-graceful stops the
        agent cold so health checks must detect the death."""
        if agent in self.nodes:
            self.nodes.remove(agent)
        if graceful:
            try:
                self.control_plane._h_drain_node({"node_id": agent.node_id})
            except Exception:
                pass
        agent.stop()

    def kill_node_by_id(self, node_id: NodeID):
        for agent in list(self.nodes):
            if agent.node_id == node_id:
                self.remove_node(agent)
                return

    def shutdown(self):
        for agent in list(self.nodes):
            agent.stop()
        self.nodes.clear()
        self.control_plane.stop()
