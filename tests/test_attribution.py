"""Per-request critical-path attribution tests (ISSUE 12): timeline
assembly and stage ordering, engine stage mapping, fleet aggregation,
the bounded CP exemplar store (oldest-first eviction + dead-worker
retraction), and an end-to-end SLO-violating request whose full ordered
timeline reaches the store."""

import json
import time
import urllib.request

import pytest

import ray_tpu


# ---------------------------------------------------------------------------
# unit: timeline + engine stage mapping (no cluster)

def test_timeline_note_merges_into_route_stamp():
    from ray_tpu.observability.attribution import Timeline

    tl = Timeline("req1", app="a", deployment="d")
    tl.note(demotion="spillover")
    tl.note(replica="rep-a", matched_pages=3)
    tl.stamp("route", 10.0, 10.01, attempt=1)
    (route,) = tl.stages
    assert route["stage"] == "route"
    assert route["attrs"]["demotion"] == "spillover"
    assert route["attrs"]["matched_pages"] == 3
    assert route["attrs"]["attempt"] == 1
    assert tl.replica == "rep-a"
    assert tl.route_attrs == {}  # consumed by the stamp


def test_timeline_orders_stages_canonically():
    from ray_tpu.observability.attribution import Timeline

    tl = Timeline("req2")
    # stamped in arrival order, not canonical order (engine stages land
    # last, a retry re-stamps route after queue)
    tl.stamp("ingress", 1.0, 1.001)
    tl.stamp("route", 1.001, 1.002)
    tl.extend([
        {"stage": "decode", "start": 1.2, "end": 1.3, "attrs": {}},
        {"stage": "queue", "start": 1.002, "end": 1.05, "attrs": {}},
        {"stage": "prefill", "start": 1.05, "end": 1.2, "attrs": {}},
    ])
    tl.stamp("route", 1.01, 1.02, attempt=2)
    names = [s["stage"] for s in tl.ordered_stages()]
    assert names == ["ingress", "route", "route", "queue", "prefill",
                     "decode"]
    # same-stage occurrences keep start order (retry after first attempt)
    routes = [s for s in tl.ordered_stages() if s["stage"] == "route"]
    assert routes[0]["start"] < routes[1]["start"]


def test_engine_stages_full_path_and_wall_mapping():
    from ray_tpu.observability import attribution

    stages = attribution.engine_stages(
        submitted_wall=1000.0, submitted_at=50.0, admitted_at=50.2,
        first_token_at=50.5, finished_at=50.9,
        cached_tokens=16, restored_tokens=32, restore_bytes=4096,
        restore_ms=100.0, prompt_tokens=64, generated_tokens=8,
        itl_s=0.05)
    names = [s["stage"] for s in stages]
    assert names == ["queue", "restore", "prefill", "decode"]
    queue, restore, prefill, decode = stages
    # monotonic -> wall: submitted_wall anchors the mapping
    assert queue["start"] == pytest.approx(1000.0)
    assert queue["end"] == pytest.approx(1000.2)
    assert queue["attrs"]["admitted"] is True
    assert restore["end"] == pytest.approx(1000.3)  # +100ms restore
    assert restore["attrs"]["restored_tokens"] == 32
    assert prefill["start"] == pytest.approx(restore["end"])
    assert prefill["end"] == pytest.approx(1000.5)
    assert prefill["attrs"]["prefilled_tokens"] == 48  # prompt - cached
    assert decode["start"] == pytest.approx(1000.5)
    assert decode["end"] == pytest.approx(1000.9)
    assert decode["attrs"]["itl_ms"] == pytest.approx(50.0)


def test_engine_stages_never_admitted_is_queue_only():
    from ray_tpu.observability import attribution

    stages = attribution.engine_stages(
        submitted_wall=time.time(), submitted_at=time.monotonic() - 1.0,
        admitted_at=None, first_token_at=None, finished_at=None)
    assert [s["stage"] for s in stages] == ["queue"]
    assert stages[0]["attrs"]["admitted"] is False


def test_engine_stages_no_restore_when_nothing_restored():
    from ray_tpu.observability import attribution

    stages = attribution.engine_stages(
        submitted_wall=1000.0, submitted_at=0.0, admitted_at=0.1,
        first_token_at=0.3, finished_at=0.4, prompt_tokens=8,
        generated_tokens=2)
    assert [s["stage"] for s in stages] == ["queue", "prefill", "decode"]


# ---------------------------------------------------------------------------
# unit: aggregation + span conversion

def _rec(rid, *, replica="rep-a", source="src01", kind="violation",
         violated=("ttft",), queue_ms=5.0, prefill_ms=50.0,
         decode_ms=20.0, matched_pages=0, deployment="llm"):
    t = 1000.0
    q1 = t + 0.002 + queue_ms / 1e3
    p1 = q1 + prefill_ms / 1e3
    d1 = p1 + decode_ms / 1e3
    stages = [
        {"stage": "ingress", "start": t, "end": t + 0.001, "attrs": {}},
        {"stage": "route", "start": t + 0.001, "end": t + 0.002,
         "attrs": {"replica": replica, "matched_pages": matched_pages}},
        {"stage": "queue", "start": t + 0.002, "end": q1,
         "attrs": {"admitted": True}},
        {"stage": "prefill", "start": q1, "end": p1,
         "attrs": {"cached_tokens": 0, "restored_tokens": 0,
                   "prefilled_tokens": 32}},
        {"stage": "decode", "start": p1, "end": d1,
         "attrs": {"generated_tokens": 8}},
    ]
    return {"request_id": rid, "ts": time.time(), "app": "app",
            "deployment": deployment, "replica": replica,
            "source": source, "kind": kind, "violated": list(violated),
            "ttft_ms": queue_ms + prefill_ms,
            "e2e_ms": queue_ms + prefill_ms + decode_ms,
            "policy": {"slo_ttft_p99_ms": 1.0}, "error": None,
            "trace_id": "", "stages": stages}


def test_aggregate_report_breakdown_and_skew():
    from ray_tpu.observability import attribution

    recs = (
        [_rec(f"a{i}", replica="rep-a", queue_ms=100.0, prefill_ms=10.0,
              matched_pages=4) for i in range(4)]
        + [_rec(f"b{i}", replica="rep-b", queue_ms=2.0, prefill_ms=60.0,
                kind="baseline", violated=()) for i in range(4)])
    rep = attribution.aggregate_report(recs)
    assert rep["count"] == 8
    assert rep["violations"] == 4
    for st in ("ingress", "route", "queue", "prefill", "decode"):
        assert rep["stage_ms"][st]["count"] == 8
    # the violating half is queue-dominated -> dominant-stage attribution
    assert rep["dominant_stage"] == {"queue": 4}
    skew = rep["replica_skew"]
    assert skew["rep-a"]["count"] == 4
    assert skew["rep-a"]["affinity_hit_share"] == 1.0
    assert skew["rep-b"]["affinity_hit_share"] == 0.0
    assert skew["rep-a"]["queue_wait_p50_ms"] > \
        skew["rep-b"]["queue_wait_p50_ms"]
    assert skew["rep-a"]["prefilled_tokens"] == 4 * 32


def test_aggregate_report_tail_fallback_without_violations():
    from ray_tpu.observability import attribution

    recs = [_rec(f"r{i}", kind="baseline", violated=(),
                 decode_ms=500.0 if i == 0 else 5.0) for i in range(10)]
    rep = attribution.aggregate_report(recs)
    assert rep["violations"] == 0
    # slowest decile (1 record) is decode-bound
    assert rep["dominant_stage"] == {"decode": 1}


def test_percentile_interpolates():
    from ray_tpu.observability.attribution import percentile

    vals = [float(v) for v in range(1, 101)]
    assert percentile(vals, 0.50) == pytest.approx(50.5)
    assert percentile(vals, 0.99) == pytest.approx(99.01)
    assert percentile([7.0], 0.95) == 7.0
    assert percentile([], 0.5) == 0.0


def test_stages_to_spans_renders_through_trace_tooling():
    from ray_tpu.observability import attribution, tracing

    rec = _rec("span01")
    spans = attribution.stages_to_spans(rec)
    root = spans[0]
    assert root["parent_id"] is None
    assert root["name"] == "request:span01"
    kids = spans[1:]
    assert len(kids) == len(rec["stages"])
    assert all(s["parent_id"] == root["span_id"] for s in kids)
    assert [s["name"] for s in kids] == \
        [f"stage:{st['stage']}" for st in rec["stages"]]
    # must be renderable by the PR-1 chrome-trace exporter unchanged
    chrome = tracing.to_chrome_trace(spans)
    assert len(chrome) == len(spans)
    assert all(ev["ph"] == "X" for ev in chrome)


# ---------------------------------------------------------------------------
# engine: queue-wait export (standalone engine, no cluster)

def test_engine_exports_queue_wait_and_stages():
    from ray_tpu.models import llama
    from ray_tpu.serve.llm import LLMConfig, LLMEngine

    eng = LLMEngine(LLMConfig(
        model_config=llama.llama_tiny(vocab_size=512),
        max_batch_size=4, page_size=16, num_pages=64,
        max_prompt_len=64, max_seq_len=128, max_tokens=8), rng_seed=0)
    eng.start()
    try:
        out = eng.generate("queue wait probe", max_tokens=4)
        assert out["queue_wait_s"] is not None
        assert out["queue_wait_s"] >= 0.0
        names = [s["stage"] for s in out["stages"]]
        assert names[0] == "queue"
        assert "prefill" in names and "decode" in names
        st = eng.engine_stats()
        assert "phase_queue_wait_p50_ms" in st
        assert "phase_queue_wait_p95_ms" in st
        # profiler on by default: the request above sampled the phase
        assert st["phase_queue_wait_p50_ms"] is not None
    finally:
        eng.shutdown()


# ---------------------------------------------------------------------------
# control plane: bounded exemplar store

@pytest.fixture(scope="module")
def slo_cluster():
    ray_tpu.shutdown()
    ctx = ray_tpu.init(num_cpus=64, _system_config={
        "health_check_period_s": 0.2,
        "health_check_failure_threshold": 3,
        # tiny cap so eviction is testable without 512 fixture records
        "slo_exemplar_max_records": 6,
    })
    yield ctx
    ray_tpu.shutdown()


def _cp():
    from ray_tpu.core import api
    return api._get_runtime().cp_client


def test_exemplar_store_bounded_evicts_oldest_first(slo_cluster):
    cp = _cp()
    for i in range(10):
        assert cp.call("report_slo_exemplar",
                       {"record": _rec(f"ev{i:02d}")})["ok"]
    from ray_tpu.util import state
    listed = [r["request_id"] for r in state.list_slo_exemplars(limit=50)]
    mine = sorted(r for r in listed if r.startswith("ev"))
    assert mine == [f"ev{i:02d}" for i in range(4, 10)]  # oldest 4 gone
    assert state.get_slo_exemplar("ev00") is None
    assert state.get_slo_exemplar("ev09")["request_id"] == "ev09"
    # the evicted records' KV summary keys went with them
    keys = cp.call("kv_keys", {"prefix": "slo_exemplar:ev"})
    assert sorted(keys) == [f"slo_exemplar:ev{i:02d}" for i in range(4, 10)]


def test_dead_worker_retracts_exemplars(slo_cluster):
    cp = _cp()
    from ray_tpu.util import state
    for rid in ("dw01", "dw02"):
        assert cp.call("report_slo_exemplar",
                       {"record": _rec(rid, source="deadbeefcafe")})["ok"]
    assert cp.call("report_slo_exemplar",
                   {"record": _rec("dw03", source="aliveworker1")})["ok"]
    assert state.get_slo_exemplar("dw01") is not None

    cp.call("worker_died", {"worker_id": "deadbeefcafe",
                            "reason": "test kill"})
    listed = {r["request_id"] for r in state.list_slo_exemplars(limit=50)}
    assert "dw01" not in listed and "dw02" not in listed
    assert "dw03" in listed  # other sources untouched
    assert state.get_slo_exemplar("dw01") is None
    keys = cp.call("kv_keys", {"prefix": "slo_exemplar:dw"})
    assert keys == ["slo_exemplar:dw03"]
    # late reports from the retracted worker are rejected, like late
    # metric flushes
    out = cp.call("report_slo_exemplar",
                  {"record": _rec("dw04", source="deadbeefcafe")})
    assert not out["ok"]


def test_slo_report_filters_by_deployment(slo_cluster):
    cp = _cp()
    from ray_tpu.util import state
    assert cp.call("report_slo_exemplar",
                   {"record": _rec("dep1", deployment="only-here",
                                   queue_ms=200.0)})["ok"]
    rep = state.slo_report(deployment="only-here")
    assert rep["count"] == 1
    assert rep["violations"] == 1
    assert rep["stage_ms"]["queue"]["p50"] == pytest.approx(200.0, rel=0.01)
    assert rep["dominant_stage"] == {"queue": 1}
    assert state.slo_report(deployment="no-such")["count"] == 0


# ---------------------------------------------------------------------------
# end to end: SLO-violating HTTP request -> complete ordered exemplar

def _http(url, payload, headers=None, timeout=120.0):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    return urllib.request.urlopen(req, timeout=timeout)


def test_slo_violation_produces_ordered_exemplar(slo_cluster):
    from ray_tpu import serve
    from ray_tpu.models import llama
    from ray_tpu.observability import attribution
    from ray_tpu.serve.llm import LLMConfig, build_openai_app
    from ray_tpu.util import state

    cfg = LLMConfig(
        model_config=llama.llama_tiny(vocab_size=512),
        max_batch_size=4, page_size=16, num_pages=64,
        max_prompt_len=64, max_seq_len=128, max_tokens=8,
        # unmeetable TTFT SLO: every request is a violation exemplar
        slo_ttft_p99_ms=0.001, slo_sample_rate=1.0)
    serve.run(build_openai_app(cfg, route_prefix="/v1"),
              name="llm-slo", route_prefix="/v1")
    proxy = serve.start_http_proxy(port=0)
    base = f"http://127.0.0.1:{proxy.port}"
    try:
        # client-supplied X-Request-Id is echoed AND names the exemplar
        with _http(f"{base}/v1/completions",
                   {"prompt": "hello slo", "max_tokens": 4},
                   headers={"X-Request-Id": "slotest0001"}) as r:
            assert r.status == 200
            assert r.headers.get("X-Request-Id") == "slotest0001"
            json.loads(r.read())
        # without one, the proxy mints an id on the response
        with _http(f"{base}/v1/completions",
                   {"prompt": "minted id", "max_tokens": 4}) as r:
            assert r.status == 200
            assert r.headers.get("X-Request-Id")
        # streaming responses carry the header too
        with _http(f"{base}/v1/completions",
                   {"prompt": "stream slo", "max_tokens": 4,
                    "stream": True},
                   headers={"X-Request-Id": "slostream01"}) as r:
            assert r.status == 200
            assert r.headers.get("X-Request-Id") == "slostream01"
            r.read()

        # the shipper is async (daemon thread -> CP): poll for arrival
        rec = None
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and rec is None:
            rec = state.get_slo_exemplar("slotest0001")
            if rec is None:
                time.sleep(0.2)
        assert rec is not None, "exemplar never reached the CP store"
        assert rec["kind"] == "violation"
        assert "ttft" in rec["violated"]
        assert rec["policy"]["slo_ttft_p99_ms"] == 0.001
        assert rec["deployment"]

        names = [s["stage"] for s in rec["stages"]]
        for want in ("ingress", "route", "queue", "prefill", "decode"):
            assert want in names, f"stage {want!r} missing from {names}"
        ranks = [attribution._STAGE_INDEX[n] for n in names
                 if n in attribution._STAGE_INDEX]
        assert ranks == sorted(ranks), f"stages out of order: {names}"
        route = next(s for s in rec["stages"] if s["stage"] == "route")
        assert "replica" in route["attrs"]
        assert rec["replica"] == route["attrs"]["replica"]

        # the streaming request's exemplar made it too
        deadline = time.monotonic() + 30.0
        srec = None
        while time.monotonic() < deadline and srec is None:
            srec = state.get_slo_exemplar("slostream01")
            if srec is None:
                time.sleep(0.2)
        assert srec is not None
        snames = [s["stage"] for s in srec["stages"]]
        assert "decode" in snames and "ingress" in snames
    finally:
        serve.shutdown()


def test_request_id_stable_across_midstream_failover(slo_cluster):
    """ISSUE 14 regression: a mid-stream failover must not re-mint the
    request identity — the client-supplied X-Request-Id survives the
    re-dispatch (response header), names the SLO exemplar record, and
    the exemplar's timeline carries an ordered `failover` stage."""
    import threading
    import uuid

    from ray_tpu import serve
    from ray_tpu.observability import attribution
    from ray_tpu.serve.controller import get_or_create_controller
    from ray_tpu.util import state

    serve.shutdown()

    @serve.deployment(num_replicas=2, health_check_period_s=0.2,
                      health_check_failure_threshold=3,
                      # unmeetable TTFT: the resumed stream must still
                      # ship a violation exemplar under its original id
                      slo_ttft_p99_ms=0.001, slo_sample_rate=1.0)
    class FlakyStream:
        def __init__(self):
            self._uid = uuid.uuid4().hex[:8]

        def whoami(self):
            return self._uid

        def handle_http(self, path, method, payload):
            if isinstance(payload, dict) and payload.get("stream"):
                return self._gen(payload)
            return {"uid": self._uid}

        async def _gen(self, payload):
            import asyncio
            start = len(payload.get("resume_tokens") or [])
            first = True
            for i in range(start, 12):
                chunk = {"choices": [{"text": f"t{i};", "index": 0,
                                      "finish_reason": None}],
                         "token_ids": [i], "rep": self._uid}
                if first and payload.get("resume_count"):
                    chunk["resume_meta"] = {
                        "resumed": True, "restored_tokens": start,
                        "restore_bytes": 0, "restore_ms": 0.0,
                        "cached_tokens": 0}
                first = False
                yield chunk
                await asyncio.sleep(0.15)
            yield {"choices": [{"text": "", "index": 0,
                                "finish_reason": "stop"}],
                   "ray_tpu": {"ttft_s": 0.01}}

    serve.run(FlakyStream.bind(), name="fo-rid", route_prefix="/forid")
    proxy = serve.start_http_proxy(port=0)
    base = f"http://127.0.0.1:{proxy.port}"
    rid = "foridstream01"
    chunks: list = []
    outcome: list = []

    def _stream():
        try:
            req = urllib.request.Request(
                f"{base}/forid/x", data=json.dumps(
                    {"stream": True}).encode(),
                headers={"Content-Type": "application/json",
                         "X-Request-Id": rid})
            with urllib.request.urlopen(req, timeout=120.0) as r:
                hdr = r.headers.get("X-Request-Id")
                for raw in r:
                    line = raw.decode().strip()
                    if line.startswith("data: ") and line != "data: [DONE]":
                        chunks.append(json.loads(line[len("data: "):]))
            outcome.append(hdr)
        except Exception as e:  # noqa: BLE001 — asserted below
            outcome.append(e)

    try:
        t = threading.Thread(target=_stream, daemon=True)
        t.start()
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline and sum(
                1 for c in list(chunks) if c.get("rep")) < 3:
            time.sleep(0.05)
        serving = next(c["rep"] for c in chunks if c.get("rep"))
        ctl = get_or_create_controller()
        import ray_tpu as _rt
        table = _rt.get(ctl.get_routing_table.remote("fo-rid"),
                        timeout=10.0)
        victim = None
        for entry in table.values():
            for h in entry[0]:
                if _rt.get(h.handle_request.remote("whoami", (), {}),
                           timeout=10.0) == serving:
                    victim = h
        assert victim is not None
        _rt.kill(victim)

        t.join(timeout=120.0)
        assert outcome and not isinstance(outcome[0], Exception), \
            f"stream failed: {outcome}"
        assert outcome[0] == rid  # header stable across the handoff

        # the exemplar lands under the SAME id, with a failover stage
        rec = None
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and rec is None:
            rec = state.get_slo_exemplar(rid)
            if rec is None:
                time.sleep(0.2)
        assert rec is not None, "resumed stream's exemplar never arrived"
        assert rec["request_id"] == rid
        names = [s["stage"] for s in rec["stages"]]
        assert "failover" in names, names
        ranks = [attribution._STAGE_INDEX[n] for n in names
                 if n in attribution._STAGE_INDEX]
        assert ranks == sorted(ranks), f"stages out of order: {names}"
        fo = next(s for s in rec["stages"] if s["stage"] == "failover")
        assert fo["attrs"]["resumed"] is True
        assert fo["attrs"]["attempt"] == 1
    finally:
        serve.shutdown()
