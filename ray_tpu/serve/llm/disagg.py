"""Prefill/decode disaggregated serving.

TPU-native analog of the reference's prefill-decode disaggregation
(python/ray/llm/_internal/serve/deployments/prefill_decode_disagg/
prefill_decode_disagg.py:1): prefill replicas run ONLY the prompt pass and
hand the resulting KV pages to decode replicas, which run ONLY the
continuous-batching token loop. Prefill is compute-bound and bursty; decode
is memory-bandwidth-bound and steady — separating them lets each replica
pool scale and batch independently.

KV handoff rides the OBJECT PLANE (the reference uses vLLM KV-transfer
connectors/NIXL): the prefill replica extracts the request's KV pages to
host memory, the blob travels as a task return through the shared-memory
object store (chunked cross-node pulls when the pools live on different
hosts), and the decode replica scatters it into its own paged pool with a
donated-buffer jitted program (no full-pool copy per injection).

Pieces:
- ``prefill_only(engine, ...)``     — prompt pass + KV extraction on a
  NON-started LLMEngine (prefill replicas have no decode loop).
- ``DecodeEngine.submit_prefilled`` — admits a prefilled request into the
  decode loop: allocates slot+pages, scatters the KV blob, continues from
  the handed-off first token.
- ``build_disagg_openai_app``       — OpenAI ingress whose completions
  path is prefill-replica → KV blob → local decode engine.

Fleet path (ISSUE 16): ``build_disagg_fleet_app`` lifts the handoff onto
the STREAMED object plane instead of a whole-blob transfer. Prefill
replicas gain ``prefill_stream``: the prompt pass's full KV pages spill
through the tier codec into a local KVTierStore and register in the CP
``kv_tier:`` index (namespace shared with decode engines via
``engine.kv_tier_namespace``); what returns is a LIGHT descriptor, not
the KV. The decode pool is plain tier-enabled ``LLMServer`` replicas
(``FleetDecodeServer``): an ordinary submit finds the prefill-registered
chain, opens a ``ChainStream`` and starts decoding as pages land — the
PR 15 ``_restoring`` machinery IS the handoff, so a dead prefill replica
mid-stream degrades to a partial restore + tail prefill instead of
failing the request. The proxy/router pick the branch per request
(``Router.disagg_plan`` when estimated prefill tokens exceed
``disagg_prompt_threshold``) and stamp an ordered ``prefill_remote``
attribution stage.

Prefix caching: the disagg path BYPASSES the prefix-cache index by
decision (``_disable_prefix_cache``), not by accident. Prefill replicas
allocate and free their pages inside one call, so nothing survives to
index; decode pools only ever receive handed-off KV blobs whose prompt
computation happened on another engine — indexing those pages would
advertise KV this engine never computed against its own admission path,
and the KV-handoff accounting (pool fully recycled per request) is an
invariant the disagg tests pin. Cross-replica prefix reuse belongs in the
prefill tier's router, not here.
"""

from __future__ import annotations

import dataclasses
import threading
import time
import uuid
from typing import Any, Optional

import numpy as np

from ray_tpu.serve.llm import llm_server as _llm_server
from ray_tpu.serve.llm.config import LLMConfig
from ray_tpu.serve.llm.engine import LLMEngine, _Request


def _disable_prefix_cache(cfg: LLMConfig) -> LLMConfig:
    """Disagg engines run with the prefix cache OFF (module docstring);
    returns the config unchanged when it already is."""
    if not cfg.prefix_cache_enabled:
        return cfg
    return dataclasses.replace(cfg, prefix_cache_enabled=False)


def _disable_spec_decode(cfg: LLMConfig) -> LLMConfig:
    """Prefill replicas run with speculative decoding OFF by decision
    (same pattern as the prefix cache): a prefill engine never enters the
    decode loop, so a verify-k program would only waste warmup compile
    time there. DECODE engines keep the caller's setting — handed-off
    requests satisfy the spec path's length invariant (seq_len ==
    prompt + generated - 1) exactly like locally prefilled ones."""
    if not cfg.spec_decode_enabled:
        return cfg
    return dataclasses.replace(cfg, spec_decode_enabled=False)


# ---------------------------------------------------------------------------
# handoff wire codec (ISSUE 16)
# ---------------------------------------------------------------------------

def _encode_state(state: dict, mode: str) -> dict:
    """Encode a handoff blob's KV pages for the wire (compiled-pipeline
    channel or object-plane task return). Pages encode independently —
    the same per-page layout the tier stores — so the decode side can
    reuse the one codec. ``none`` passes through untouched."""
    if mode == "none" or "kv_k" not in state:
        return state
    from ray_tpu.serve.llm import kv_codec
    n = int(state["n_pages"])
    pages = [(kv_codec.encode_page(state["kv_k"][:, :, i:i + 1], mode),
              kv_codec.encode_page(state["kv_v"][:, :, i:i + 1], mode))
             for i in range(n)]
    out = {k: v for k, v in state.items() if k not in ("kv_k", "kv_v")}
    out["enc_pages"] = pages
    out["wire_bytes"] = sum(
        kv_codec.encoded_nbytes(ek) + kv_codec.encoded_nbytes(ev)
        for ek, ev in pages)
    return out


def _decode_state(state: dict) -> dict:
    """Invert :func:`_encode_state`; raw blobs pass through (mixed-codec
    rollouts: the decode side accepts both shapes regardless of its own
    wire setting)."""
    if "enc_pages" not in state:
        return state
    from ray_tpu.serve.llm import kv_codec
    ks = [kv_codec.decode_page(ek) for ek, _ in state["enc_pages"]]
    vs = [kv_codec.decode_page(ev) for _, ev in state["enc_pages"]]
    out = {k: v for k, v in state.items() if k != "enc_pages"}
    out["kv_k"] = np.concatenate(ks, axis=2)
    out["kv_v"] = np.concatenate(vs, axis=2)
    return out


def int8_wire_divergence(ref_tokens, got_tokens) -> float:
    """Greedy-output divergence between a lossless-wire reference and an
    int8-wire run: fraction of positions that differ (length mismatch
    counts every unmatched position). The bench A/B arm feeds this to
    :func:`int8_wire_allowed`."""
    ref = list(ref_tokens or [])
    got = list(got_tokens or [])
    n = max(len(ref), len(got), 1)
    diff = sum(1 for a, b in zip(ref, got) if a != b) \
        + abs(len(ref) - len(got))
    return diff / n


def int8_wire_allowed(cfg: LLMConfig, measured_divergence: float) -> bool:
    """Per-deployment quality policy gating int8 on the disagg wire: the
    lossy codec is only policy-approved when the MEASURED divergence
    stays within the deployment's bound. The default bound (0.0) demands
    bit-identity — int8 never silently defaults on."""
    return float(measured_divergence) <= max(
        0.0, float(cfg.disagg_int8_max_divergence))


# ---------------------------------------------------------------------------
# prefill side
# ---------------------------------------------------------------------------

def prefill_only(eng: LLMEngine, prompt, *, temperature: float | None = None,
                 top_k: int | None = None) -> dict:
    """Run the prompt pass on a prefill-role engine and extract the KV.

    The engine must NOT have its decode loop started; calls are serialized
    on the engine lock (prefill replicas scale by replica count, not by
    intra-process concurrency — each call owns the chip while it runs).

    Returns a host-side handoff blob:
      {prompt_tokens, plen, n_pages, first_token, kv_k, kv_v,
       temperature, prefill_ttft_s}
    """
    jnp = eng._jnp
    t0 = time.monotonic()
    if isinstance(prompt, str):
        toks = eng.tokenizer.encode(prompt)
    else:
        toks = list(prompt)
    toks = toks[: eng.cfg.max_prompt_len]
    temperature = eng.cfg.temperature if temperature is None else temperature
    if top_k is not None and top_k != eng.cfg.top_k:
        pass  # sampling uses the engine top_k (static to the programs)

    plen = max(1, len(toks))
    n_pages = -(-plen // eng.cfg.page_size)
    if n_pages > eng.cfg.num_pages - 1:  # page 0 is the trash page
        raise ValueError(
            f"prompt needs {n_pages} KV pages but the pool has "
            f"{eng.cfg.num_pages - 1}; raise num_pages or page_size")
    with eng._lock:
        # each call allocates AND frees inside this lock scope, so the pool
        # is always fully free here — a failed alloc can never resolve by
        # waiting (hence the hard error above instead of a retry loop)
        pages = eng.allocator.alloc(n_pages)
        if pages is None:
            raise RuntimeError("prefill page pool unexpectedly exhausted")
        try:
            table = np.zeros((eng.max_pages_per_seq,), np.int32)
            table[:n_pages] = pages
            bucket = eng._bucket(plen)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :plen] = toks
            fn = eng._prefill_fn(bucket)
            eng._rng, sub = eng._jax.random.split(eng._rng)
            tok_dev, eng.kv = fn(
                eng.params, eng.kv, jnp.asarray(table), jnp.asarray(padded),
                jnp.int32(plen), sub,
                jnp.asarray([temperature], jnp.float32))
            # extract this request's pages to host (the handoff payload);
            # pool layout [L, Hkv, P, page, D] — pages are axis 2
            pidx = jnp.asarray(table[:n_pages], jnp.int32)
            kv_k = np.asarray(eng.kv["k"][:, :, pidx])
            kv_v = np.asarray(eng.kv["v"][:, :, pidx])
            first = int(tok_dev)
        finally:
            eng.allocator.free(pages)
        eng.stats["prefills"] += 1
    return {
        "prompt_tokens": toks, "plen": plen, "n_pages": n_pages,
        "first_token": first, "kv_k": kv_k, "kv_v": kv_v,
        "temperature": temperature,
        "prefill_ttft_s": time.monotonic() - t0,
    }


# ---------------------------------------------------------------------------
# decode side
# ---------------------------------------------------------------------------

class DecodeEngine(LLMEngine):
    """LLMEngine that can admit PREFILLED requests: the prompt KV arrives
    as a host blob and is scattered into the local paged pool; decode
    continues from the handed-off first token."""

    def __init__(self, cfg: LLMConfig, params=None, rng_seed: int = 0):
        super().__init__(_disable_prefix_cache(cfg), params=params,
                         rng_seed=rng_seed)
        self._inject_q: list[tuple[_Request, dict]] = []
        self._inject_fn = None

    def submit_prefilled(self, state: dict, *,
                         max_tokens: Optional[int] = None,
                         request_id: Optional[str] = None) -> str:
        state = _decode_state(state)  # wire-encoded blobs decode HERE
        toks = list(state["prompt_tokens"])
        req = _Request(
            request_id=request_id or uuid.uuid4().hex[:16],
            prompt_tokens=toks,
            max_tokens=max(1, min(max_tokens or self.cfg.max_tokens,
                                  self.cfg.max_seq_len - len(toks))),
            temperature=float(state.get("temperature", 0.0)),
            top_k=self.cfg.top_k,
            stop_token=getattr(self.tokenizer, "eos_token_id", None))
        req.dispatched = 1
        with self._lock:
            self._requests[req.request_id] = req
            self.stats["requests"] += 1
            # the first token already exists — record it through the normal
            # bookkeeping so stop/max handling is uniform
            self._record_token(req, int(state["first_token"]))
            if req.done:
                req.done_event.set()
                return req.request_id
            self._inject_q.append((req, state))
        self._wake.set()
        return req.request_id

    def _admissions_blocked(self) -> bool:
        # prefilled requests queued for injection count as blocked
        # admissions too: shrink decode blocks so their pages/slots free up
        # promptly (lock held by _step)
        return super()._admissions_blocked() or (
            bool(self._inject_q) and bool(self.free_slots))

    def engine_stats(self) -> dict:
        stats = super().engine_stats()
        stats["waiting"] += len(self._inject_q)
        return stats

    def _admit(self) -> int:
        admitted = super()._admit()
        while True:
            with self._lock:
                if not self._inject_q or not self.free_slots:
                    return admitted
                req, state = self._inject_q[0]
                need = -(-max(state["plen"] + req.max_tokens, 1)
                         // self.cfg.page_size)
                need = min(need, self.max_pages_per_seq)
                pages = self.allocator.alloc(need)
                if pages is None:
                    return admitted  # page pool exhausted; retry next loop
                self._inject_q.pop(0)
                slot = self.free_slots.pop()
                req.slot = slot
                req.pages = pages
            self._inject(req, state)
            admitted += 1

    def _inject(self, req: _Request, state: dict):
        """Scatter the handed-off KV pages into the local pool and arm the
        slot (loop thread only)."""
        jnp = self._jnp
        n_src = state["n_pages"]
        table = np.zeros((self.max_pages_per_seq,), np.int32)
        table[: len(req.pages)] = req.pages
        # pad the blob to max_pages_per_seq so ONE program shape covers
        # every prompt length (targets pad onto the trash page 0)
        mp = self.max_pages_per_seq
        # blob layout [L, Hkv, n_pages, page, D] — pad the page axis (2)
        pad = ((0, 0), (0, 0), (0, mp - n_src), (0, 0), (0, 0))
        blob_k = jnp.asarray(np.pad(state["kv_k"], pad))
        blob_v = jnp.asarray(np.pad(state["kv_v"], pad))
        tgt = np.zeros((mp,), np.int32)
        tgt[:n_src] = req.pages[:n_src]
        if self._inject_fn is None:
            jax = self._jax

            def impl(kv, bk, bv, pages):
                # donated pool: injection rewrites the pages in place
                # instead of copying the (GB-scale) pool per admission
                return {"k": kv["k"].at[:, :, pages].set(bk),
                        "v": kv["v"].at[:, :, pages].set(bv)}

            self._inject_fn = jax.jit(impl, donate_argnums=(0,))
        self.kv = self._inject_fn(self.kv, blob_k, blob_v,
                                  jnp.asarray(tgt, jnp.int32))
        with self._lock:
            self.page_tables[req.slot] = table
            self.seq_lens[req.slot] = state["plen"]
            self.slot_req[req.slot] = req
            self._dirty_slots[req.slot] = (state["plen"], req.temperature)
            # continue decoding from the handed-off first token
            self._overrides[req.slot] = int(state["first_token"])


# ---------------------------------------------------------------------------
# serve deployments
# ---------------------------------------------------------------------------

class PrefillServer:
    """Prefill-role replica: owns a non-started engine; each call runs one
    prompt pass and returns the KV handoff blob (reference: the "p" servers
    of prefill_decode_disagg)."""

    def __init__(self, llm_config: LLMConfig | dict):
        if isinstance(llm_config, dict):
            llm_config = LLMConfig(**llm_config)
        self.cfg = llm_config
        # loop NOT started; prefix cache + spec decode off (module
        # docstring / _disable_spec_decode)
        self.engine = LLMEngine(
            _disable_spec_decode(_disable_prefix_cache(llm_config)))
        # streamed-handoff tier store (ISSUE 16), built on first
        # prefill_stream: the engine's own tier requires the prefix
        # cache (off here by decision), so the prefill role spills
        # through a store of its own — SAME namespace as the decode
        # engines (kv_tier_namespace over the same config), which is
        # what makes the registrations restorable over there
        self._tier = None
        self._tier_lock = threading.Lock()

    def _tier_store(self):
        with self._tier_lock:
            if self._tier is None:
                from ray_tpu.serve.llm import kv_tier as kvt
                from ray_tpu.serve.llm.engine import kv_tier_namespace
                cfg = self.cfg
                self._tier = kvt.KVTierStore(
                    max_bytes=cfg.kv_tier_max_bytes,
                    disk_dir=None,  # handoffs are transient; no disk tier
                    disk_max_bytes=0,
                    ttl_s=cfg.kv_tier_ttl_s,
                    page_size=cfg.page_size,
                    namespace=kv_tier_namespace(
                        cfg, self.engine.model_cfg,
                        self.engine.kv["k"].dtype),
                    codec=cfg.kv_tier_codec)
            return self._tier

    def prefill(self, prompt, sampling: dict) -> dict:
        state = prefill_only(
            self.engine, prompt,
            temperature=sampling.get("temperature"),
            top_k=sampling.get("top_k"))
        return _encode_state(state, self.cfg.disagg_wire_codec)

    def prefill_one(self, req: dict) -> dict:
        """Single-argument stage entry for the compiled pipeline (the KV
        blob then rides the mutable-channel edge to the decode node instead
        of the object plane)."""
        return {"rid": req["rid"],
                "state": self.prefill(req["prompt"],
                                      req.get("sampling") or {})}

    def prefill_stream(self, subpath: str, payload: dict) -> dict:
        """Streamed fleet handoff (ISSUE 16): run the prompt pass, spill
        the full KV pages through the tier codec into this replica's
        store, and register them in the CP ``kv_tier:`` index. Returns a
        LIGHT descriptor — the KV itself travels later, chunk by chunk,
        when the decode replica's ``ChainStream`` pulls it.

        ``flush_index`` is the handshake that makes the return value
        mean something: once this call returns, the decode side's
        ``_match_entries`` can see every page, so the proxy may dispatch
        the decode leg immediately. KV pages are sampling-independent,
        so the decode leg re-applies the request's own sampling params.
        """
        from ray_tpu.serve import affinity
        prompt = affinity.prompt_from_payload(subpath, payload)
        if prompt is None:
            raise ValueError(f"no prompt in disagg prefill payload "
                             f"for route {subpath!r}")
        state = prefill_only(self.engine, prompt, temperature=0.0)
        ps = self.cfg.page_size
        toks = state["prompt_tokens"]
        full = len(toks) // ps
        registered = 0
        wire = 0
        if full > 0:
            tier = self._tier_store()
            digest = b""
            digs, tokens = [], []
            for i in range(full):
                digest = self.engine._kvc._chain_digest(
                    digest, toks[i * ps:(i + 1) * ps])
                digs.append(digest.hex())
                tokens.append((i + 1) * ps)
            with self._tier_lock:
                enc0 = tier.counters["put_bytes_enc"]
                registered = tier.put(
                    state["kv_k"][:, :, :full], state["kv_v"][:, :, :full],
                    digests=digs, tokens=tokens)
                wire = tier.counters["put_bytes_enc"] - enc0
            tier.flush_index(2.0)
        return {"plen": state["plen"], "pages_registered": int(registered),
                "wire_bytes": int(wire),
                "prefill_ttft_s": state["prefill_ttft_s"]}

    def wire_ratio_probe(self) -> float:
        """Measured raw/encoded ratio of this model's real prefill KV
        under the wire codec (one deterministic max-length prompt pass).
        Feeds `_handoff_channel_capacity`'s encoded sizing — a guess
        would either re-over-provision the channel or overflow it."""
        mode = self.cfg.disagg_wire_codec
        if mode == "none":
            return 1.0
        from ray_tpu.serve.llm import kv_codec
        vocab = max(2, int(getattr(self.engine.model_cfg,
                                   "vocab_size", 2)))
        toks = [(i * 37 + 11) % vocab
                for i in range(max(1, self.cfg.max_prompt_len))]
        state = prefill_only(self.engine, toks, temperature=0.0)
        raw = int(state["kv_k"].nbytes) + int(state["kv_v"].nbytes)
        enc = 0
        for i in range(state["n_pages"]):
            for a in (state["kv_k"], state["kv_v"]):
                enc += kv_codec.encoded_nbytes(
                    kv_codec.encode_page(a[:, :, i:i + 1], mode))
        return raw / max(1, enc)

    def engine_stats(self) -> dict:
        stats = {**self.engine.engine_stats(), "mode": "prefill"}
        if self._tier is not None:
            stats["handoff_bytes_wire"] = int(
                self._tier.counters["put_bytes_enc"])
        return stats

    def check_health(self) -> bool:
        return True


def _handoff_channel_capacity(cfg: LLMConfig,
                              measured_ratio: float | None = None) -> int:
    """Channel capacity sized for the largest KV handoff blob this config
    can produce (a max_prompt_len prompt's pages), not the default 8 MiB:
    k+v arrays are [L, Hkv, n_pages, page, D] in the model dtype, and
    Channel.write hard-fails on overflow — an undersized pipe would poison
    every later request on it.

    Since PR 15 the blob travels ENCODED (``disagg_wire_codec``), so raw
    model-dtype sizing over-provisions the channel by the codec ratio
    (~4–9× on bf16 KV). With a ``measured_ratio`` (raw/encoded, from
    ``PrefillServer.wire_ratio_probe`` on the real model) the capacity
    shrinks accordingly — but only trusting HALF the measured ratio and
    never dropping below raw sizing: the probe samples one prompt, other
    prompts compress worse, and overflow poisons the pipe while idle
    headroom only costs shm."""
    mc = cfg.llama()
    pages = -(-cfg.max_prompt_len // cfg.page_size)
    itemsize = np.dtype(getattr(mc, "dtype", np.float32)).itemsize
    kv_bytes = 2 * mc.n_layers * mc.n_kv_heads * pages * cfg.page_size \
        * mc.head_dim * itemsize  # k+v in the model dtype
    if cfg.disagg_wire_codec != "none":
        ratio = max(1.0, 0.5 * float(measured_ratio or 0.0))
        kv_bytes = int(kv_bytes / ratio)
    # prompt tokens + pickle/ndarray framing + slack
    return int(kv_bytes * 1.25) + (1 << 20)


class DisaggLLMServer:
    """Decode-role ingress: completions run prefill on a prefill replica,
    then decode locally from the handed-off KV (reference: the "d" servers
    + PDProxyServer routing).

    Two prefill transports:
    - ``prefill_handle``: a serve deployment handle; the KV blob travels as
      a task return through the object plane.
    - ``prefill_actors`` (compiled-pipeline path): raw prefill actors, each
      compiled into a CompiledPipeline whose prompt→KV edge is a mutable
      channel (agent-relayed across nodes) — the aDAG shape of the same
      handoff (reference compiled_dag_node.py:805 over
      experimental/channel)."""

    def __init__(self, llm_config: LLMConfig | dict, prefill_handle=None,
                 prefill_actors: list | None = None):
        if isinstance(llm_config, dict):
            llm_config = LLMConfig(**llm_config)
        self.cfg = llm_config
        self.prefill = prefill_handle
        self._pipes = []
        self._pipe_lock = threading.Lock()
        self._pipe_rr = 0
        self._rid = 0
        if prefill_actors:
            import ray_tpu
            from ray_tpu.dag import CompiledPipeline
            ratio = None
            if llm_config.disagg_wire_codec != "none":
                # size the channels from a MEASURED codec ratio (one real
                # prefill on actor 0) — conservative floor inside
                # _handoff_channel_capacity; a failed probe sizes raw
                try:
                    ratio = ray_tpu.get(
                        prefill_actors[0].wire_ratio_probe.remote(),
                        timeout=600.0)
                except Exception:  # noqa: BLE001 — raw sizing is safe
                    ratio = None
            cap = _handoff_channel_capacity(llm_config,
                                            measured_ratio=ratio)
            self._pipes = [
                CompiledPipeline([(a, "prefill_one")], capacity=cap).compile()
                for a in prefill_actors]
        self.engine = DecodeEngine(llm_config)
        self.engine.start()

    # ---- OpenAI surface (mirrors llm_server.LLMServer) ----------------
    def completions(self, payload: dict) -> Any:
        prompt = payload.get("prompt", "")
        if isinstance(prompt, list):
            prompt = prompt[0] if prompt else ""
        return self._run(prompt, payload, chat=False)

    def chat(self, payload: dict) -> Any:
        from ray_tpu.serve.llm.llm_server import _chat_prompt
        return self._run(_chat_prompt(payload.get("messages", [])),
                         payload, chat=True)

    def _pipeline_prefill(self, prompt, sampling: dict) -> dict:
        """Prefill through a compiled pipeline (round-robin over prefill
        stages); execute() raising over-capacity just means that pipe has
        its buffers full — try the next, else wait briefly."""
        deadline = time.monotonic() + 600.0
        while time.monotonic() < deadline:
            with self._pipe_lock:
                pipe = self._pipes[self._pipe_rr % len(self._pipes)]
                self._pipe_rr += 1
                self._rid += 1
                rid = self._rid
            try:
                ref = pipe.execute(
                    {"rid": rid, "prompt": prompt, "sampling": sampling})
            except RuntimeError:
                time.sleep(0.05)  # all slots busy: prefill is chip-bound
                continue
            out = ref.get(timeout=600.0)
            if out["rid"] != rid:
                # belt over the pipeline's write-order lock: a cross-wired
                # prefill would decode the WRONG prompt's KV silently
                raise RuntimeError(
                    f"prefill pipeline returned rid {out['rid']} for "
                    f"request {rid}")
            return out["state"]
        raise TimeoutError("prefill pipeline saturated for 600s")

    def _run(self, prompt, payload: dict, chat: bool) -> Any:
        from ray_tpu.serve.llm.llm_server import LLMServer
        sampling = {k: payload[k] for k in ("temperature", "top_k")
                    if payload.get(k) is not None}
        t0 = time.monotonic()
        if self._pipes:
            state = self._pipeline_prefill(prompt, sampling)
        else:
            state = self.prefill.options(
                method_name="prefill", timeout_s=600.0).remote(
                prompt, sampling).result(timeout_s=600.0)
        rid = self.engine.submit_prefilled(
            state, max_tokens=payload.get("max_tokens"))
        out = self.engine.result(rid, timeout=600.0)
        out["ttft_s"] = state["prefill_ttft_s"]
        out["latency_s"] = time.monotonic() - t0
        # reuse the OpenAI response shaping
        return LLMServer._completion_response(self, out, chat=chat)

    def models(self) -> dict:
        return {"object": "list",
                "data": [{"id": self.cfg.model_id, "object": "model",
                          "owned_by": "ray_tpu", "mode": "disagg"}]}

    def engine_stats(self) -> dict:
        from ray_tpu.serve.llm.llm_server import _export_engine_stats
        stats = {**self.engine.engine_stats(), "mode": "disagg"}
        _export_engine_stats(self.cfg.model_id, stats)
        return stats

    def check_health(self) -> bool:
        return True

    def handle_http(self, path: str, method: str, payload: Any) -> Any:
        path = "/" + path.strip("/")
        # chat first: "/chat/completions".endswith("/completions") is True
        if path.endswith("/chat/completions"):
            return self.chat(payload if isinstance(payload, dict) else {})
        if path.endswith("/completions"):
            return self.completions(
                payload if isinstance(payload, dict) else {})
        if path.endswith("/models"):
            return self.models()
        if path.endswith("/stats"):
            return self.engine_stats()
        return {"error": {"message": f"no route for {path}", "code": 404}}


def build_disagg_openai_app(llm_config: LLMConfig | dict,
                            route_prefix: str = "/v1",
                            num_prefill: int = 1, num_decode: int = 1,
                            prefill_actor_options: dict | None = None,
                            decode_actor_options: dict | None = None,
                            use_pipeline: bool = False):
    """Disaggregated OpenAI application: num_prefill prefill replicas feed
    num_decode decode ingress replicas (reference:
    prefill_decode_disagg.build_pd_app). With ``use_pipeline`` the
    prefill→decode handoff rides compiled mutable-channel pipelines
    (the aDAG path) instead of object-plane task returns."""
    import ray_tpu
    from ray_tpu import serve

    if isinstance(llm_config, dict):
        llm_config = LLMConfig(**llm_config)
    if use_pipeline:
        # raw prefill actors, compiled into pipelines by each decode server
        # (max_concurrency 2: the resident stage loop + health checks)
        opts = dict(prefill_actor_options or {})
        opts.setdefault("max_concurrency", 2)
        actors = [ray_tpu.remote(PrefillServer).options(**opts).remote(
            llm_config) for _ in range(num_prefill)]
        decode_dep = serve.deployment(
            DisaggLLMServer, name=f"{llm_config.name}-decode",
            num_replicas=num_decode,
            max_ongoing_requests=4 * llm_config.max_batch_size,
            ray_actor_options=dict(decode_actor_options or {}),
            health_check_timeout_s=600.0)
        decode_dep.route_prefix = route_prefix
        return decode_dep.bind(llm_config, None, actors)
    prefill_dep = serve.deployment(
        PrefillServer, name=f"{llm_config.name}-prefill",
        num_replicas=num_prefill,
        max_ongoing_requests=2,  # a prefill owns the chip while it runs
        ray_actor_options=dict(prefill_actor_options or {}),
        health_check_timeout_s=600.0)
    decode_dep = serve.deployment(
        DisaggLLMServer, name=f"{llm_config.name}-decode",
        num_replicas=num_decode,
        max_ongoing_requests=4 * llm_config.max_batch_size,
        ray_actor_options=dict(decode_actor_options or {}),
        health_check_timeout_s=600.0)
    decode_dep.route_prefix = route_prefix
    return decode_dep.bind(llm_config, prefill_dep.bind(llm_config))


# ---------------------------------------------------------------------------
# fleet disaggregation on the streamed KV plane (ISSUE 16)
# ---------------------------------------------------------------------------

class FleetDecodeServer(_llm_server.LLMServer):
    """Decode-role replica for the FLEET disagg path: a plain tier-
    enabled ``LLMServer`` — prefix cache ON, ordinary submit path — plus
    an ignored second init arg that anchors the prefill pool in the
    serve bind graph (``serve.run`` deploys bound sub-apps; the decode
    ingress never calls the prefill handle, the PROXY dispatches
    ``prefill_stream`` through the router's disagg plan). A real
    subclass, not a trampoline: the controller's ingress probe checks
    the CLASS for ``handle_http``."""

    def __init__(self, llm_config: LLMConfig | dict, prefill_handle=None):
        super().__init__(llm_config)


def build_disagg_fleet_app(llm_config: LLMConfig | dict,
                           route_prefix: str = "/v1",
                           num_prefill: int = 2, num_decode: int = 2,
                           prefill_actor_options: dict | None = None,
                           decode_actor_options: dict | None = None):
    """Fleet-level disaggregated application (ISSUE 16): ``num_prefill``
    prefill replicas (controller role ``prefill``) stream KV to
    ``num_decode`` tier-enabled decode replicas through the CP
    ``kv_tier:`` index. The decode deployment is the ingress; its config
    carries ``disagg_prefill_deployment`` + ``disagg_prompt_threshold``,
    which the replicas export via ``prefix_summary`` meta so the
    router's ``disagg_plan`` can take the third placement mode."""
    from ray_tpu import serve

    if isinstance(llm_config, dict):
        llm_config = LLMConfig(**llm_config)
    prefill_name = f"{llm_config.name}-prefill"
    decode_cfg = dataclasses.replace(
        llm_config,
        prefix_cache_enabled=True,
        kv_tier_enabled=True,
        disagg_prefill_deployment=prefill_name)
    prefill_dep = serve.deployment(
        PrefillServer, name=prefill_name,
        num_replicas=num_prefill,
        max_ongoing_requests=2,  # a prefill owns the chip while it runs
        ray_actor_options=dict(prefill_actor_options or {}),
        health_check_timeout_s=600.0)
    prefill_dep.config.role = "prefill"
    decode_dep = serve.deployment(
        FleetDecodeServer, name=llm_config.name,
        num_replicas=num_decode,
        max_ongoing_requests=4 * llm_config.max_batch_size,
        ray_actor_options=dict(decode_actor_options or {}),
        health_check_timeout_s=600.0)
    decode_dep.config.role = "decode"
    decode_dep.route_prefix = route_prefix
    return decode_dep.bind(decode_cfg, prefill_dep.bind(llm_config))
