"""Caller-side task submission pipelines.

TPU-native analog of the reference's task submission layer
(/root/reference/src/ray/core_worker/task_submission/):

- ``NormalTaskSubmitter`` (normal_task_submitter.h:82): lease workers from the
  node agent, push tasks caller→executor directly (the agent is not on the data
  path), cache granted leases and reuse idle workers for queued tasks of the
  same shape (OnWorkerIdle, normal_task_submitter.cc:139), handle spillback
  redirects, and retry on worker failure.
- ``ActorTaskSubmitter`` (actor_task_submitter.cc): per-actor ordered pipeline —
  sequence numbers assigned at submit, sends over one TCP connection preserve
  order (sequential_actor_submit_queue.cc), pending tasks resubmitted on actor
  restart or failed with ActorDiedError on death (SendPendingTasks :223,339).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field

from ray_tpu.core.config import get_config
from ray_tpu.core.ids import ActorID
from ray_tpu.core.task_spec import DefaultStrategy, TaskSpec
from ray_tpu.exceptions import ActorDiedError, TaskError, WorkerCrashedError

logger = logging.getLogger(__name__)


@dataclass
class _ShapeState:
    queue: deque = field(default_factory=deque)
    leases: list = field(default_factory=list)     # list[_Lease]
    requests_in_flight: int = 0
    strategy: object = None
    runtime_env: dict | None = None
    last_busy: float = 0.0  # ts of last busy (saturated) lease reply
    last_submit: float = 0.0  # ts of last submit() into this shape's queue


class _Flusher:
    """Rate-adaptive coalescing pump shared by both submitters: submit paths
    mark a key dirty and set the event; this thread drains dirty keys via
    the supplied callback until quiescent. A lone call finds the thread idle
    and ships immediately; a tight fan-out loop outruns the thread, so each
    drain picks up whatever accumulated — batching scales with submission
    pressure with no artificial delay. Also keeps frame pickling + sends off
    the submitting thread (normal_task_submitter.cc keeps submission
    non-blocking the same way via the asio io-service)."""

    def __init__(self, name: str, drain):
        self._name = name
        self._drain = drain
        self._lock = threading.Lock()
        self._dirty: set = set()
        self._event = threading.Event()
        self._stopped = False
        self._thread: threading.Thread | None = None

    def mark(self, key):
        with self._lock:
            self._dirty.add(key)
            # lazy pump start: a worker that never submits (the common case
            # for plain actors — thousands of them in the in-proc scale
            # harness) must not pay a resident thread for each submitter
            if self._thread is None and not self._stopped:
                self._thread = threading.Thread(
                    target=self._loop, name=self._name, daemon=True)
                self._thread.start()
        self._event.set()

    def stop(self):
        self._stopped = True
        self._event.set()

    def _loop(self):
        while True:
            self._event.wait()
            self._event.clear()
            if self._stopped:
                return
            while True:
                with self._lock:
                    dirty, self._dirty = self._dirty, set()
                if not dirty:
                    break
                for key in dirty:
                    if self._stopped:
                        return
                    self._drain(key)


def _take_batch(queue: deque, limit: int) -> list[TaskSpec]:
    """Pop up to ``limit`` specs for one frame, stopping BEFORE any spec
    that consumes a ref produced by a spec already in the batch. A frame's
    replies are aggregated into one response, so an intra-frame consumer
    would block resolving its arg while its producer's completed result sits
    unsent in the same aggregate — a head-of-line deadlock. Cross-frame
    dependencies are fine: each frame replies independently."""
    batch = [queue.popleft()]
    produced = {batch[0].task_id.binary()}
    while queue and len(batch) < limit:
        spec = queue[0]
        if any(r and r[0].task_id().binary() in produced
               for r in spec.ref_args()):
            break
        queue.popleft()
        batch.append(spec)
        produced.add(spec.task_id.binary())
    return batch


def _shape_key(spec: TaskSpec):
    """Tasks are queued per (resources, strategy, runtime_env) shape so a
    cached lease only serves tasks with identical placement constraints AND
    worker environment (reference worker_pool env-hash keying)."""
    from ray_tpu.runtime_env import env_hash
    pg = getattr(spec.strategy, "pg_id", None)
    idx = getattr(spec.strategy, "bundle_index", -1)
    s = spec.strategy
    strat_key: tuple = (type(s).__name__, env_hash(spec.runtime_env))
    if hasattr(s, "node_id_hex"):
        strat_key += (s.node_id_hex, s.soft)
    if hasattr(s, "hard"):
        strat_key += (frozenset(s.hard.items()), frozenset(s.soft.items()))
    return (frozenset(spec.resources.items()), pg, idx, strat_key)


@dataclass
class _Lease:
    lease_id: str
    agent_addr: tuple
    worker_addr: tuple
    worker_id: object
    inflight: int = 0  # pushed-not-replied tasks pipelined on this worker
    frames: int = 0    # batch frames in flight (≤ MAX_FRAMES_PER_WORKER)
    idle_since: float = 0.0  # monotonic ts when inflight last hit 0


class NormalTaskSubmitter:
    MAX_LEASES_PER_SHAPE = 16
    # Tasks pushed to one worker without waiting for replies (the reference's
    # max_tasks_in_flight_per_worker lease pipelining). Depth beyond 1 only
    # opens once no lease requests are outstanding — otherwise a 2-task burst
    # on a 2-node cluster would bind both tasks to the first granted worker
    # instead of spreading (and breadth is what the scheduler promised).
    MAX_INFLIGHT_PER_WORKER = 32
    # Queued bursts coalesce into one push_task_batch frame (amortizes
    # pickling, syscalls and handler dispatch — the interpreted-runtime
    # analog of the reference's cheap per-task C++ pushes). A sync
    # call-loop's queue never holds more than one task, so it still gets
    # per-task frames with no added latency.
    MAX_BATCH = 16
    # Frames in flight per worker: 2 keeps a frame queued executor-side
    # while the previous one runs (overlap), without deep HOL queues.
    MAX_FRAMES_PER_WORKER = 2
    # Granted leases linger briefly after their queue drains so sync
    # call-loops reuse a warm worker instead of re-leasing per task
    # (ref: worker lease idle keep-alive).
    IDLE_LEASE_TTL_S = 0.5

    def __init__(self, runtime):
        self._rt = runtime
        self._lock = threading.Lock()
        self._shapes: dict[object, _ShapeState] = {}
        self._lease_pool = ThreadPoolExecutor(max_workers=8, thread_name_prefix="lease")
        # reaper starts lazily with the first submission: a worker that
        # never submits tasks (most actors) holds no leases to reap, and a
        # resident 0.25s-tick thread per worker is real GIL churn when
        # thousands of in-proc workers share one interpreter
        self._reaper: threading.Thread | None = None
        self._stopped = threading.Event()
        self._flusher = _Flusher("task-flush", self._pump)

    def _ensure_reaper(self):
        if self._reaper is None and not self._stopped.is_set():
            self._reaper = threading.Thread(
                target=self._reap_idle_leases, name="lease-reaper", daemon=True)
            self._reaper.start()

    def _depth(self, st: _ShapeState) -> int:
        """Pipelining depth per held lease. With lease breadth still in
        flight, don't sink the whole queue into the first worker(s) — split
        it over EXPECTED breadth (held leases + in-flight requests), so an
        incoming grant still finds queued work. But never collapse to a
        hard 1: under saturation (busy cluster, many submitters) a request
        is ~always in flight and depth-1 serializes every pipeline on its
        reply RTT."""
        if st.requests_in_flight == 0:
            return self.MAX_INFLIGHT_PER_WORKER
        breadth = len(st.leases) + st.requests_in_flight
        return max(1, min(self.MAX_INFLIGHT_PER_WORKER,
                          -(-len(st.queue) // max(1, breadth))))

    def submit(self, spec: TaskSpec):
        key = _shape_key(spec)
        push = None
        with self._lock:
            self._ensure_reaper()
            st = self._shapes.setdefault(key, _ShapeState())
            st.strategy = spec.strategy
            st.runtime_env = spec.runtime_env
            st.last_submit = time.monotonic()
            # Fast path for interactive (sync call-loop) traffic: with
            # nothing queued or in flight for this shape, skip the flusher
            # handoff and push the singleton frame inline. Any concurrency
            # (in-flight work) routes through the flusher so bursts batch.
            if not st.queue and st.requests_in_flight == 0 and st.leases \
                    and all(l.inflight == 0 for l in st.leases):
                push = st.leases[0]
                push.inflight += 1
                push.frames += 1
            if push is None:
                st.queue.append(spec)
        if push is not None:
            self._push(key, push, [spec])
        else:
            self._flusher.mark(key)

    def _pump(self, key):
        """Dispatch queued tasks onto lease capacity; request more leases if
        the queue still has undispatchable work."""
        to_push = []
        new_requests = 0
        with self._lock:
            st = self._shapes.get(key)
            if st is None:
                return
            depth = self._depth(st)
            while st.queue and st.leases:
                open_leases = [l for l in st.leases
                               if l.frames < self.MAX_FRAMES_PER_WORKER
                               and l.inflight < depth]
                if not open_leases:
                    break
                lease = min(open_leases, key=lambda l: l.inflight)
                batch = _take_batch(
                    st.queue,
                    min(depth - lease.inflight, self.MAX_BATCH))
                lease.inflight += len(batch)
                lease.frames += 1
                to_push.append((lease, batch))
            new_requests = min(
                max(0, len(st.queue) - st.requests_in_flight),
                self.MAX_LEASES_PER_SHAPE
                - len(st.leases) - st.requests_in_flight)
            # The cluster just said it's saturated for this shape: don't
            # storm it with more lease requests; pipelining onto held leases
            # carries the queue meanwhile. With NO leases held there is
            # nothing to pipeline onto — retry much sooner or this shape
            # stalls in 0.5s sawtooths while competitors hold the workers.
            if time.monotonic() - st.last_busy < (0.5 if st.leases else 0.15):
                new_requests = 0
            if new_requests > 0:
                st.requests_in_flight += new_requests
        for lease, batch in to_push:
            self._push(key, lease, batch)
        for _ in range(max(0, new_requests)):
            self._lease_pool.submit(self._request_lease, key)

    def _reap_idle_leases(self):
        while not self._stopped.wait(0.25):
            now = time.monotonic()
            to_return = []
            repump = []
            with self._lock:
                for key, st in self._shapes.items():
                    for lease in list(st.leases):
                        if (lease.inflight == 0 and not st.queue
                                and now - lease.idle_since
                                > self.IDLE_LEASE_TTL_S):
                            st.leases.remove(lease)
                            to_return.append(lease)
                    # starvation guard: a queued shape with no outstanding
                    # lease requests re-pumps here — the busy-damping above
                    # deliberately drops requests, and nothing else re-arms
                    # a shape that holds zero leases
                    if st.queue and st.requests_in_flight == 0:
                        repump.append(key)
            for lease in to_return:
                self._return_lease(lease)
            for key in repump:
                self._pump(key)

    def _request_lease(self, key):
        resources, pg_id, bundle_index = dict(key[0]), key[1], key[2]
        agent_addr = self._rt.agent_addr
        cfg = get_config()
        granted = None
        with self._lock:
            st0 = self._shapes.get(key)
            strategy = st0.strategy if st0 else None
            runtime_env = st0.runtime_env if st0 else None
            # lease pool threads have no ambient span context; the head of
            # the queue is a representative parent for this lease round
            trace_parent = (getattr(st0.queue[0], "trace_ctx", None)
                            if st0 and st0.queue else None)
        lease_t0 = time.time()
        max_hops = 4
        try:
            if pg_id is not None:
                # PG bundles live on specific nodes; lease at the agent holding
                # the (committed) bundle (ref: the raylet lease request carries
                # the bundle id and the GCS placed it, bundle_spec.h)
                agent_addr = self._resolve_pg_agent(pg_id, bundle_index) or agent_addr
            elif strategy is not None and not isinstance(strategy, DefaultStrategy):
                # constrained strategies pick the node up front (the caller-side
                # analog of the reference's scheduling policies, scheduling/policy/)
                picked = self._pick_strategy_node(resources, strategy)
                if picked is None:
                    # infeasible right now: do NOT fall back to an arbitrary
                    # node — wait and let the pump retry the pick
                    time.sleep(0.2)
                    max_hops = 0
                else:
                    agent_addr = picked
                    max_hops = 1  # do not follow spillback off a constrained node
            for _ in range(max_hops):
                body = {"resources": resources, "timeout": cfg.lease_timeout_s,
                        "job_id": self._rt.job_id.hex(),
                        # lessee identity: if this runtime dies holding the
                        # lease (actor kill, crash), the agent reclaims the
                        # reservation when it reaps our process
                        "lessee": self._rt.worker_id}
                if runtime_env:
                    body["runtime_env"] = runtime_env
                if pg_id is not None:
                    body["pg_id"] = pg_id
                    body["bundle_index"] = bundle_index
                reply = self._rt.peer_pool.get(agent_addr).call(
                    "lease_worker", body, timeout=cfg.lease_timeout_s + 5)
                if reply.get("granted"):
                    granted = _Lease(reply["lease_id"], agent_addr,
                                     tuple(reply["worker_addr"]), reply["worker_id"])
                    break
                if reply.get("redirect"):
                    agent_addr = tuple(reply["redirect"])
                    continue
                if reply.get("busy") or reply.get("draining"):
                    # cluster saturated for this shape right now (or the
                    # target node is draining with nowhere to spill): back
                    # off so the retry loop doesn't hot-spin, then let
                    # _pump decide
                    with self._lock:
                        st_b = self._shapes.get(key)
                        if st_b is not None:
                            st_b.last_busy = time.monotonic()
                    time.sleep(0.1)
                break
        except Exception as e:
            logger.debug("lease request failed: %s", e)
        if trace_parent:
            from ray_tpu.observability import tracing
            tracing.record_span(
                "lease.acquire", lease_t0, time.time(),
                parent=trace_parent, kind="scheduler",
                attrs={"granted": granted is not None,
                       "resources": repr(resources)})
        with self._lock:
            st = self._shapes.get(key)
            if st is None:
                return
            st.requests_in_flight -= 1
            if granted is not None:
                if st.queue:
                    st.leases.append(granted)
                else:
                    self._return_lease(granted)
                    return
        if granted is not None:
            self._pump(key)
        else:
            # failed/busy grant: re-pump whenever work remains — with leases
            # held, the depth gate has just loosened (requests_in_flight
            # dropped), so queued tasks can now pipeline onto them; with no
            # leases at all this retries the lease request (throttled by the
            # busy backoff above)
            with self._lock:
                st = self._shapes.get(key)
                retry = st is not None and bool(st.queue)
            if retry:
                self._pump(key)

    def _pick_strategy_node(self, resources, strategy):
        """Apply spread/affinity/label policies against the control plane's
        cluster view and return the chosen node's agent address."""
        from ray_tpu.core.scheduler import NodeView, pick_node
        try:
            nodes = self._rt.cp_client.call_with_retry("get_nodes", None, timeout=10.0)
        except Exception:
            return None
        views = [NodeView(node_id=n["node_id"], addr=tuple(n["addr"]),
                          total=n["resources"], available=n["available"],
                          labels=n["labels"], alive=n["alive"]) for n in nodes]
        picked = pick_node(views, resources, strategy,
                           local_node_id=self._rt.node_id)
        return picked.addr if picked is not None else None

    def _resolve_pg_agent(self, pg_id, bundle_index):
        """Wait for the PG to be placed, then return the agent address hosting
        the target bundle (first bundle's node when index is -1)."""
        try:
            reply = self._rt.cp_client.call_with_retry(
                "pg_ready", {"pg_id": pg_id, "timeout": 60.0}, timeout=70.0)
            if reply.get("state") != "CREATED":
                return None
            node_ids = reply["node_ids"]
            node_id = node_ids[bundle_index if bundle_index >= 0 else 0]
            return self._rt._node_addr(node_id)
        except Exception:
            return None

    def _push(self, key, lease: _Lease, batch: list[TaskSpec]):
        """Push a coalesced frame of specs (ref: PushNormalTask
        normal_task_submitter.cc:183; batching is ours — see MAX_BATCH)."""
        client = self._rt.peer_pool.get(lease.worker_addr)

        def on_reply(ok, body):
            if ok:
                for spec, rep in zip(batch, body["replies"]):
                    self._rt.process_task_reply(spec, rep)
                self._on_worker_idle(key, lease, len(batch))
            else:
                self._on_push_failed(key, lease, batch, body)

        client.call_async("push_task_batch", {"specs": batch},
                          callback=on_reply)

    def _on_worker_idle(self, key, lease: _Lease, done: int):
        """(ref: OnWorkerIdle normal_task_submitter.cc:139). A fully idle
        lease is NOT returned here — it lingers IDLE_LEASE_TTL_S (reaper
        thread) so sync call-loops reuse the warm worker."""
        next_batch = None
        repump = False
        surplus = None
        with self._lock:
            st = self._shapes.get(key)
            if st is None:
                self._return_lease(lease)
                return
            lease.inflight -= done
            lease.frames -= 1
            if lease not in st.leases:
                # _on_push_failed declared this worker dead while other
                # pipelined calls were still in flight: never dispatch onto
                # it again (it would burn a retry on a known-dead address)
                repump = bool(st.queue)
            elif st.queue:
                # same adaptive depth gate as _pump (see _depth)
                limit = min(self._depth(st) - lease.inflight, self.MAX_BATCH)
                if limit > 0:
                    next_batch = _take_batch(st.queue, limit)
                    lease.inflight += len(next_batch)
                    lease.frames += 1
            elif lease.inflight == 0:
                now = time.monotonic()
                lease.idle_since = now
                # eager surplus return: the queue is drained, so surplus
                # breadth is pure hoarding — a CONTENDED cluster redistributes
                # it to whoever is starving right now instead of after the
                # reaper's idle TTL (the straggler tail in many-client
                # fan-outs: 3 clients done at 0.45s, the 4th at 1.0s waiting
                # on TTL handoffs). One lease stays warm for sync call-loops,
                # and an ACTIVE burst (a submit landed within 100ms — the
                # queue just happens to be momentarily drained into flight)
                # keeps its breadth.
                if len(st.leases) > 1 and now - st.last_submit > 0.1:
                    st.leases.remove(lease)
                    surplus = lease
        if surplus is not None:
            self._return_lease(surplus)
        if next_batch is not None:
            self._push(key, lease, next_batch)
        elif repump:
            self._pump(key)

    def _on_push_failed(self, key, lease: _Lease, batch: list[TaskSpec], err):
        with self._lock:
            st = self._shapes.get(key)
            if st is not None and lease in st.leases:
                st.leases.remove(lease)
        self._rt.peer_pool.invalidate(lease.worker_addr)
        for spec in batch:
            retry_spec = self._rt.task_manager.should_retry_system_failure(
                spec.task_id)
            if retry_spec is not None:
                logger.info("retrying task %s after worker failure (%s)",
                            spec.repr_name(), err)
                self.submit(retry_spec)
            else:
                self._rt.fail_task(spec, TaskError(
                    WorkerCrashedError(
                        f"worker at {lease.worker_addr} died: {err}"),
                    task_repr=spec.repr_name()))
        self._pump(key)

    def _return_lease(self, lease: _Lease):
        try:
            # per-task hot path: the agent reconciles leaked leases via
            # worker-death cleanup and the drain deadline bounds any stall
            # graftlint: fire-and-forget
            self._rt.peer_pool.get(lease.agent_addr).notify(
                "return_lease", {"lease_id": lease.lease_id})
        except Exception:
            pass

    def shutdown(self):
        self._stopped.set()
        self._flusher.stop()
        # Return only IDLE leases so agents free those workers promptly.
        # Leases with pushed tasks still in flight must NOT be returned: the
        # agent would mark the worker free and could re-lease a CPU that is
        # still executing the orphaned task — those are left to the agent's
        # dead-lessee reclamation, which terminates the mid-task worker.
        with self._lock:
            idle = [l for st in self._shapes.values() for l in st.leases
                    if l.inflight == 0]
            for st in self._shapes.values():
                st.leases.clear()
        for lease in idle:
            self._return_lease(lease)
        self._lease_pool.shutdown(wait=False)


@dataclass
class _ActorState:
    actor_id: ActorID
    addr: tuple | None = None
    state: str = "RESOLVING"  # RESOLVING | ALIVE | DEAD
    seq: int = 0
    queued: deque = field(default_factory=deque)       # waiting for address
    outbox: deque = field(default_factory=deque)        # awaiting the flusher
    inflight: dict = field(default_factory=dict)        # seq -> spec
    death_cause: str = ""
    resolving: bool = False


class ActorTaskSubmitter:
    # Submissions enqueue to a per-actor outbox drained by a _Flusher into
    # push_task_batch frames, with NO in-flight cap (async actors
    # legitimately run thousands of concurrent calls). Order across frames
    # is restored executor-side by seq_no (the
    # sequential_actor_submit_queue.cc analog in worker._enqueue_actor_task).
    MAX_BATCH = 32

    def __init__(self, runtime):
        self._rt = runtime
        self._lock = threading.Lock()
        self._actors: dict[ActorID, _ActorState] = {}
        self._resolve_pool = ThreadPoolExecutor(max_workers=4, thread_name_prefix="actor-resolve")
        self._flusher = _Flusher("actor-flush", self._drain_actor)

    def _state(self, actor_id: ActorID) -> _ActorState:
        st = self._actors.get(actor_id)
        if st is None:
            st = self._actors[actor_id] = _ActorState(actor_id)
        return st

    def submit(self, spec: TaskSpec):
        dead_cause = None
        fast_addr = None
        with self._lock:
            st = self._state(spec.actor_id)
            spec.seq_no = st.seq
            st.seq += 1
            if st.state == "DEAD":
                dead_cause = st.death_cause
            elif st.state == "ALIVE" and st.addr is not None:
                st.inflight[spec.seq_no] = spec
                # Fast path for interactive (sync call-loop) traffic: with
                # nothing outstanding on this actor, skip the flusher
                # handoff and send the singleton frame inline. Concurrent
                # traffic routes through the flusher so bursts batch.
                if not st.outbox and len(st.inflight) == 1:
                    fast_addr = st.addr
                else:
                    st.outbox.append(spec)
            else:
                st.queued.append(spec)
                if not st.resolving:
                    st.resolving = True
                    self._resolve_pool.submit(self._resolve, spec.actor_id)
        if dead_cause is not None:
            self._rt.fail_task(spec, TaskError(
                ActorDiedError(f"actor is dead: {dead_cause}"), task_repr=spec.repr_name()))
        elif fast_addr is not None:
            self._send_batch(st, fast_addr, [spec])
        else:
            self._flusher.mark(spec.actor_id)

    def _drain_actor(self, actor_id: ActorID):
        # sends happen outside the lock: a synchronous connect failure
        # invokes the on_reply callback inline, and _on_connection_lost
        # takes self._lock
        sends = []
        with self._lock:
            st = self._actors.get(actor_id)
            if st is None:
                return
            while st.outbox and st.state == "ALIVE" and st.addr is not None:
                batch = _take_batch(st.outbox, self.MAX_BATCH)
                sends.append((st.addr, batch))
        for addr, batch in sends:
            self._send_batch(st, addr, batch)

    def _send_batch(self, st: _ActorState, addr, batch: list[TaskSpec]):
        client = self._rt.peer_pool.get(addr)

        def on_reply(ok, body):
            if ok:
                with self._lock:
                    for spec in batch:
                        st.inflight.pop(spec.seq_no, None)
                for spec, rep in zip(batch, body["replies"]):
                    self._rt.process_task_reply(spec, rep)
            else:
                self._on_connection_lost(st.actor_id, addr, str(body))

        client.call_async("push_task_batch", {"specs": batch},
                          callback=on_reply)

    def _resolve(self, actor_id: ActorID):
        """Resolve the actor address from the control plane, then flush the
        queue (ref: actor_task_submitter.cc ConnectActor)."""
        try:
            while True:
                reply = self._rt.cp_client.call_with_retry(
                    "resolve_actor",
                    {"actor_id": actor_id, "timeout": 120.0}, timeout=130.0)
                # TIMEOUT is only the long-poll bound, NOT a death verdict:
                # actor creation is legitimately unbounded (model loads,
                # compile warmup). Keep polling until ALIVE or DEAD — the
                # reference blocks the same way (resolve ends only when the
                # GCS reports a terminal state).
                if reply.get("state") != "TIMEOUT":
                    break
        except Exception as e:
            reply = {"state": "DEAD", "death_cause": f"resolve failed: {e}"}
        to_fail = []
        flush = False
        with self._lock:
            st = self._state(actor_id)
            st.resolving = False
            if reply.get("state") == "ALIVE":
                st.state = "ALIVE"
                st.addr = tuple(reply["addr"])
                self._rt.subscribe_actor_events(actor_id)
                # A (re)started actor instance expects sequence numbers from 0:
                # renumber the queue in submission order (the reference tracks
                # this as the caller's per-incarnation sequence window).
                st.seq = 0
                while st.queued:
                    spec = st.queued.popleft()
                    spec.seq_no = st.seq
                    st.seq += 1
                    st.inflight[spec.seq_no] = spec
                    st.outbox.append(spec)
                if st.outbox:
                    flush = True
            else:
                st.state = "DEAD"
                st.death_cause = reply.get("death_cause", reply.get("state", "unknown"))
                while st.queued:
                    to_fail.append(st.queued.popleft())
                st.outbox.clear()  # outbox specs are all in inflight too
                inflight = list(st.inflight.values())
                st.inflight.clear()
                to_fail.extend(inflight)
        if flush:
            self._flusher.mark(actor_id)
        for spec in to_fail:
            self._rt.fail_task(spec, TaskError(
                ActorDiedError(f"actor is dead: {self._actors[actor_id].death_cause}"),
                task_repr=spec.repr_name()))

    def _on_connection_lost(self, actor_id: ActorID, addr, err: str):
        """Push failed: the actor may be restarting. Re-resolve and resubmit
        in-flight tasks whose retry budget allows (ref: actor_task_submitter.cc
        DisconnectActor + retry queue)."""
        with self._lock:
            st = self._state(actor_id)
            if st.addr == addr:
                st.addr = None
                st.state = "RESOLVING"
            self._rt.peer_pool.invalidate(addr)
            st.outbox.clear()  # outbox specs are all in inflight too
            inflight = sorted(st.inflight.items())
            st.inflight.clear()
            requeue, fail = [], []
            for _, spec in inflight:
                retry = self._rt.task_manager.should_retry_system_failure(spec.task_id)
                if retry is not None:
                    requeue.append(retry)
                else:
                    fail.append(spec)
            for spec in reversed(requeue):
                st.queued.appendleft(spec)
            if not st.resolving:
                st.resolving = True
                self._resolve_pool.submit(self._resolve, actor_id)
        for spec in fail:
            self._rt.fail_task(spec, TaskError(
                ActorDiedError(f"actor connection lost: {err}"), task_repr=spec.repr_name()))

    def on_actor_death(self, actor_id: ActorID, reason: str):
        """Pubsub death notification from the control plane."""
        to_fail = []
        with self._lock:
            st = self._actors.get(actor_id)
            if st is None:
                return
            st.state = "DEAD"
            st.death_cause = reason
            st.addr = None
            while st.queued:
                to_fail.append(st.queued.popleft())
            st.outbox.clear()  # outbox specs are all in inflight too
            to_fail.extend(st.inflight.values())
            st.inflight.clear()
        for spec in to_fail:
            self._rt.fail_task(spec, TaskError(
                ActorDiedError(f"actor died: {reason}"), task_repr=spec.repr_name()))

    def on_actor_restart(self, actor_id: ActorID):
        with self._lock:
            st = self._actors.get(actor_id)
            if st is None:
                return
            st.addr = None
            st.state = "RESOLVING"
            if not st.resolving:
                st.resolving = True
                self._resolve_pool.submit(self._resolve, actor_id)

    def shutdown(self):
        self._flusher.stop()
        self._resolve_pool.shutdown(wait=False)
