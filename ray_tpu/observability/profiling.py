"""Engine performance introspection + cluster-wide on-demand XProf capture.

Three concerns the serving runtime attributes ITSELF (the MegaScale
argument: in-situ diagnostics are a precondition for operating a fleet —
offline benching found the 138 ms/step residual cost, production needs the
runtime to find the next one):

- **Phase timers** (`EngineProfiler.record`): the engine loop stamps each
  phase — admit / prefill / chunk_prefill / decode_dispatch /
  verify_dispatch / harvest — into bounded rings and a tagged Histogram.
  Dispatch phases measure host-side dispatch cost (the loop never blocks
  on the device); `harvest` is where the device sync lives
  (`np.asarray` on the oldest in-flight block), so device slowness shows
  up there, attributed, instead of smeared across the loop.
- **Compile-event tracking** (`compile_scope`): every jit entry point's
  first dispatch per static signature (prefill bucket, chunk length,
  decode (width, block), verify width) is timed as a compile event.
  Compiles while traffic is in flight are the documented loop-stall
  failure class (engine.py `_warmup_decode_programs`): they're flagged
  `mid_traffic`, logged as warnings, and counted — a regression here is
  a serving-latency regression.
- **Device-memory accounting**: weights / KV-pool byte gauges computed
  from array layouts, KV page occupancy, and the backend allocator's
  live/peak bytes when the platform reports them (`device.memory_stats()`
  — absent on the cpu backend, surfaced as None rather than guessed).

Plus the **capture controller**: a process-wide start/stop pair around
`jax.profiler` XPlane tracing, callable from an RPC handler, so
`ray-tpu profile --node <id>` captures a trace on any live worker and the
dashboard serves the artifact. The local context-manager helpers
(`profile_trace` / `annotate` / `profile_step` / `dump_thread_stacks`)
live here too — `ray_tpu.util.profiling` is a compatibility re-export.
"""

from __future__ import annotations

import collections
import contextlib
import logging
import os
import threading
import time
from typing import Any, Optional

from ray_tpu.util import metrics as _metrics

logger = logging.getLogger(__name__)

# engine phases, in loop order (the drift-guard test and README table key
# off this tuple — extend it and both follow)
PHASES = ("queue_wait", "admit", "prefill", "chunk_prefill",
          "decode_dispatch", "verify_dispatch", "harvest")

_PHASE_BOUNDS = (0.0001, 0.0003, 0.001, 0.003, 0.01, 0.03,
                 0.1, 0.3, 1.0, 3.0, 10.0)
_ITL_BOUNDS = (0.001, 0.003, 0.01, 0.03, 0.1, 0.3, 1.0, 3.0, 10.0)
_COMPILE_BOUNDS = (0.01, 0.03, 0.1, 0.3, 1.0, 3.0, 10.0, 30.0, 100.0)

PHASE_SECONDS = _metrics.Histogram(
    "ray_tpu_llm_engine_phase_seconds",
    "Engine loop time per phase (dispatch phases are host cost; harvest "
    "carries the device sync)", boundaries=_PHASE_BOUNDS,
    tag_keys=("phase",))
ITL_SECONDS = _metrics.Histogram(
    "ray_tpu_llm_itl_seconds",
    "Inter-token latency (host record-time gaps; pipelined harvests land "
    "in bursts of decode_block)", boundaries=_ITL_BOUNDS)
COMPILE_EVENTS = _metrics.Counter(
    "ray_tpu_llm_compile_events_total",
    "XLA compilations by jit entry point; mid_traffic=true ones stalled "
    "live requests", tag_keys=("kind", "mid_traffic"))
COMPILE_SECONDS = _metrics.Histogram(
    "ray_tpu_llm_compile_seconds",
    "Wall time of first-dispatch-per-signature (≈ trace+compile)",
    boundaries=_COMPILE_BOUNDS, tag_keys=("kind",))
DEVICE_MEMORY = _metrics.Gauge(
    "ray_tpu_llm_device_memory_bytes",
    "Device/HBM bytes by component (weights, kv_pool, in_use, peak)",
    tag_keys=("component",))
KV_OCCUPANCY = _metrics.Gauge(
    "ray_tpu_llm_kv_page_occupancy",
    "Fraction of KV pool pages held by live sequences (evictable cached "
    "prefix pages count as free — an alloc can reclaim them)")


def _pct(sorted_vals: list, q: float) -> float:
    """Interpolated percentile of an ascending list (non-empty)."""
    if len(sorted_vals) == 1:
        return sorted_vals[0]
    pos = q * (len(sorted_vals) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = pos - lo
    return sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac


class _Noop:
    """Reusable no-op context manager (compile_scope fast path: the
    signature was already seen, so the per-dispatch cost is one set
    lookup and no allocation)."""

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NOOP = _Noop()


class _CompileScope:
    def __init__(self, prof: "EngineProfiler", kind: str, sig,
                 mid_traffic: bool):
        self._prof = prof
        self._kind = kind
        self._sig = sig
        self._mid = mid_traffic

    def __enter__(self):
        self._t0 = time.perf_counter()
        return None

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            self._prof._record_compile(
                self._kind, self._sig, time.perf_counter() - self._t0,
                self._mid)
        return False


class EngineProfiler:
    """Per-engine introspection state: phase rings, compile tracker, ITL
    ring, memory layout. All mutating entry points are cheap enough to
    sit on the engine loop's hot path; `enabled=False` reduces phase/ITL
    recording to a single attribute check (the `--profile-ab` bench
    bounds the enabled-path overhead). Compile tracking stays on either
    way — it only does work on the FIRST dispatch of a new signature,
    and a silent mid-traffic compile is exactly what this exists to
    catch."""

    def __init__(self, enabled: bool = True, ring_size: int = 256,
                 itl_ring_size: int = 2048):
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._rings: dict[str, collections.deque] = {
            p: collections.deque(maxlen=ring_size) for p in PHASES}
        self._itl: collections.deque = collections.deque(maxlen=itl_ring_size)
        self._seen: set = set()
        self.compile_events = 0
        self.mid_traffic_compiles = 0
        self.compile_s = 0.0
        # memory layout (set once by the engine after weights/pool init)
        self.weights_bytes = 0
        self.kv_pool_bytes = 0

    # ---- phase timers --------------------------------------------------
    def record(self, phase: str, dt: float) -> None:
        if not self.enabled:
            return
        self._rings[phase].append(dt)
        PHASE_SECONDS.observe(dt, {"phase": phase})

    @contextlib.contextmanager
    def phase(self, name: str):
        """Time a block as one phase sample (skips the clock reads
        entirely when disabled)."""
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(name, time.perf_counter() - t0)

    def record_itl(self, gap_s: float) -> None:
        if not self.enabled:
            return
        self._itl.append(gap_s)
        ITL_SECONDS.observe(gap_s)

    def phase_stats(self) -> dict:
        """`phase_<name>_p50_ms` / `_p95_ms` per phase plus `itl_s` (p50);
        None where no samples exist yet (or profiling is disabled)."""
        out: dict[str, Optional[float]] = {}
        for p in PHASES:
            vals = sorted(self._rings[p])
            out[f"phase_{p}_p50_ms"] = (
                round(_pct(vals, 0.5) * 1e3, 4) if vals else None)
            out[f"phase_{p}_p95_ms"] = (
                round(_pct(vals, 0.95) * 1e3, 4) if vals else None)
        itl = sorted(self._itl)
        out["itl_s"] = round(_pct(itl, 0.5), 6) if itl else None
        return out

    # ---- compile tracking ----------------------------------------------
    def compile_scope(self, kind: str, sig, mid_traffic: bool = False):
        """Context manager around a jit entry point's dispatch. First use
        of ``sig`` is timed and counted as a compile event; later uses
        return a shared no-op. ``mid_traffic`` should be True when any
        request has been submitted — such a compile stalled live work."""
        if sig in self._seen:
            return _NOOP
        return _CompileScope(self, kind, sig, mid_traffic)

    def compile_count(self, kinds) -> int:
        """Compiled-program count for the given scope kinds (each sig's
        first element is its kind — e.g. ("decode", w, k)). Feeds the
        per-kernel compile counters in engine_stats(): with warmup on,
        this number is reached before traffic and must then stay flat
        (the compile-once contract per (width, k) tier)."""
        kinds = tuple(kinds)
        with self._lock:
            return sum(1 for s in self._seen
                       if isinstance(s, tuple) and s and s[0] in kinds)

    def _record_compile(self, kind: str, sig, dt: float,
                        mid_traffic: bool) -> None:
        with self._lock:
            if sig in self._seen:
                return
            self._seen.add(sig)
            self.compile_events += 1
            self.compile_s += dt
            if mid_traffic:
                self.mid_traffic_compiles += 1
        COMPILE_EVENTS.inc(1, {"kind": kind,
                               "mid_traffic": str(bool(mid_traffic)).lower()})
        COMPILE_SECONDS.observe(dt, {"kind": kind})
        if mid_traffic:
            logger.warning(
                "mid-traffic compile: kind=%s sig=%s took %.2fs — every "
                "active generation stalled for it (warm this program at "
                "startup, see engine warmup_compile)", kind, sig, dt)
            # off-box visibility (ISSUE 19): a WARNING journal event
            # carrying the compile signature. Warmup compiles
            # (mid_traffic=False) emit nothing — the regression test
            # holds that line.
            from ray_tpu.observability import events as _fr
            _fr.emit("mid_traffic_compile", "WARNING",
                     reason=kind,
                     attrs={"kind": kind,
                            "sig": list(sig) if isinstance(
                                sig, (tuple, list)) else [str(sig)],
                            "seconds": round(float(dt), 4)})

    # ---- memory accounting ---------------------------------------------
    def set_memory_layout(self, weights_bytes: int,
                          kv_pool_bytes: int) -> None:
        self.weights_bytes = int(weights_bytes)
        self.kv_pool_bytes = int(kv_pool_bytes)
        DEVICE_MEMORY.set(self.weights_bytes, {"component": "weights"})
        DEVICE_MEMORY.set(self.kv_pool_bytes, {"component": "kv_pool"})

    def memory_stats(self, used_pages: Optional[int] = None,
                     total_pages: Optional[int] = None) -> dict:
        occ = None
        if used_pages is not None and total_pages:
            occ = round(used_pages / total_pages, 4)
            KV_OCCUPANCY.set(occ)
        in_use, peak = device_memory_stats()
        if in_use is not None:
            DEVICE_MEMORY.set(in_use, {"component": "in_use"})
        if peak is not None:
            DEVICE_MEMORY.set(peak, {"component": "peak"})
        return {"weights_bytes": self.weights_bytes,
                "kv_pool_bytes": self.kv_pool_bytes,
                "kv_page_occupancy": occ,
                "device_bytes_in_use": in_use,
                "device_peak_bytes": peak}


def tree_bytes(tree) -> int:
    """Total bytes of every array leaf in a pytree (weights / KV pool
    sizing; shape*dtype math, no device round trip)."""
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        nbytes = getattr(leaf, "nbytes", None)
        if nbytes is None:
            size = getattr(leaf, "size", None)
            itemsize = getattr(getattr(leaf, "dtype", None), "itemsize", None)
            nbytes = size * itemsize if size and itemsize else 0
        total += int(nbytes)
    return total


def device_memory_stats() -> tuple[Optional[int], Optional[int]]:
    """(bytes_in_use, peak_bytes_in_use) from the default device's
    allocator, or (None, None) where the backend doesn't report (cpu)."""
    try:
        import jax

        dev = jax.devices()[0]
        stats = dev.memory_stats()
    except Exception:  # noqa: BLE001 - stats are strictly best-effort
        return None, None
    if not stats:
        return None, None
    return (stats.get("bytes_in_use"), stats.get("peak_bytes_in_use"))


# ---------------------------------------------------------------------------
# on-demand XPlane capture (remote-drivable: worker RPC handlers call these)
# ---------------------------------------------------------------------------

class CaptureController:
    """Process-wide start/stop around `jax.profiler` tracing. jax allows
    ONE active trace per process, so this serializes: a second start while
    active raises instead of corrupting the run."""

    def __init__(self):
        self._lock = threading.Lock()
        self._logdir: Optional[str] = None
        self._started_at: Optional[float] = None

    def start(self, logdir: Optional[str] = None) -> dict:
        import jax

        with self._lock:
            if self._logdir is not None:
                raise RuntimeError(
                    f"capture already active (logdir={self._logdir})")
            if not logdir:
                logdir = os.path.join(
                    "/tmp", "ray_tpu_xprof",
                    f"{int(time.time())}-{os.getpid()}")
            os.makedirs(logdir, exist_ok=True)
            jax.profiler.start_trace(logdir, create_perfetto_link=False)
            self._logdir = logdir
            self._started_at = time.time()
            return {"logdir": logdir, "pid": os.getpid()}

    def stop(self) -> dict:
        import jax

        with self._lock:
            if self._logdir is None:
                raise RuntimeError("no capture active")
            jax.profiler.stop_trace()
            logdir, self._logdir = self._logdir, None
            dur = time.time() - (self._started_at or time.time())
            self._started_at = None
        return {"logdir": logdir, "duration_s": round(dur, 3),
                "pid": os.getpid()}

    def status(self) -> dict:
        with self._lock:
            return {"active": self._logdir is not None,
                    "logdir": self._logdir, "pid": os.getpid()}


_capture = CaptureController()


def start_capture(logdir: Optional[str] = None) -> dict:
    return _capture.start(logdir)


def stop_capture() -> dict:
    return _capture.stop()


def capture_status() -> dict:
    return _capture.status()


def save_device_memory_profile(path: Optional[str] = None) -> str:
    """pprof device-memory dump — the 'why is my model OOMing' tool.
    RPC-friendly default path when none is given."""
    import jax

    if not path:
        path = os.path.join(
            "/tmp", "ray_tpu_xprof",
            f"memory-{int(time.time())}-{os.getpid()}.prof")
    os.makedirs(os.path.dirname(path), exist_ok=True)
    jax.profiler.save_device_memory_profile(path)
    return path


# ---------------------------------------------------------------------------
# local context-manager helpers (driver/train-fn ergonomics; formerly
# ray_tpu.util.profiling, which now re-exports from here)
# ---------------------------------------------------------------------------


@contextlib.contextmanager
def profile_trace(logdir: str, *, host_tracer_level: int = 2):
    """Capture an XPlane trace of everything inside the block.

    Usage (inside a train fn)::

        with profile_trace("/tmp/prof"):
            for _ in range(10):
                state, metrics = step(state, batch)
        # then: tensorboard --logdir /tmp/prof  (Profile tab)
    """
    import jax

    os.makedirs(logdir, exist_ok=True)
    jax.profiler.start_trace(logdir, create_perfetto_link=False)
    try:
        yield logdir
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region inside a profile_trace (shows as a span in XProf).
    Usage: `with annotate("data-load"): ...`"""
    import jax

    return jax.profiler.TraceAnnotation(name)


def profile_step(fn, *args, logdir: str = "/tmp/ray_tpu_prof", **kwargs):
    """One-shot: trace a single call of `fn` and return its result."""
    with profile_trace(logdir):
        out = fn(*args, **kwargs)
        import jax

        jax.block_until_ready(out)
    return out


def dump_thread_stacks() -> str:
    """Every thread's Python stack as text (named), for on-demand hang
    diagnosis (ref: dashboard/modules/reporter/profile_manager.py:191 —
    the reference shells out to py-spy; a pure-Python snapshot needs no
    debugger attach and works from an RPC handler)."""
    import sys
    import threading as _threading
    import traceback

    names = {t.ident: t.name for t in _threading.enumerate()}
    out = []
    for tid, frame in sys._current_frames().items():
        out.append(f"--- thread {names.get(tid, '?')} ({tid})\n"
                   + "".join(traceback.format_stack(frame)))
    return "\n".join(out)
