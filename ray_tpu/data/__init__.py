"""ray_tpu.data — streaming dataset engine (reference: python/ray/data/).

Lazy logical plans over Arrow blocks in the object store, executed by a
streaming executor with backpressure; `iter_jax_batches` double-buffers
batches into TPU HBM.
"""

from ray_tpu.data import aggregate
from ray_tpu.data.aggregate import Count, Max, Mean, Min, Std, Sum
from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata
from ray_tpu.data.dataset import Dataset, MaterializedDataset
from ray_tpu.data.datasource import Datasource, ReadTask
from ray_tpu.data.iterator import DataIterator
from ray_tpu.data.read_api import (
    from_arrow,
    from_huggingface,
    from_items,
    from_numpy,
    from_pandas,
    range,
    range_tensor,
    read_bigquery,
    read_binary_files,
    read_clickhouse,
    read_csv,
    read_datasource,
    read_delta,
    read_iceberg,
    read_images,
    read_lance,
    read_mongo,
    read_json,
    read_parquet,
    read_sql,
    read_text,
    read_tfrecords,
    read_webdataset,
)
from ray_tpu.data.expressions import col, lit
from ray_tpu.data import preprocessors

__all__ = [
    "Block", "BlockAccessor", "BlockMetadata", "Count", "DataIterator",
    "Dataset", "Datasource", "MaterializedDataset", "Max", "Mean", "Min",
    "ReadTask", "Std", "Sum", "aggregate", "from_arrow", "from_huggingface",
    "from_items", "from_numpy", "from_pandas", "range", "range_tensor",
    "read_bigquery", "read_binary_files", "read_clickhouse", "read_csv",
    "read_datasource", "read_delta", "read_iceberg", "read_images",
    "read_json", "read_lance", "read_mongo", "read_parquet", "read_sql",
    "read_text", "read_tfrecords", "read_webdataset", "col", "lit", "preprocessors",
]
