"""ray_tpu.job — job submission (reference: dashboard/modules/job/).

A job is a user script run as a supervised driver subprocess: a detached
supervisor actor starts it with the cluster address in the environment,
captures its output, and records status in the control-plane KV so any
client can query it (job_manager.py + job_supervisor.py in the reference).
"""

from ray_tpu.job.manager import JobStatus, JobSubmissionClient

__all__ = ["JobStatus", "JobSubmissionClient"]
