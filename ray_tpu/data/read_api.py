"""Read API: the ray_tpu.data entry points (reference:
/root/reference/python/ray/data/read_api.py — read_parquet:796,
read_images:973, read_json:1268, read_csv:1441, range, from_items,
from_numpy, from_pandas, from_arrow)."""

from __future__ import annotations

from typing import Optional

import numpy as np

from ray_tpu.data.dataset import Dataset, MaterializedDataset
from ray_tpu.data.datasource import (
    BinaryDatasource,
    CSVDatasource,
    Datasource,
    ImageDatasource,
    ItemsDatasource,
    JSONDatasource,
    NumpyDatasource,
    ParquetDatasource,
    RangeDatasource,
    SQLDatasource,
    TextDatasource,
    TFRecordsDatasource,
    WebDatasetDatasource,
)
from ray_tpu.data.logical import InputData, Read


def _read(ds: Datasource, parallelism: int = -1) -> Dataset:
    return Dataset(Read(name="", datasource=ds, parallelism=parallelism))


def range(n: int, *, parallelism: int = -1) -> Dataset:  # noqa: A001
    return _read(RangeDatasource(n), parallelism)


def range_tensor(n: int, *, shape: tuple = (1,), parallelism: int = -1) -> Dataset:
    arr = np.arange(n, dtype=np.int64).reshape((n,) + (1,) * len(shape))
    arr = np.broadcast_to(arr, (n, *shape)).copy()
    return from_numpy(arr, column="data")


def read_datasource(ds: Datasource, *, parallelism: int = -1) -> Dataset:
    return _read(ds, parallelism)


def read_parquet(paths, *, columns: Optional[list] = None,
                 parallelism: int = -1) -> Dataset:
    return _read(ParquetDatasource(paths, columns=columns), parallelism)


def read_csv(paths, *, parallelism: int = -1) -> Dataset:
    return _read(CSVDatasource(paths), parallelism)


def read_json(paths, *, parallelism: int = -1) -> Dataset:
    return _read(JSONDatasource(paths), parallelism)


def read_text(paths, *, parallelism: int = -1) -> Dataset:
    return _read(TextDatasource(paths), parallelism)


def read_binary_files(paths, *, parallelism: int = -1) -> Dataset:
    return _read(BinaryDatasource(paths), parallelism)


def read_images(paths, *, size: Optional[tuple] = None, mode: str = "RGB",
                parallelism: int = -1) -> Dataset:
    return _read(ImageDatasource(paths, size=size, mode=mode), parallelism)


def read_webdataset(paths, *, parallelism: int = -1) -> Dataset:
    """Read WebDataset tar shards: tar members group into one row per
    sample key, columns keyed by extension (reference read_api.py:2101)."""
    return _read(WebDatasetDatasource(paths), parallelism)


def read_sql(sql: str, connection_factory, *,
             parallelism_column=None, parallelism: int = -1) -> Dataset:
    """Read a SQL query through a DB-API connection factory; with
    ``parallelism_column`` the query shards by hash-mod on that column
    (reference read_api read_sql)."""
    return _read(SQLDatasource(sql, connection_factory,
                               parallelism_column), parallelism)


def read_tfrecords(paths, *, parallelism: int = -1) -> Dataset:
    return _read(TFRecordsDatasource(paths), parallelism)


def read_delta(table_path: str, *, columns=None,
               parallelism: int = -1) -> Dataset:
    """Delta Lake table (native: parquet + _delta_log JSON fold; no
    deltalake dependency). Reference: the delta/hudi table-format
    readers under data/_internal/datasource/."""
    from ray_tpu.data.datasource_ext import DeltaLakeDatasource
    return _read(DeltaLakeDatasource(table_path, columns), parallelism)


def read_lance(uri: str, *, columns=None, parallelism: int = -1) -> Dataset:
    """Lance dataset (requires `lance`; reference lance_datasource.py)."""
    from ray_tpu.data.datasource_ext import LanceDatasource
    return _read(LanceDatasource(uri, columns), parallelism)


def read_iceberg(table_identifier: str, *, catalog_kwargs=None,
                 row_filter=None, selected_fields: tuple = ("*",),
                 parallelism: int = -1) -> Dataset:
    """Iceberg table (requires `pyiceberg`; reference
    iceberg_datasource.py)."""
    from ray_tpu.data.datasource_ext import IcebergDatasource
    return _read(IcebergDatasource(
        table_identifier, catalog_kwargs=catalog_kwargs,
        row_filter=row_filter, selected_fields=selected_fields), parallelism)


def read_bigquery(project_id: str, *, dataset=None, query=None,
                  parallelism: int = -1) -> Dataset:
    """BigQuery table or query (requires `google-cloud-bigquery`;
    reference bigquery_datasource.py)."""
    from ray_tpu.data.datasource_ext import BigQueryDatasource
    return _read(BigQueryDatasource(project_id, dataset, query), parallelism)


def read_mongo(uri: str, database: str, collection: str, *,
               pipeline=None, parallelism: int = -1) -> Dataset:
    """MongoDB collection (requires `pymongo`; reference
    mongo_datasource.py)."""
    from ray_tpu.data.datasource_ext import MongoDatasource
    return _read(MongoDatasource(uri, database, collection, pipeline),
                 parallelism)


def read_clickhouse(query: str, *, url: str = "http://localhost:8123",
                    user=None, password=None,
                    parallelism: int = -1) -> Dataset:
    """ClickHouse query over the HTTP interface (library-free ArrowStream;
    reference clickhouse_datasource.py)."""
    from ray_tpu.data.datasource_ext import ClickHouseDatasource
    return _read(ClickHouseDatasource(query, url=url, user=user,
                                      password=password), parallelism)


def from_items(items: list, *, parallelism: int = -1) -> Dataset:
    return _read(ItemsDatasource(items), parallelism)


def from_numpy(arr: np.ndarray, *, column: str = "data",
               parallelism: int = -1) -> Dataset:
    return _read(NumpyDatasource(arr, column), parallelism)


def from_pandas(df) -> Dataset:
    import pyarrow as pa
    return from_arrow(pa.Table.from_pandas(df, preserve_index=False))


def from_arrow(table) -> Dataset:
    import ray_tpu
    from ray_tpu.data.block import BlockAccessor
    ref = ray_tpu.put(table)
    meta = BlockAccessor.for_block(table).metadata()
    return MaterializedDataset(InputData(name="Input", bundles=[(ref, meta)]))


def from_huggingface(hf_dataset, *, parallelism: int = -1) -> Dataset:
    """Wrap a `datasets.Dataset` (reference read_api.py:3285)."""
    table = hf_dataset.data.table  # HF datasets are arrow-backed
    return from_arrow(table)
