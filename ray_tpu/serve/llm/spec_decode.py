"""N-gram draft proposal for speculative decoding (prompt lookup).

Speculative decoding (Leviathan et al. 2023) verifies several drafted
tokens in ONE model pass; with greedy sampling the accepted output is
provably identical to step-by-step decoding, so the only question is where
drafts come from. Here they come for free: prompt-lookup / n-gram drafting
(Saxena 2023) — if the tokens just generated end with an n-gram that
already occurred earlier in the slot's prompt+output, the tokens that
followed that earlier occurrence are a cheap guess at what follows now.
Repetitive workloads (code, extraction, multi-turn chat quoting context)
accept most of the draft; adversarial text accepts none and the engine
degrades to ordinary decode.

Everything in this module is host-side Python over small int lists —
zero device work, zero new compiled programs. The engine owns one
``NGramProposer`` per in-flight request and asks it for a draft before
each verify round (engine.py ``_step`` spec path).

The index is incremental: every position of the context is indexed at
most once (per n-gram size), so the amortized cost per generated token is
O(spec_ngram_max), independent of context length — no quadratic suffix
scans on long generations.
"""

from __future__ import annotations


class NGramProposer:
    """Per-request suffix-match draft proposer.

    Maintains, for every n in [1, ngram_max], a dict mapping each n-gram
    of the context to the position AFTER its most recent occurrence
    (the draft continuation start). ``propose`` looks up the context's
    current suffix, longest n first — a longer match is stronger evidence
    the continuation repeats.

    Positions are indexed lazily up to ``len(ctx) - 1`` (an n-gram ending
    at the final position has no continuation yet), so the suffix's own
    occurrence never shadows an earlier one.
    """

    def __init__(self, ngram_max: int, draft_len: int):
        self.ngram_max = max(1, int(ngram_max))
        self.draft_len = max(1, int(draft_len))
        # n -> {ngram tuple -> continuation start position}
        self._index: list[dict] = [dict() for _ in range(self.ngram_max + 1)]
        self._indexed = 0  # positions with an indexed n-gram ENDING there

    def _extend(self, ctx: list[int]) -> None:
        """Index n-grams ending at positions [_indexed, len(ctx) - 1);
        the last position is left for the next call (its continuation
        doesn't exist yet)."""
        hi = len(ctx) - 1
        for end in range(self._indexed, hi):
            for n in range(1, self.ngram_max + 1):
                lo = end - n + 1
                if lo < 0:
                    break
                self._index[n][tuple(ctx[lo: end + 1])] = end + 1
        self._indexed = max(self._indexed, hi)

    def propose(self, ctx: list[int]) -> list[int]:
        """Draft up to ``draft_len`` tokens continuing ``ctx`` (the slot's
        prompt + generated tokens). Empty list = no draft (no suffix
        n-gram recurs); the engine then decodes this slot normally."""
        if len(ctx) < 2:
            return []
        self._extend(ctx)
        t = len(ctx)
        for n in range(min(self.ngram_max, t - 1), 0, -1):
            start = self._index[n].get(tuple(ctx[t - n:]))
            if start is None or start >= t:
                continue
            draft = ctx[start: start + self.draft_len]
            if draft:
                return list(draft)
        return []


def accept_length(draft: list[int], verified: list[int]) -> int:
    """Longest prefix of ``draft`` matched by the verify pass's
    step-by-step (greedy) outputs ``verified`` — the number of drafted
    tokens that are BIT-IDENTICAL to what ordinary decode would have
    produced. verified[i] is the model's token after consuming draft[:i],
    so draft[i] is acceptable iff it equals verified[i] AND every earlier
    draft token was accepted (a mismatch invalidates all later positions:
    their KV was computed from the wrong tokens)."""
    a = 0
    while a < len(draft) and a < len(verified) and draft[a] == verified[a]:
        a += 1
    return a
