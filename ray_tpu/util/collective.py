"""Host-level collectives between actors/tasks.

TPU-native analog of the reference's ray.util.collective
(/root/reference/python/ray/util/collective/collective.py —
init_collective_group:166, allreduce:311, broadcast:426, allgather:476,
reducescatter:525, send:584, recv:647). The reference's backends are
NCCL/gloo/NIXL; here the DEVICE data plane is XLA collectives over ICI
(psum/all_gather emitted by pjit — no framework code needed), so this module
only provides the HOST control/data plane: numpy arrays over the
control-plane rendezvous actor, used for cross-process coordination
(checkpointing barriers, eval aggregation, parameter broadcast at startup).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Optional

import numpy as np

import ray_tpu

_REDUCE_OPS = {
    "sum": lambda arrs: np.sum(arrs, axis=0),
    "prod": lambda arrs: np.prod(arrs, axis=0),
    "min": lambda arrs: np.min(arrs, axis=0),
    "max": lambda arrs: np.max(arrs, axis=0),
    "mean": lambda arrs: np.mean(arrs, axis=0),
}


@ray_tpu.remote
class _CollectiveGroupActor:
    """Rendezvous + reduce for one group. Each collective is a generation-
    numbered barrier keyed by op sequence, so the group is reusable."""

    def __init__(self, world_size: int):
        self._world = world_size
        self._cv = threading.Condition()
        self._rounds: dict = {}  # seq -> {"values": {rank: v}, "result": ...}
        self._p2p: dict = {}     # (src, dst, tag) -> value

    def collect(self, seq: int, rank: int, value, op: str,
                timeout: float = 300.0):
        with self._cv:
            rd = self._rounds.setdefault(seq, {"values": {}, "result": None,
                                               "done": False})
            rd["values"][rank] = value
            if len(rd["values"]) == self._world:
                vals = [rd["values"][r] for r in sorted(rd["values"])]
                if op == "gather":
                    rd["result"] = vals
                elif op == "bcast":
                    rd["result"] = next(v for v in vals if v is not None)
                else:
                    rd["result"] = _REDUCE_OPS[op](
                        [np.asarray(v) for v in vals])
                rd["done"] = True
                self._cv.notify_all()
            else:
                deadline = time.monotonic() + timeout
                while not rd["done"]:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise TimeoutError(
                            f"collective seq={seq}: "
                            f"{len(rd['values'])}/{self._world} ranks arrived")
                    self._cv.wait(remaining)
            result = rd["result"]
            rd.setdefault("retrieved", 0)
            rd["retrieved"] += 1
            if rd["retrieved"] == self._world:
                del self._rounds[seq]
            return result

    def send(self, src: int, dst: int, tag: int, value):
        with self._cv:
            self._p2p[(src, dst, tag)] = value
            self._cv.notify_all()

    def recv(self, src: int, dst: int, tag: int, timeout: float = 300.0):
        deadline = time.monotonic() + timeout
        with self._cv:
            while (src, dst, tag) not in self._p2p:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(f"recv src={src} tag={tag} timed out")
                self._cv.wait(remaining)
            return self._p2p.pop((src, dst, tag))


class _GroupState:
    def __init__(self, actor, world_size: int, rank: int):
        self.actor = actor
        self.world_size = world_size
        self.rank = rank
        self.seq = 0

    def next_seq(self) -> int:
        s = self.seq
        self.seq += 1
        return s


_groups: dict[tuple, _GroupState] = {}
_lock = threading.Lock()


def _scope():
    """Rank-state scope: the RANK, not the process, owns group state. Two
    rank-tasks can share one worker process (the submitter pipelines onto
    warm leases), so module-global state keyed by group name alone would
    let the second rank's init clobber the first's (rank id + seq counter
    corruption → permanent barrier hangs). Actors scope by actor id (init
    and collectives happen in different method calls); tasks by task id."""
    from ray_tpu.core import api
    rt = api._try_get_runtime()
    if rt is None:
        return None
    if rt.in_actor():
        return rt._actor_state.actor_id
    return rt.current_task_id()


def _group_key(group_name: str) -> tuple:
    return (_scope(), group_name)


def init_collective_group(world_size: int, rank: int,
                          backend: str = "host",
                          group_name: str = "default") -> None:
    """Join (rank 0: create) a named collective group."""
    name = f"_collective_{group_name}"
    if rank == 0:
        try:
            actor = ray_tpu.get_actor(name, timeout=0.2)
        except Exception:  # noqa: BLE001 - not created yet
            actor = _CollectiveGroupActor.options(
                name=name, max_concurrency=max(8, world_size * 2),
                lifetime="detached").remote(world_size)
    else:
        actor = ray_tpu.get_actor(name, timeout=60.0)
    with _lock:
        _groups[_group_key(group_name)] = _GroupState(actor, world_size, rank)
        # tasks that exit without destroy_collective_group would otherwise
        # leak their scoped entries forever in a long-lived worker; keep a
        # bounded window over TASK-scoped entries only (oldest first).
        # Actor-scoped entries are intentionally long-lived across method
        # calls and must never be evicted from under a live actor.
        from ray_tpu.core.ids import TaskID
        task_keys = [k for k in _groups if isinstance(k[0], TaskID)]
        for k in task_keys[:max(0, len(task_keys) - 512)]:
            _groups.pop(k, None)


def is_group_initialized(group_name: str = "default") -> bool:
    return _group_key(group_name) in _groups


def destroy_collective_group(group_name: str = "default") -> None:
    with _lock:
        st = _groups.pop(_group_key(group_name), None)
    if st is not None and st.rank == 0:
        try:
            ray_tpu.kill(st.actor)
        except Exception:  # noqa: BLE001
            pass


def get_rank(group_name: str = "default") -> int:
    return _groups[_group_key(group_name)].rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _groups[_group_key(group_name)].world_size


def _state(group_name: str) -> _GroupState:
    st = _groups.get(_group_key(group_name))
    if st is None:
        raise RuntimeError(
            f"collective group {group_name!r} not initialized; call "
            f"init_collective_group first")
    return st


def allreduce(tensor: np.ndarray, op: str = "sum",
              group_name: str = "default") -> np.ndarray:
    st = _state(group_name)
    out = ray_tpu.get(st.actor.collect.remote(
        st.next_seq(), st.rank, np.asarray(tensor), op))
    return np.asarray(out)


def allgather(tensor: np.ndarray, group_name: str = "default") -> list:
    st = _state(group_name)
    return ray_tpu.get(st.actor.collect.remote(
        st.next_seq(), st.rank, np.asarray(tensor), "gather"))


def broadcast(tensor: Optional[np.ndarray], src_rank: int = 0,
              group_name: str = "default") -> np.ndarray:
    st = _state(group_name)
    value = np.asarray(tensor) if st.rank == src_rank else None
    out = ray_tpu.get(st.actor.collect.remote(
        st.next_seq(), st.rank, value, "bcast"))
    return np.asarray(out)


def reducescatter(tensor: np.ndarray, op: str = "sum",
                  group_name: str = "default") -> np.ndarray:
    st = _state(group_name)
    reduced = allreduce(tensor, op, group_name)
    shards = np.array_split(reduced, st.world_size)
    return shards[st.rank]


def barrier(group_name: str = "default") -> None:
    st = _state(group_name)
    ray_tpu.get(st.actor.collect.remote(st.next_seq(), st.rank, 0, "sum"))


def send(tensor: np.ndarray, dst_rank: int, group_name: str = "default",
         tag: int = 0) -> None:
    st = _state(group_name)
    ray_tpu.get(st.actor.send.remote(st.rank, dst_rank, tag,
                                     np.asarray(tensor)))


def recv(src_rank: int, group_name: str = "default", tag: int = 0) -> np.ndarray:
    st = _state(group_name)
    return np.asarray(ray_tpu.get(st.actor.recv.remote(
        src_rank, st.rank, tag)))
