"""Observability subsystems: distributed tracing (tracing.py) and
performance introspection — engine phase timers, compile-event tracking,
device-memory accounting, on-demand XProf capture (profiling.py). Local
context-manager profiling helpers remain in ray_tpu.util.profiling."""
