"""OpenAI-compatible application builder.

Matches the reference's openai-compatible router
(python/ray/llm/_internal/serve/deployments/routers/router.py +
serve/llm/openai_api_models.py): `build_openai_app(config)` returns a serve
Application whose ingress answers

    POST /v1/completions
    POST /v1/chat/completions
    GET  /v1/models
    GET  /v1/stats          (engine telemetry; ray_tpu addition)

The HTTP proxy dispatches sub-paths through the ingress deployment's
`handle_http(path, method, payload)` (ray_tpu.serve.proxy); `stream: true`
requests return chunk lists that the proxy frames as SSE.
"""

from __future__ import annotations

from typing import Optional

from ray_tpu.serve.llm.config import LLMConfig
from ray_tpu.serve.llm.llm_server import build_llm_deployment


def build_openai_app(llm_config: LLMConfig | dict,
                     route_prefix: str = "/v1",
                     name: Optional[str] = None):
    """Application: LLMServer ingress rooted at /v1 (reference
    build_openai_app, llm/_internal/serve/builders/application_builders.py)."""
    if isinstance(llm_config, dict):
        llm_config = LLMConfig(**llm_config)
    dep = build_llm_deployment(llm_config, name=name)
    dep.route_prefix = route_prefix
    return dep.bind(llm_config)
