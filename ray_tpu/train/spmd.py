"""SPMD training step: sharded init + jitted update over an ICI×DCN mesh.

This is the in-framework replacement for the reference's delegated training
step machinery (torch DDP wrap at
/root/reference/python/ray/train/torch/train_loop_utils.py:153, FSDP
passthrough :171-185, DeepSpeed examples): instead of wrapping a module with a
communication library, parameters/optimizer state carry `NamedSharding`s over
the mesh and `jax.jit` emits the collectives (grad psum over data axes,
all-gather/reduce-scatter for fsdp) on ICI.

Design notes (TPU-first):
- params are initialized *directly sharded* (`jit` with out_shardings) so an
  8B model never materializes replicated on one host;
- the step donates the previous state, so param+opt memory is reused in-place;
- loss/grad math runs in the model dtype (bf16) with fp32 accumulation where
  the model chooses; the optimizer state is fp32.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax

from ray_tpu.parallel.mesh import MeshSpec, build_mesh
from ray_tpu.parallel.sharding import (
    batch_sharding,
    infer_fsdp_sharding,
    logical_to_shardings,
    replicated,
    rule_shardings,
)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TrainState:
    """Minimal train state pytree (params + optimizer + step counter)."""

    params: Any
    opt_state: Any
    step: jax.Array

    @classmethod
    def create(cls, params, optimizer: optax.GradientTransformation):
        return cls(params=params, opt_state=optimizer.init(params),
                   step=jnp.zeros((), jnp.int32))


def default_optimizer(learning_rate: float = 3e-4,
                      weight_decay: float = 0.1,
                      warmup_steps: int = 100,
                      decay_steps: int = 10_000,
                      grad_clip: float = 1.0,
                      name: str = "adamw") -> optax.GradientTransformation:
    """Optimizer + cosine schedule + global-norm clip.

    name="adamw" is the Llama-pretrain recipe the BASELINE configs assume;
    name="adafactor" is the TPU-native memory saver (factored second moment
    — T5/PaLM recipe): adam's fp32 m+v cost 8 bytes/param (12 GB for 1.5B,
    most of a v5e chip's HBM), adafactor's factored state is ~0 — the
    difference between OOM and headroom for remat policies / larger models
    on one chip."""
    sched = optax.warmup_cosine_decay_schedule(
        0.0, learning_rate, warmup_steps, max(decay_steps, warmup_steps + 1))
    if name == "adafactor":
        # NO weight decay here: optax.adafactor's weight_decay_rate is NOT
        # learning-rate-scaled (0.1 would shrink params 10% per step) —
        # the T5/PaLM adafactor recipe trains without decoupled decay
        return optax.chain(
            optax.clip_by_global_norm(grad_clip),
            optax.adafactor(sched, min_dim_size_to_factor=128),
        )
    if name != "adamw":
        raise ValueError(f"unknown optimizer {name!r} (adamw | adafactor)")
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(sched, b1=0.9, b2=0.95, weight_decay=weight_decay),
    )


def state_shardings(params_logical_axes, params_shape, mesh,
                    optimizer: optax.GradientTransformation,
                    rules: dict | None = None,
                    partition_rules=None):
    """Shardings for a full TrainState.

    Param shardings come from ONE of three sources, in priority order:
    regex ``partition_rules`` ((pattern, PartitionSpec) pairs matched
    against slash-joined param paths via the shared
    ``parallel.sharding.match_partition_rules`` — the same machinery the
    TP serving engine uses), logical-axis annotations, or shape-driven
    FSDP inference. Optimizer state shards like the params it mirrors
    (adam mu/nu are param-shaped); scalars/schedules replicate.
    """
    if partition_rules is not None:
        p_sh = rule_shardings(partition_rules, params_shape, mesh)
    elif params_logical_axes is not None:
        p_sh = logical_to_shardings(params_logical_axes, mesh, rules)
    else:
        p_sh = infer_fsdp_sharding(params_shape, mesh)

    # Build optimizer state shape via eval_shape, then map param-shaped leaves
    # to the matching param sharding and everything else to replicated.
    opt_shape = jax.eval_shape(optimizer.init, params_shape)
    flat_params, _ = jax.tree_util.tree_flatten(params_shape)
    flat_sh, _ = jax.tree_util.tree_flatten(
        p_sh, is_leaf=lambda x: isinstance(x, jax.sharding.NamedSharding))
    by_shape = {}
    for leaf, sh in zip(flat_params, flat_sh):
        by_shape.setdefault((tuple(leaf.shape), jnp.dtype(leaf.dtype).name), sh)

    def opt_leaf(leaf):
        key = (tuple(getattr(leaf, "shape", ())),
               jnp.dtype(getattr(leaf, "dtype", jnp.float32)).name)
        return by_shape.get(key, replicated(mesh))

    # A param-shaped opt leaf gets the param's sharding only if shapes match
    # one-to-one; collisions fall back to replicated-safe behavior above.
    opt_sh = jax.tree.map(opt_leaf, opt_shape)
    return TrainState(params=p_sh, opt_state=opt_sh,
                      step=replicated(mesh))


def sharded_create_state(init_params_fn: Callable[[], Any],
                         optimizer: optax.GradientTransformation,
                         mesh, params_logical_axes=None,
                         rules: dict | None = None,
                         partition_rules=None) -> tuple[TrainState, Any]:
    """Initialize a TrainState directly sharded on the mesh (ZeRO-style init:
    no replicated materialization). Returns (state, state_shardings)."""
    params_shape = jax.eval_shape(init_params_fn)
    sh = state_shardings(params_logical_axes, params_shape, mesh, optimizer,
                         rules, partition_rules)

    def init():
        params = init_params_fn()
        return TrainState.create(params, optimizer)

    state = jax.jit(init, out_shardings=sh)()
    return state, sh


def make_train_step(loss_fn: Callable, optimizer: optax.GradientTransformation,
                    mesh, sh: TrainState, *, donate: bool = True):
    """Build the jitted SPMD train step.

    loss_fn(params, batch) -> scalar loss.
    Returns step(state, batch) -> (state, metrics dict).
    """
    b_sh = batch_sharding(mesh)

    def step(state: TrainState, batch):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        updates, opt_state = optimizer.update(
            grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        gnorm = optax.global_norm(grads)
        new = TrainState(params=params, opt_state=opt_state,
                         step=state.step + 1)
        return new, {"loss": loss, "grad_norm": gnorm, "step": new.step}

    in_batch = jax.tree.map(lambda _: b_sh, jax.tree.structure((0,)))
    del in_batch  # batch sharding applied via in_shardings below
    return jax.jit(
        step,
        in_shardings=(sh, None),
        out_shardings=(sh, None),
        donate_argnums=(0,) if donate else (),
    )


def shard_batch(batch, mesh):
    """Device-put a host batch sharded over the data axes (dim 0)."""
    b_sh = batch_sharding(mesh)

    def put(x):
        extra = getattr(x, "ndim", 1) - 1
        sh = batch_sharding(mesh, extra_dims=extra)
        return jax.device_put(x, sh)

    return jax.tree.map(put, batch)


def make_mesh(n_devices: int | None = None, devices=None,
              **spec_kw) -> jax.sharding.Mesh:
    """Convenience: infer a MeshSpec over the visible devices and build it."""
    if devices is None:
        devices = jax.devices()
    n = n_devices or len(devices)
    spec = MeshSpec.infer(n, **spec_kw)
    return build_mesh(spec, devices[:n])
