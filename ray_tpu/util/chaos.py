"""Chaos testing harness: kill cluster components under load.

TPU-native analog of the reference's chaos tooling (SURVEY.md §5.2:
rpc_chaos.cc deterministic RPC faults — mirrored in ray_tpu.core.rpc — plus
the release-test node killers, `ray._private.test_utils` get_and_run_
resource_killer). RPC-level faults live in `core/rpc.py` (config
`testing_rpc_failure`); this module adds the PROCESS level: a killer thread
that terminates random worker processes (or whole node agents) while a
workload runs, so retry/restart/reconstruction paths are exercised
systematically instead of by hand-written one-off tests.
"""

from __future__ import annotations

import random
import threading
import time


class WorkerKiller:
    """Kills random task-executing worker PROCESSES of a cluster at an
    interval. Drive it around a workload whose tasks have retries:

        killer = WorkerKiller(cluster_or_none, interval_s=0.5)
        killer.start()
        try:    ... run workload with max_retries > 0 ...
        finally: report = killer.stop()
    """

    def __init__(self, cluster=None, *, interval_s: float = 0.5,
                 kill_probability: float = 1.0, seed: int = 0,
                 spare_actors: bool = True, max_kills: int | None = None):
        self._cluster = cluster
        self._interval = interval_s
        self._prob = kill_probability
        self._rng = random.Random(seed)
        self._spare_actors = spare_actors
        # cap total kills (parity with NodeKiller) so chaos-under-serve
        # tests are deterministic and bounded; None = unbounded
        self._max = max_kills
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.kills = 0

    def _agents(self):
        if self._cluster is not None:
            return list(self._cluster.nodes)
        from ray_tpu.core import api
        head = api._head
        return [head[1]] if head is not None else []

    def _victims(self):
        out = []
        for agent in self._agents():
            with agent._lock:
                for info in agent._workers.values():
                    if info.proc is None or info.proc.poll() is not None:
                        continue
                    if self._spare_actors and info.actor_id is not None:
                        continue
                    out.append(info.proc)
        return out

    def _loop(self):
        while not self._stop.wait(self._interval):
            if self._max is not None and self.kills >= self._max:
                return
            if self._rng.random() > self._prob:
                continue
            victims = self._victims()
            if not victims:
                continue
            victim = self._rng.choice(victims)
            try:
                victim.kill()
                self.kills += 1
            except Exception:  # noqa: BLE001 - already gone
                pass

    def start(self) -> "WorkerKiller":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="chaos-worker-killer", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> dict:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        return {"kills": self.kills}


class NodeKiller:
    """Kills (stops) random NON-HEAD node agents of an in-process Cluster —
    the coarse-grained chaos the reference's release tests run against
    autoscaled clusters."""

    def __init__(self, cluster, *, interval_s: float = 2.0, seed: int = 0,
                 max_kills: int = 1):
        self._cluster = cluster
        self._interval = interval_s
        self._rng = random.Random(seed)
        self._max = max_kills
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.killed: list = []

    def _loop(self):
        while not self._stop.wait(self._interval):
            if len(self.killed) >= self._max:
                return
            candidates = [a for a in self._cluster.nodes[1:]
                          if a not in self.killed]
            if not candidates:
                continue
            agent = self._rng.choice(candidates)
            try:
                agent.stop()
                self.killed.append(agent)
            except Exception:  # noqa: BLE001
                pass

    def start(self) -> "NodeKiller":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="chaos-node-killer", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> dict:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        return {"nodes_killed": len(self.killed)}


def run_with_chaos(workload, *, killer) -> tuple:
    """Run `workload()` with `killer` active; returns (result, report)."""
    killer.start()
    try:
        result = workload()
    finally:
        report = killer.stop()
    return result, report


class FaultSchedule:
    """Deterministic timed fault injection: a seeded schedule of cluster
    faults fired at fixed offsets from start() (reference: the release
    chaos tests' resource killers, made reproducible — same seed + same
    schedule = same victims in the same order).

        sched = FaultSchedule(cluster, [
            (1.0, "worker_kill", {}),
            (2.5, "node_kill", {}),
            (4.0, "node_drain", {"wait": True}),
            (5.0, "cp_restart", {"down_s": 1.0}),
            (6.0, "rpc_delay", {"spec": "*:0:0:0.05", "duration_s": 2.0}),
        ], seed=7)
        sched.start()
        ...  # drive traffic
        sched.join()
        print(sched.report)

    Event kinds:
      worker_kill  kill one random non-actor (or any, spare_actors=False)
                   worker process
      node_kill    hard-stop a random non-head node agent
      node_drain   graceful drain of a random non-head node (the full
                   protocol: no new leases, in-flight completes, objects
                   migrate); {"wait": True} blocks until drained
      cp_restart   kill the control plane, wait {"down_s"}, restart it on
                   the same address
      rpc_delay    stall matched RPC handlers via testing_rpc_failure
                   ({"spec": "*:0:0:DELAY", "duration_s": S})
      rpc_drop     drop matched RPCs ({"spec": "*:PROB", "duration_s": S})
      replica_kill kill a serve REPLICA actor (ISSUE 14): named via
                   {"app", "deployment"}, picked by {"replica_index"} or
                   {"busiest": True} (live queue-length probe), else a
                   random one; {"prepare": True} first runs a short
                   prepare_for_shutdown (SIGTERM-with-grace: the replica
                   eager-spills in-flight KV chains) before the hard kill
      replica_scale retarget a serve deployment mid-traffic (ISSUE 17):
                   {"app", "deployment"} plus {"target": N} or
                   {"delta": +/-n}. Scale-up goes through the controller's
                   cache-warm path (STARTING -> WARMING -> atomic
                   publish); scale-down drains the coldest replica —
                   in-flight streams finish or resume token-identically

    Every event appends {"t", "kind", "ok", "detail"} to `report`."""

    KINDS = ("worker_kill", "node_kill", "node_drain", "cp_restart",
             "rpc_delay", "rpc_drop", "replica_kill", "replica_scale")

    def __init__(self, cluster, events, *, seed: int = 0):
        for _, kind, _kw in events:
            if kind not in self.KINDS:
                raise ValueError(f"unknown fault kind {kind!r}")
        self._cluster = cluster
        self._events = sorted(events, key=lambda e: e[0])
        self._rng = random.Random(seed)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.report: list[dict] = []

    # ---- event implementations ----------------------------------------
    def _do_worker_kill(self, kw) -> str:
        spare_actors = bool(kw.get("spare_actors", False))
        victims = []
        for agent in self._cluster.nodes:
            with agent._lock:
                for info in agent._workers.values():
                    if info.proc is None or info.proc.poll() is not None:
                        continue
                    if spare_actors and info.actor_id is not None:
                        continue
                    victims.append(info.proc)
        if not victims:
            return "no victim workers"
        victim = self._rng.choice(victims)
        victim.kill()
        return f"killed worker pid={victim.pid}"

    def _pick_node(self, kw):
        idx = kw.get("node_index")
        if idx is not None:
            return self._cluster.nodes[idx]
        candidates = self._cluster.nodes[1:]  # never the head-ish node 0
        if not candidates:
            raise RuntimeError("no non-head nodes to target")
        return self._rng.choice(candidates)

    def _do_node_kill(self, kw) -> str:
        agent = self._pick_node(kw)
        nid = agent.node_id.hex()[:8]
        self._cluster.remove_node(agent, graceful=False)
        return f"killed node {nid}"

    def _do_node_drain(self, kw) -> str:
        agent = self._pick_node(kw)
        nid = agent.node_id.hex()[:8]
        if kw.get("wait", True):
            # full blocking protocol, then stop the drained agent
            self._cluster.remove_node(agent, graceful=True)
            return f"drained node {nid}"
        self._cluster.control_plane._h_drain_node(
            {"node_id": agent.node_id, "reason": "chaos"})
        return f"draining node {nid} (async)"

    def _do_cp_restart(self, kw) -> str:
        down_s = float(kw.get("down_s", 1.0))
        addr = self._cluster.kill_control_plane()
        self._stop.wait(down_s)
        self._cluster.restart_control_plane(addr)
        return f"cp restarted after {down_s}s at {addr[0]}:{addr[1]}"

    def _rpc_fault(self, kw, default_spec: str) -> str:
        from ray_tpu.core.config import get_config
        spec = kw.get("spec", default_spec)
        duration_s = float(kw.get("duration_s", 1.0))
        cfg = get_config()
        prev = cfg.testing_rpc_failure
        cfg.testing_rpc_failure = spec
        try:
            self._stop.wait(duration_s)
        finally:
            cfg.testing_rpc_failure = prev
        return f"rpc fault {spec!r} for {duration_s}s"

    def _do_rpc_delay(self, kw) -> str:
        return self._rpc_fault(kw, "*:0:0:0.05")

    def _do_rpc_drop(self, kw) -> str:
        return self._rpc_fault(kw, "*:0.3")

    def _do_replica_kill(self, kw) -> str:
        import ray_tpu
        ctl = ray_tpu.get_actor("_serve_controller", timeout=2.0)
        app, dep = kw.get("app"), kw.get("deployment")
        if app is None or dep is None:
            status = ray_tpu.get(ctl.status.remote(), timeout=5.0)
            for full in status:          # full names are "app#deployment"
                a, d = full.split("#", 1)
                if (app is None or a == app) and (dep is None or d == dep):
                    app, dep = a, d
                    break
        if app is None or dep is None:
            return "no serve deployments to target"
        table = ray_tpu.get(ctl.get_routing_table.remote(app), timeout=5.0)
        entry = table.get(dep)
        if not entry or not entry[0]:
            return f"no replicas for {app}#{dep}"
        replicas = list(entry[0])
        idx = kw.get("replica_index")
        if idx is not None:
            victim = replicas[int(idx) % len(replicas)]
        elif kw.get("busiest"):
            # live probe: the replica holding the most in-flight work is
            # exactly the one whose death exercises mid-stream failover
            qlens = []
            for r in replicas:
                try:
                    qlens.append(int(ray_tpu.get(r.get_queue_len.remote(),
                                                 timeout=2.0)))
                except Exception:  # noqa: BLE001 — dead looks idle
                    qlens.append(-1)
            victim = replicas[max(range(len(replicas)),
                                  key=lambda i: qlens[i])]
        else:
            victim = self._rng.choice(replicas)
        prepared = ""
        if kw.get("prepare"):
            # SIGTERM-with-grace: a short prepare window lets the replica
            # eager-spill its in-flight KV chains before the hard kill
            try:
                ray_tpu.get(victim.prepare_for_shutdown.remote(
                    timeout_s=float(kw.get("prepare_timeout_s", 0.2))),
                    timeout=10.0)
                prepared = " (prepared)"
            except Exception:  # noqa: BLE001 — kill regardless
                pass
        ray_tpu.kill(victim)
        aid = getattr(victim, "_actor_id", None)
        aid = aid.hex()[:8] if hasattr(aid, "hex") else "?"
        return f"killed replica {app}#{dep}[{aid}]{prepared}"

    def _do_replica_scale(self, kw) -> str:
        import ray_tpu
        ctl = ray_tpu.get_actor("_serve_controller", timeout=2.0)
        app, dep = kw.get("app"), kw.get("deployment")
        if app is None:
            status = ray_tpu.get(ctl.status.remote(), timeout=5.0)
            for full in status:          # full names are "app#deployment"
                a, d = full.split("#", 1)
                if dep is None or d == dep:
                    app, dep = a, d
                    break
        if app is None:
            return "no serve deployments to target"
        res = ray_tpu.get(ctl.set_target_replicas.remote(
            app, deployment=dep, target=kw.get("target"),
            delta=kw.get("delta"), reason="chaos"), timeout=10.0)
        return f"retargeted {res}"

    # ---- driver --------------------------------------------------------
    def _loop(self):
        t0 = time.monotonic()
        for offset, kind, kw in self._events:
            delay = t0 + offset - time.monotonic()
            if delay > 0 and self._stop.wait(delay):
                return
            if self._stop.is_set():
                return
            entry = {"t": offset, "kind": kind}
            t_inject = time.time()
            try:
                entry["detail"] = getattr(self, "_do_" + kind)(
                    dict(kw or {}))
                entry["ok"] = True
            except Exception as e:  # noqa: BLE001 — recorded, not raised
                entry["detail"] = repr(e)
                entry["ok"] = False
            self.report.append(entry)
            # ground-truth journal event (ISSUE 19): every injected fault
            # is on the record, stamped at INJECTION time so its symptom
            # events (replica_death/node_dead/...) sort after it. Emitted
            # AFTER the injection returns — a cp_restart's event must land
            # in the restarted CP, and the flusher backlog carries it
            # across any outage window either way.
            from ray_tpu.observability import events as _fr
            _fr.emit("chaos_fault",
                     "WARNING" if entry["ok"] else "ERROR",
                     reason=kind, ts=t_inject,
                     attrs={"kind": kind, "kwargs": dict(kw or {}),
                            "ok": entry["ok"],
                            "detail": str(entry["detail"])[:500]})

    def start(self) -> "FaultSchedule":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="chaos-schedule", daemon=True)
            self._thread.start()
        return self

    def join(self, timeout: float | None = None) -> list[dict]:
        """Wait for the schedule to finish firing; returns the report."""
        if self._thread is not None:
            self._thread.join(timeout)
        return self.report

    def stop(self) -> list[dict]:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        return self.report
