"""Benchmark: Llama pretraining step throughput (tokens/sec/chip).

North-star metric per BASELINE.json ("Ray Train tokens/sec/chip @
Llama-3-8B"); the reference repo publishes no number for it ("published": {}),
so vs_baseline reports model-FLOPs utilization (MFU) against the chip's bf16
roofline instead (1.0 = peak matmul throughput).

Runs an A/B over attention implementations (dense einsum vs the Pallas flash
kernel, ops/attention.py) on the largest Llama config that fits the visible
chip, and reports the better one as the headline with both in "extra".
The true 8B config needs a v5p-64 pod (BASELINE target); one v5e chip tops
out around ~2B params with remat+bf16, so the bench scales the config to the
chip and says so rather than faking the 8B label.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


# bf16 peak TFLOP/s per chip for MFU reporting (best-effort device match)
_PEAK_TFLOPS = {
    "v4": 275.0, "v5e": 197.0, "v5p": 459.0, "v6e": 918.0,
}


def _peak_tflops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for key, val in _PEAK_TFLOPS.items():
        if key in kind:
            return val
    return _PEAK_TFLOPS["v5e"]  # conservative default


def _make_step(cfg, dev, optimizer: str):
    """Shared recipe for BOTH the static-batch and data-plane runs — one
    copy so the A/B always compares identical training setups."""
    from ray_tpu.models import llama
    from ray_tpu.train import spmd

    mesh = spmd.make_mesh(1, devices=[dev])
    # adafactor: adam's fp32 moments cost 8 bytes/param — most of one v5e's
    # HBM at 1.5B params; factored state frees it for the "dots" remat
    # policy (saved matmul outputs, no backward recompute), the single
    # biggest measured MFU lever on this chip
    opt = spmd.default_optimizer(warmup_steps=10, decay_steps=1000,
                                 name=optimizer)
    state, sh = spmd.sharded_create_state(
        lambda: llama.init_params(jax.random.PRNGKey(0), cfg), opt, mesh,
        params_logical_axes=llama.logical_axes(cfg))
    step = spmd.make_train_step(
        lambda p, b: llama.loss_fn(p, b, cfg, mesh), opt, mesh, sh)
    return mesh, state, step


def _run_config(cfg, batch: int, seq: int, steps: int, warmup: int, dev,
                optimizer: str = "adafactor"):
    from ray_tpu.train import spmd

    mesh, state, step = _make_step(cfg, dev, optimizer)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(
        rng.integers(0, cfg.vocab_size, (batch, seq + 1)), jnp.int32)
    batch_data = spmd.shard_batch({"tokens": tokens}, mesh)

    # NOTE: force a device->host transfer as the sync barrier —
    # block_until_ready is not a reliable fence over the axon tunnel.
    for _ in range(warmup):
        state, metrics = step(state, batch_data)
    float(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, batch_data)
    float(metrics["loss"])
    dt = time.perf_counter() - t0
    return batch * seq * steps / dt


def _run_data_pipeline(cfg, batch: int, seq: int, steps: int, warmup: int,
                       dev, optimizer: str = "adafactor") -> float:
    """Same train step, but batches arrive through the REAL Data plane:
    synthetic tokens generated in Data tasks -> streaming_split ->
    iter_jax_batches HBM double-buffering (reference:
    release/train_tests/benchmark/train_benchmark.py drives training
    through ray.data the same way). Returns tokens/s; the delta vs the
    static-batch path is the input-pipeline cost."""
    from ray_tpu import data as rdata
    from ray_tpu.train import spmd

    mesh, state, step = _make_step(cfg, dev, optimizer)
    n_rows = (steps + warmup) * batch
    vocab = cfg.vocab_size
    seqlen = seq

    def gen_tokens(b: dict) -> dict:
        rng = np.random.default_rng(int(b["id"][0]))
        return {"tokens": rng.integers(
            0, vocab, (len(b["id"]), seqlen + 1)).astype(np.int32)}

    ds = rdata.range(n_rows).map_batches(gen_tokens, batch_size=batch)
    (it,) = ds.streaming_split(1)
    sharding = spmd.batch_sharding(mesh, extra_dims=1)
    batches = it.iter_jax_batches(batch_size=batch, sharding=sharding,
                                  prefetch_batches=2)

    for _ in range(warmup):
        state, metrics = step(state, next(batches))
    if warmup:
        float(metrics["loss"])
    t0 = time.perf_counter()
    n = 0
    for batch_data in batches:
        state, metrics = step(state, batch_data)
        n += 1
    float(metrics["loss"])
    dt = time.perf_counter() - t0
    return batch * seq * n / dt


def main() -> None:
    import dataclasses

    from ray_tpu.models import llama

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    if on_tpu:
        # Measured recipe for one v5e chip at 1.5B params / seq 2048 (the 8B
        # config's sequence length; the 8B model itself needs a pod —
        # BASELINE's v5p-64): flash attention + "dots" remat (no backward
        # recompute) + adafactor + batch 4. Sweep results on this chip:
        # full-remat b8 flash 0.446 MFU, dots b4 flash 0.49-0.51, dense
        # dots b4 0.42, 3.6B full-remat b4 0.39.
        # ce_remat=False: keep the CE chunk's fp32 logits as residuals
        # instead of recomputing the lm_head matmul in backward — the
        # 4.2 GB residual fits at b4 and buys ~33 ms/step (r5 CE lever)
        base = llama.llama3_1b(max_seq_len=2048, remat_policy="dots",
                               ce_chunk=2048, ce_remat=False)
        batch, seq, steps, warmup = 4, 2048, 10, 3
        impls = ("dense", "flash")
        optimizer = "adafactor"  # frees adam's 12GB of fp32 moments for dots
    else:
        base = llama.llama_tiny()
        batch, seq, steps, warmup = 8, 64, 5, 2
        impls = ("dense",)  # pallas interpret mode is too slow to bench
        optimizer = "adamw"  # the BASELINE recipe; tiny model fits anywhere

    results: dict[str, float] = {}
    for impl in impls:
        cfg = dataclasses.replace(base, attn_impl=impl)
        try:
            results[impl] = _run_config(cfg, batch, seq, steps, warmup, dev,
                                        optimizer=optimizer)
        except Exception as e:  # noqa: BLE001 - report the surviving impl
            results[impl] = float("nan")
            print(f"# {impl} failed: {e!r}", file=sys.stderr)

    ok = {k: v for k, v in results.items() if v == v}  # drop NaN (failed)
    best_impl = max(ok, key=ok.get) if ok else "none"
    tok_per_s = ok.get(best_impl, float("nan"))

    # Data-plane A/B: the same step fed through streaming_split ->
    # iter_jax_batches (tokens generated in Data tasks). Reported as the
    # input-pipeline cost vs the static-batch headline.
    data_tps = None
    if ok:
        import os as _os

        import ray_tpu
        from ray_tpu.core import config as _cfgmod
        try:
            # Honest overlap: cap the executor's output buffering so block
            # generation CANNOT pre-complete during warmup (13 tiny blocks
            # would otherwise all materialize before t0 and the "pipeline
            # cost" would measure queue pulls only), and run 3x the steps
            # so most generation lands inside the timed region.
            _os.environ.setdefault("RAY_TPU_DATA_OP_OUTPUT_BUFFER_BYTES",
                                   str(64 * 1024))
            _cfgmod.reset_config()
            ray_tpu.init(num_cpus=4)
            cfg = dataclasses.replace(base, attn_impl=best_impl)
            data_tps = round(_run_data_pipeline(
                cfg, batch, seq, steps * 3, warmup, dev,
                optimizer=optimizer), 1)
        except Exception as e:  # noqa: BLE001 — A/B must not sink the bench
            print(f"# data pipeline A/B failed: {e!r}", file=sys.stderr)
        finally:
            try:
                ray_tpu.shutdown()
            except Exception:  # noqa: BLE001
                pass

    n_params = llama.num_params(base)
    peak = _peak_tflops(dev)

    def mfu(tps: float) -> float | None:
        if not on_tpu or tps != tps:
            return None
        return round((6.0 * n_params * tps) / (peak * 1e12), 4)

    print(json.dumps({
        "metric": "llama_train_tokens_per_sec_per_chip",
        "value": round(tok_per_s, 1) if tok_per_s == tok_per_s else None,
        "unit": "tokens/s/chip",
        "vs_baseline": mfu(tok_per_s),
        "extra": {
            "attn_impl": best_impl,
            "per_impl_tokens_per_s": {k: (round(v, 1) if v == v else None)
                                      for k, v in results.items()},
            "per_impl_mfu": {k: mfu(v) for k, v in results.items()},
            "params": n_params,
            "batch": batch, "seq": seq,
            "device": getattr(dev, "device_kind", str(dev)),
            "data_pipeline_tokens_per_s": data_tps,
            "data_pipeline_cost_pct": round(
                100.0 * (1.0 - data_tps / tok_per_s), 2)
            if data_tps and tok_per_s == tok_per_s else None,
        },
    }))


if __name__ == "__main__":
    sys.exit(main())
