"""Mid-stream generation failover (ISSUE 14): token-identical resumption
of in-flight LLM requests after replica death.

Pins the PR's acceptance invariants:
- a continuation submit (original prompt + already-generated tokens) is
  admitted through the cache-aware path and the resumed decode is
  bit-identical to an uninterrupted greedy run, on all three admission
  paths: local prefix hit, kv-tier restore of another engine's eager
  spill, and cold recompute (no cache at all);
- `spill_inflight` pushes every LIVE chain's computed pages into the
  tier NOW (drain/SIGTERM path), so a surviving replica restores the
  dead replica's progress instead of recomputing it;
- past the resume cap (or with failover disabled) the server degrades to
  a plain retry-from-scratch with the already-streamed prefix
  suppressed — never a duplicated or missing token;
- the ambient request deadline binds across the handoff: an expired
  continuation is shed, not computed;
- the proxy splices a resumed stream with zero duplicated/missing
  tokens, emits a single `event: resumed` frame, keeps the X-Request-Id,
  and lands an ordered `failover` stage in the attribution timeline.
"""

import json
import threading
import time
import urllib.request
import uuid

import pytest

import ray_tpu


def _cfg(**kw):
    from ray_tpu.models import llama
    from ray_tpu.serve.llm import LLMConfig

    d = dict(model_config=llama.llama_tiny(vocab_size=512),
             max_batch_size=4, page_size=16, num_pages=64,
             max_prompt_len=96, max_seq_len=160, max_tokens=8)
    d.update(kw)
    return LLMConfig(**d)


PROMPT = "the quick brown fox jumps over the lazy dog"   # 43 byte-tokens
LONG = PROMPT + " " + PROMPT                             # 87 -> 5 full pages

_WANT: dict = {}


def _want_tokens(prompt, max_tokens=8):
    """Greedy ground truth from a cache-off, tier-off engine (memoized —
    engine startup dominates this suite's runtime)."""
    from ray_tpu.serve.llm import LLMEngine

    key = (prompt, max_tokens)
    if key not in _WANT:
        off = LLMEngine(_cfg(prefix_cache_enabled=False), rng_seed=0)
        off.start()
        try:
            _WANT[key] = off.generate(prompt, max_tokens=max_tokens,
                                      temperature=0.0)["tokens"]
        finally:
            off.shutdown()
    return _WANT[key]


def _wait(pred, timeout=60.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return pred()


# ---------------------------------------------------------------------------
# unit: continuation-vs-degrade gating (llm_server policy)
# ---------------------------------------------------------------------------


def test_resume_plan_gating():
    """Within the cap a resumed leg is a continuation (skip 0); past the
    cap — or with failover off — it degrades to retry-from-scratch with
    the full already-streamed prefix suppressed."""
    from ray_tpu.serve.llm.llm_server import _resume_plan

    cfg = _cfg()
    assert _resume_plan([], 0, cfg) == (False, 0)
    assert _resume_plan(None, 0, cfg) == (False, 0)
    assert _resume_plan([1, 2, 3], 1, cfg) == (True, 0)
    assert _resume_plan([1, 2, 3], cfg.failover_max_resumes, cfg) == (True, 0)
    assert _resume_plan([1, 2, 3], cfg.failover_max_resumes + 1,
                        cfg) == (False, 3)
    off = _cfg(failover_enabled=False)
    assert _resume_plan([1, 2], 1, off) == (False, 2)


def test_continuation_submit_rejected_when_disabled():
    """The engine refuses continuation admission when the operator turned
    failover off — the caller must fall back to retry-from-scratch."""
    from ray_tpu.serve.llm import LLMEngine

    eng = LLMEngine(_cfg(failover_enabled=False), rng_seed=0)
    try:
        with pytest.raises(ValueError, match="failover_enabled"):
            eng.submit(PROMPT, resume_tokens=[1, 2, 3])
    finally:
        eng.shutdown()


# ---------------------------------------------------------------------------
# engine: continuation admission token identity
# ---------------------------------------------------------------------------


def test_continuation_cold_recompute_token_identity():
    """No cache anywhere: the continuation chunk-prefills prompt+resume
    from scratch and decode still resumes at the exact next token."""
    from ray_tpu.serve.llm import LLMEngine

    want = _want_tokens(PROMPT, 8)
    eng = LLMEngine(_cfg(prefix_cache_enabled=False), rng_seed=0)
    eng.start()
    try:
        for k in (1, 4, 7):
            rid = eng.submit(PROMPT, resume_tokens=want[:k],
                             max_tokens=8 - k, temperature=0.0)
            out = eng.result(rid, timeout=180.0)
            assert out["error"] is None, out
            assert out["tokens"] == want[k:], f"diverged at resume k={k}"
        st = eng.engine_stats()
        assert st["failover_resumed"] == 3
        assert st["failover_restored_tokens"] == 0  # nothing to recover
    finally:
        eng.shutdown()


def test_continuation_local_prefix_token_identity():
    """Same-replica resume: the original leg's prompt pages are resident,
    so the continuation admits over the local prefix match and only the
    resume suffix is prefilled."""
    from ray_tpu.serve.llm import LLMEngine

    want = _want_tokens(LONG, 8)
    eng = LLMEngine(_cfg(), rng_seed=0)
    eng.start()
    try:
        assert eng.generate(LONG, temperature=0.0)["tokens"] == want
        k = 5
        rid = eng.submit(LONG, resume_tokens=want[:k],
                         max_tokens=8 - k, temperature=0.0)
        out = eng.result(rid, timeout=180.0)
        assert out["error"] is None, out
        assert out["tokens"] == want[k:]
        st = eng.engine_stats()
        assert st["failover_resumed"] == 1
        # LONG's 5 full prompt pages were resident from the first leg
        assert st["failover_restored_tokens"] >= 4 * 16
    finally:
        eng.shutdown()


def test_request_progress_journal():
    """request_progress exposes the per-request journal the failover
    path re-dispatches from; unknown ids answer None."""
    from ray_tpu.serve.llm import LLMEngine

    eng = LLMEngine(_cfg(), rng_seed=0)
    eng.start()
    try:
        assert eng.request_progress("no-such-request") is None
        rid = eng.submit(LONG, max_tokens=8, temperature=0.0)
        assert _wait(lambda: bool(
            (eng.request_progress(rid) or {}).get("generated")))
        prog = eng.request_progress(rid)
        assert prog["prompt_tokens"] == len(eng.tokenizer.encode(LONG))
        assert prog["resume_len"] == 0
        assert prog["admitted"] is True
        out = eng.result(rid, timeout=180.0)
        assert out["error"] is None
    finally:
        eng.shutdown()


# ---------------------------------------------------------------------------
# engine: eager in-flight spill (drain/SIGTERM path)
# ---------------------------------------------------------------------------


def test_eager_spill_inflight_pushes_live_chains():
    """spill_inflight spills the computed full pages of LIVE requests
    (ordinary spill only fires at pool eviction); a tier-off engine
    answers 0."""
    from ray_tpu.serve.llm import LLMEngine

    off = LLMEngine(_cfg(), rng_seed=0)
    try:
        assert off.spill_inflight() == 0
    finally:
        off.shutdown()

    eng = LLMEngine(_cfg(kv_tier_enabled=True), rng_seed=0)
    eng.start()
    try:
        rid = eng.submit(LONG, max_tokens=64, temperature=0.0)
        assert _wait(lambda: len(
            (eng.request_progress(rid) or {}).get("generated") or ()) >= 2,
            timeout=120.0)
        n = eng.spill_inflight()
        # 5 full prompt pages are computed the moment decode starts
        assert n >= 5, f"spilled only {n} pages for a live 5-page prompt"
        assert _wait(lambda: eng.engine_stats()["spilled_pages"] >= 5)
        out = eng.result(rid, timeout=180.0)
        assert out["error"] is None
    finally:
        eng.shutdown()


# ---------------------------------------------------------------------------
# engine: deadline carried across the handoff
# ---------------------------------------------------------------------------


def test_expired_deadline_sheds_continuation():
    """The proxy re-dispatches under the ambient deadline scope: a
    continuation whose deadline already passed must be shed by the
    engine, not silently recomputed."""
    from ray_tpu.core import deadline as request_deadline
    from ray_tpu.serve.llm import LLMEngine

    eng = LLMEngine(_cfg(), rng_seed=0)
    eng.start()
    try:
        with request_deadline.scope(time.time() - 0.5):
            rid = eng.submit(PROMPT, resume_tokens=[5, 6, 7], max_tokens=4,
                             temperature=0.0)
        out = eng.result(rid, timeout=60.0)
        assert out["error"] == "deadline exceeded"
        assert out["tokens"] == []
    finally:
        eng.shutdown()


# ---------------------------------------------------------------------------
# attribution: the failover stage is a first-class ordered stage
# ---------------------------------------------------------------------------


def test_failover_stage_ordered_in_timeline():
    from ray_tpu.observability import attribution
    from ray_tpu.observability.attribution import Timeline

    assert "failover" in attribution.STAGES
    idx = attribution._STAGE_INDEX
    assert idx["route"] < idx["failover"] < idx["queue"]

    tl = Timeline("fo-tl")
    # stamped in arrival order: the failover stamp lands when the FIRST
    # resumed chunk arrives, after the engine stages of the dead leg
    tl.stamp("ingress", 1.0, 1.001)
    tl.stamp("route", 1.001, 1.002)
    tl.extend([
        {"stage": "queue", "start": 1.3, "end": 1.31, "attrs": {}},
        {"stage": "restore", "start": 1.31, "end": 1.35,
         "attrs": {"restored_tokens": 96}},
        {"stage": "prefill", "start": 1.35, "end": 1.4, "attrs": {}},
        {"stage": "decode", "start": 1.4, "end": 1.6, "attrs": {}},
    ])
    tl.stamp("failover", 1.1, 1.35, attempt=1, resumed=True,
             restored_tokens=96, restore_bytes=12288, restore_ms=40.0)
    names = [s["stage"] for s in tl.ordered_stages()]
    assert names == ["ingress", "route", "failover", "queue", "restore",
                     "prefill", "decode"]
    fo = next(s for s in tl.ordered_stages() if s["stage"] == "failover")
    assert fo["attrs"]["restored_tokens"] == 96
    assert fo["attrs"]["resumed"] is True

    rec = {"request_id": "fo-agg", "ts": time.time(), "app": "a",
           "deployment": "d", "replica": "rep-a", "source": "src",
           "kind": "violation", "violated": ["e2e"], "ttft_ms": 10.0,
           "e2e_ms": 600.0, "policy": {}, "error": None, "trace_id": "",
           "stages": tl.ordered_stages()}
    rep = attribution.aggregate_report([rec])
    assert rep["stage_ms"]["failover"]["count"] == 1
    assert rep["stage_ms"]["failover"]["p50"] == pytest.approx(250.0)


# ---------------------------------------------------------------------------
# cluster: cross-engine tier restore of an eagerly spilled in-flight chain
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def failover_cluster(ray_start_module):
    yield ray_start_module


def test_tier_restore_continuation_cross_engine(failover_cluster):
    """The full failover KV path: engine A eagerly spills a LIVE chain
    (prompt + generated pages), engine B admits the continuation via the
    CP index + object plane and resumes token-identically — the dead
    replica's decode progress is restored, not recomputed."""
    from ray_tpu.serve.llm import LLMEngine

    # 72 tokens (the whole remaining seq budget): with a warm in-process
    # jit cache the decode runs at ~ms/token, and a shorter run can
    # FINISH between wait-polls — a completed chain is no longer
    # in-flight and spill_inflight() would correctly find nothing
    want = _want_tokens(LONG, 72)
    cfg = _cfg(kv_tier_enabled=True)
    a = LLMEngine(cfg, rng_seed=0)
    a.start()
    b = None
    try:
        rid = a.submit(LONG, max_tokens=72, temperature=0.0)
        # wait until the chain covers a full page PAST the prompt, so the
        # spill includes generated-region KV (covered = 87 + gen-1 >= 96)
        assert _wait(lambda: len(
            (a.request_progress(rid) or {}).get("generated") or ()) >= 12,
            timeout=120.0)
        n = a.spill_inflight()
        assert n >= 6, f"expected prompt+generated pages spilled, got {n}"
        assert _wait(lambda: a.engine_stats()["spilled_pages"] >= 6)

        b = LLMEngine(cfg, rng_seed=0)
        b.start()
        k = 12
        rid_b = b.submit(LONG, resume_tokens=want[:k],
                         max_tokens=72 - k, temperature=0.0)
        out = b.result(rid_b, timeout=180.0)
        assert out["error"] is None, out
        assert out["tokens"] == want[k:], "resumed decode diverged"
        st = b.engine_stats()
        assert st["failover_resumed"] == 1
        assert st["restored_pages"] >= 6        # includes a generated page
        assert st["failover_restored_tokens"] >= 6 * 16
    finally:
        a.shutdown()
        if b is not None:
            b.shutdown()


# ---------------------------------------------------------------------------
# cluster: proxy splice — kill the serving replica mid-stream
# ---------------------------------------------------------------------------


def _read_sse(base, path, payload, rid, events, done):
    """Stream an SSE response, appending ("event", name) / ("data", obj)
    tuples to `events`; `done` carries the response headers or error."""
    try:
        req = urllib.request.Request(
            f"{base}{path}", data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json",
                     "X-Request-Id": rid})
        with urllib.request.urlopen(req, timeout=120.0) as r:
            hdr = dict(r.headers)
            for raw in r:
                line = raw.decode().strip()
                if line.startswith("event: "):
                    events.append(("event", line[len("event: "):]))
                elif line.startswith("data: "):
                    body = line[len("data: "):]
                    if body == "[DONE]":
                        break
                    events.append(("data", json.loads(body)))
        done.append(hdr)
    except Exception as e:  # noqa: BLE001 — the test asserts on this
        done.append(e)


def test_proxy_splices_stream_across_replica_death(failover_cluster):
    """End-to-end resume plumbing without an engine: a scripted streaming
    ingress on 2 replicas, the serving replica hard-killed mid-stream.
    The client must see every token exactly once, one `event: resumed`
    frame, the same X-Request-Id, and a normal finish."""
    from ray_tpu import serve
    from ray_tpu.serve.controller import get_or_create_controller

    serve.shutdown()
    n_tokens = 16

    @serve.deployment(num_replicas=2, health_check_period_s=0.2,
                      health_check_failure_threshold=3)
    class ScriptedStream:
        def __init__(self):
            self._uid = uuid.uuid4().hex[:8]

        def whoami(self):
            return self._uid

        def handle_http(self, path, method, payload):
            if isinstance(payload, dict) and payload.get("stream"):
                return self._gen(payload)
            return {"uid": self._uid}

        async def _gen(self, payload):
            import asyncio
            resume = payload.get("resume_tokens") or []
            start = len(resume)
            total = start + int(payload.get("max_tokens") or n_tokens)
            first = True
            for i in range(start, total):
                chunk = {"choices": [{"text": f"t{i};", "index": 0,
                                      "finish_reason": None}],
                         "token_ids": [i], "rep": self._uid}
                if first and payload.get("resume_count"):
                    chunk["resume_meta"] = {
                        "resumed": True, "restored_tokens": start,
                        "restore_bytes": 0, "restore_ms": 0.0,
                        "cached_tokens": 0}
                first = False
                yield chunk
                await asyncio.sleep(0.15)
            yield {"choices": [{"text": "", "index": 0,
                                "finish_reason": "stop"}],
                   "ray_tpu": {"ttft_s": 0.01}}

    serve.run(ScriptedStream.bind(), name="fo-scripted",
              route_prefix="/fo")
    proxy = serve.start_http_proxy(port=0)
    base = f"http://127.0.0.1:{proxy.port}"
    rid = "fostream0001"
    events: list = []
    finished: list = []
    try:
        t = threading.Thread(
            target=_read_sse, args=(base, "/fo/stream",
                                    {"stream": True,
                                     "max_tokens": n_tokens},
                                    rid, events, finished), daemon=True)
        t.start()
        # let a few chunks reach the client, then kill the serving replica
        assert _wait(lambda: sum(1 for k, v in list(events)
                                 if k == "data" and v.get("rep")) >= 3,
                     timeout=60.0)
        serving = next(v["rep"] for k, v in events
                       if k == "data" and v.get("rep"))
        ctl = get_or_create_controller()
        table = ray_tpu.get(ctl.get_routing_table.remote("fo-scripted"),
                            timeout=10.0)
        victim = None
        for entry in table.values():
            for h in entry[0]:
                uid = ray_tpu.get(
                    h.handle_request.remote("whoami", (), {}), timeout=10.0)
                if uid == serving:
                    victim = h
        assert victim is not None, f"serving replica {serving} not in table"
        ray_tpu.kill(victim)

        t.join(timeout=120.0)
        assert not t.is_alive(), "stream never finished after the kill"
        assert finished and not isinstance(finished[0], Exception), \
            f"stream failed: {finished}"
        assert finished[0].get("X-Request-Id") == rid  # stable across legs

        texts = [c["choices"][0]["text"] for k, c in events
                 if k == "data" and c.get("choices")]
        assert "".join(texts) == "".join(f"t{i};" for i in range(n_tokens)), \
            f"spliced stream has duplicated/missing tokens: {texts}"
        resumed = [v for k, v in events if k == "event" and v == "resumed"]
        assert len(resumed) == 1, f"expected one resumed frame: {events}"
        # the resumed leg ran on the OTHER replica
        reps = {c["rep"] for k, c in events if k == "data" and c.get("rep")}
        assert len(reps) == 2, f"resume stayed on the dead replica: {reps}"
        # the wire never leaks the internal journal keys
        assert all("token_ids" not in c and "resume_meta" not in c
                   for k, c in events if k == "data")
        assert proxy.stats.get("stream_resumes", 0) >= 1
    finally:
        serve.shutdown()
