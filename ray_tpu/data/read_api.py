"""Read API: the ray_tpu.data entry points (reference:
/root/reference/python/ray/data/read_api.py — read_parquet:796,
read_images:973, read_json:1268, read_csv:1441, range, from_items,
from_numpy, from_pandas, from_arrow)."""

from __future__ import annotations

from typing import Optional

import numpy as np

from ray_tpu.data.dataset import Dataset, MaterializedDataset
from ray_tpu.data.datasource import (
    BinaryDatasource,
    CSVDatasource,
    Datasource,
    ImageDatasource,
    ItemsDatasource,
    JSONDatasource,
    NumpyDatasource,
    ParquetDatasource,
    RangeDatasource,
    SQLDatasource,
    TextDatasource,
    TFRecordsDatasource,
    WebDatasetDatasource,
)
from ray_tpu.data.logical import InputData, Read


def _read(ds: Datasource, parallelism: int = -1) -> Dataset:
    return Dataset(Read(name="", datasource=ds, parallelism=parallelism))


def range(n: int, *, parallelism: int = -1) -> Dataset:  # noqa: A001
    return _read(RangeDatasource(n), parallelism)


def range_tensor(n: int, *, shape: tuple = (1,), parallelism: int = -1) -> Dataset:
    arr = np.arange(n, dtype=np.int64).reshape((n,) + (1,) * len(shape))
    arr = np.broadcast_to(arr, (n, *shape)).copy()
    return from_numpy(arr, column="data")


def read_datasource(ds: Datasource, *, parallelism: int = -1) -> Dataset:
    return _read(ds, parallelism)


def read_parquet(paths, *, columns: Optional[list] = None,
                 parallelism: int = -1) -> Dataset:
    return _read(ParquetDatasource(paths, columns=columns), parallelism)


def read_csv(paths, *, parallelism: int = -1) -> Dataset:
    return _read(CSVDatasource(paths), parallelism)


def read_json(paths, *, parallelism: int = -1) -> Dataset:
    return _read(JSONDatasource(paths), parallelism)


def read_text(paths, *, parallelism: int = -1) -> Dataset:
    return _read(TextDatasource(paths), parallelism)


def read_binary_files(paths, *, parallelism: int = -1) -> Dataset:
    return _read(BinaryDatasource(paths), parallelism)


def read_images(paths, *, size: Optional[tuple] = None, mode: str = "RGB",
                parallelism: int = -1) -> Dataset:
    return _read(ImageDatasource(paths, size=size, mode=mode), parallelism)


def read_webdataset(paths, *, parallelism: int = -1) -> Dataset:
    """Read WebDataset tar shards: tar members group into one row per
    sample key, columns keyed by extension (reference read_api.py:2101)."""
    return _read(WebDatasetDatasource(paths), parallelism)


def read_sql(sql: str, connection_factory, *,
             parallelism_column=None, parallelism: int = -1) -> Dataset:
    """Read a SQL query through a DB-API connection factory; with
    ``parallelism_column`` the query shards by hash-mod on that column
    (reference read_api read_sql)."""
    return _read(SQLDatasource(sql, connection_factory,
                               parallelism_column), parallelism)


def read_tfrecords(paths, *, parallelism: int = -1) -> Dataset:
    return _read(TFRecordsDatasource(paths), parallelism)


def from_items(items: list, *, parallelism: int = -1) -> Dataset:
    return _read(ItemsDatasource(items), parallelism)


def from_numpy(arr: np.ndarray, *, column: str = "data",
               parallelism: int = -1) -> Dataset:
    return _read(NumpyDatasource(arr, column), parallelism)


def from_pandas(df) -> Dataset:
    import pyarrow as pa
    return from_arrow(pa.Table.from_pandas(df, preserve_index=False))


def from_arrow(table) -> Dataset:
    import ray_tpu
    from ray_tpu.data.block import BlockAccessor
    ref = ray_tpu.put(table)
    meta = BlockAccessor.for_block(table).metadata()
    return MaterializedDataset(InputData(name="Input", bundles=[(ref, meta)]))


def from_huggingface(hf_dataset, *, parallelism: int = -1) -> Dataset:
    """Wrap a `datasets.Dataset` (reference read_api.py:3285)."""
    table = hf_dataset.data.table  # HF datasets are arrow-backed
    return from_arrow(table)
