"""Algorithm/AlgorithmConfig — the RL library's public API.

Mirrors the reference's new API stack surface (rllib/algorithms/algorithm.py,
algorithm_config.py): config.environment(...).env_runners(...).training(...)
.build() -> Algorithm; algo.train() returns a result dict per iteration.

Architecture is the reference's split re-shaped for TPU: host-side EnvRunner
actors collect experience (branchy, CPU-bound), a jitted Learner updates
params (dense, MXU-bound). Weight sync is an object-store broadcast.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any

import numpy as np

from ray_tpu.rllib.env import make_env
from ray_tpu.rllib.env_runner import EnvRunnerGroup
from ray_tpu.rllib.models import RLModule


@dataclass
class AlgorithmConfig:
    algo_cls: type | None = None
    env_spec: Any = "CartPole"
    num_env_runners: int = 2
    rollout_steps: int = 256          # per runner per iteration
    hidden: tuple = (64, 64)
    lr: float = 3e-4
    gamma: float = 0.99
    seed: int = 0
    train_kwargs: dict = field(default_factory=dict)
    # connector pipeline FACTORIES (rllib/connectors/ analog): callables
    # returning a Connector/ConnectorPipeline; one instance per runner
    env_to_module_connector: Any = None
    learner_connector: Any = None

    # builder-style setters (ref: algorithm_config.py fluent API)
    def environment(self, env) -> "AlgorithmConfig":
        self.env_spec = env
        return self

    def env_runners(self, num_env_runners: int,
                    rollout_steps: int | None = None) -> "AlgorithmConfig":
        self.num_env_runners = num_env_runners
        if rollout_steps is not None:
            self.rollout_steps = rollout_steps
        return self

    def connectors(self, *, env_to_module=None,
                   learner=None) -> "AlgorithmConfig":
        if env_to_module is not None:
            self.env_to_module_connector = env_to_module
        if learner is not None:
            self.learner_connector = learner
        return self

    def training(self, **kw) -> "AlgorithmConfig":
        for k in ("lr", "gamma", "seed"):
            if k in kw:
                setattr(self, k, kw.pop(k))
        if "hidden" in kw:
            self.hidden = tuple(kw.pop("hidden"))
        self.train_kwargs.update(kw)
        return self

    def build(self) -> "Algorithm":
        if self.algo_cls is None:
            raise ValueError("config is not bound to an algorithm class")
        return self.algo_cls(self)


class Algorithm:
    """Base: owns the module, the runner group, and the iteration loop."""

    def __init__(self, config: AlgorithmConfig):
        import jax

        self.config = config
        probe = make_env(config.env_spec)
        # the driver keeps its OWN env-to-module pipeline instance: it
        # sizes the module from the FILTERED observation (shape-changing
        # connectors like FrameStack widen it), filters evaluation
        # observations identically to training, and merges/broadcasts the
        # per-runner filter states each iteration
        self._env_to_module = (config.env_to_module_connector()
                               if config.env_to_module_connector else None)
        probe_obs = np.asarray(probe.reset(seed=0), np.float32)
        if self._env_to_module is not None:
            probe_obs = np.asarray(self._env_to_module(probe_obs))
        self.module = RLModule(int(probe_obs.shape[-1]), probe.num_actions,
                               hidden=config.hidden)
        self.params = self.module.init(jax.random.PRNGKey(config.seed))
        self.runners = EnvRunnerGroup(config.env_spec, self.module,
                                      env_to_module_fn=config.env_to_module_connector,
                                      learner_connector_fn=config.learner_connector,
                                      num_runners=config.num_env_runners,
                                      seed=config.seed)
        self._iter = 0
        self._timesteps = 0
        self.setup()

    # subclass hooks ----------------------------------------------------
    def setup(self) -> None:
        pass

    def training_step(self) -> dict:
        raise NotImplementedError

    # public ------------------------------------------------------------
    def train(self) -> dict:
        t0 = time.monotonic()
        metrics = self.training_step()
        self._iter += 1
        if self._env_to_module is not None:
            # merge per-runner stateful-connector states (Welford combine
            # for the mean/std filter) and broadcast back, so every runner
            # and the driver's eval pipeline normalize identically
            # (reference: connector state synced through the learner group)
            states = [st for st in self.runners.connector_states()
                      if st is not None]
            if states:
                merged = self._env_to_module.merge_states(states)
                self.runners.set_connector_states(merged)
                self._env_to_module.set_state(merged)
        stats = self.runners.episode_stats()
        rets = stats["episode_returns"]
        return {
            "training_iteration": self._iter,
            "num_env_steps_sampled_lifetime": self._timesteps,
            "episode_return_mean": float(np.mean(rets)) if rets else None,
            "episodes_this_iter": len(rets),
            "time_this_iter_s": time.monotonic() - t0,
            **metrics,
        }

    def compute_single_action(self, obs, explore: bool = False) -> int:
        if self._env_to_module is not None:
            # same preprocessing the policy trained on, without polluting
            # the running statistics from evaluation streams
            frozen = getattr(self._env_to_module, "frozen", None)
            obs = frozen(obs) if frozen is not None \
                else self._env_to_module(obs)
        logits = np.asarray(
            self.module.forward_inference(self.params, np.asarray(obs)[None]))[0]
        if explore:
            z = logits - logits.max()
            p = np.exp(z) / np.exp(z).sum()
            return int(np.random.default_rng().choice(len(p), p=p))
        return int(logits.argmax())

    def evaluate(self, num_episodes: int = 5, max_steps: int = 1000) -> dict:
        env = make_env(self.config.env_spec)
        rets = []
        for ep in range(num_episodes):
            obs = env.reset(seed=1000 + ep)
            total = 0.0
            for _ in range(max_steps):
                obs, r, term, trunc = env.step(
                    self.compute_single_action(obs))
                total += r
                if term or trunc:
                    break
            rets.append(total)
        return {"episode_return_mean": float(np.mean(rets))}

    def stop(self) -> None:
        self.runners.stop()

    # tune integration: Algorithm is a trainable ------------------------
    @classmethod
    def as_trainable(cls, config: AlgorithmConfig, stop_iters: int = 10):
        """Returns fn(cfg_overrides, report) usable with ray_tpu.tune."""
        def trainable(overrides: dict, report=None):
            import dataclasses
            cfg = dataclasses.replace(config, algo_cls=cls)
            for k, v in (overrides or {}).items():
                if hasattr(cfg, k):
                    setattr(cfg, k, v)
                else:
                    cfg.train_kwargs[k] = v
            algo = cfg.build()
            try:
                for _ in range(stop_iters):
                    result = algo.train()
                    if report is not None:
                        report(result)
                return result
            finally:
                algo.stop()
        return trainable
