"""Speculative decoding: n-gram draft + batched verify-k.

Pins the PR's acceptance invariants:
- with spec on and greedy sampling, token sequences are BIT-IDENTICAL to
  spec-off for the same prompts (single, repetitive, and concurrent);
- repetitive workloads actually accept drafts (>1 emitted token per
  verify round on average);
- non-greedy slots never draft (the identity guarantee is greedy-only);
- one verify program per bucket width (no compile churn mid-traffic);
- disagg: prefill tier bypasses spec by decision, decode tier keeps it;
- max_tokens is an exact cap even when a whole draft run is accepted.
"""

import pytest

from ray_tpu.serve.llm.spec_decode import NGramProposer, accept_length


def _tiny_cfg(**kw):
    from ray_tpu.models import llama
    from ray_tpu.serve.llm import LLMConfig

    d = dict(model_config=llama.llama_tiny(vocab_size=512),
             max_batch_size=4, page_size=16, num_pages=64,
             max_prompt_len=64, max_seq_len=128, max_tokens=8)
    d.update(kw)
    return LLMConfig(**d)


# ---------------------------------------------------------------------------
# proposer unit tests (pure host-side)
# ---------------------------------------------------------------------------


def test_proposer_drafts_continuation_of_repeated_ngram():
    p = NGramProposer(ngram_max=3, draft_len=4)
    # suffix [1] recurs at position 1; its continuation is [2, 1]
    assert p.propose([1, 2, 1]) == [2, 1]


def test_proposer_no_recurrence_no_draft():
    p = NGramProposer(ngram_max=3, draft_len=4)
    assert p.propose([1, 2, 3, 4, 5]) == []
    assert p.propose([]) == []
    assert p.propose([7]) == []  # too short to have a continuation


def test_proposer_prefers_longest_ngram_match():
    p = NGramProposer(ngram_max=3, draft_len=4)
    # suffix 3-gram (2,3,4) occurred at positions 1..3 -> continues with 9;
    # the 1-gram (4) alone most recently continued with 2 (position 6).
    # Longest match must win: the draft starts from the 3-gram's
    # continuation, not the more recent 1-gram's.
    ctx = [1, 2, 3, 4, 9, 8, 4, 2, 3, 4]
    assert p.propose(ctx) == [9, 8, 4, 2]


def test_proposer_draft_len_caps_output():
    p = NGramProposer(ngram_max=2, draft_len=2)
    assert p.propose([5, 6, 7, 8, 5, 6]) == [7, 8]


def test_proposer_incremental_index_across_calls():
    p = NGramProposer(ngram_max=2, draft_len=3)
    ctx = [4, 5, 6]
    assert p.propose(ctx) == []
    # grow the context the way a generating slot does; earlier positions
    # must stay indexed (and never be re-scanned — _indexed is monotone)
    ctx += [4, 5]
    assert p.propose(ctx) == [6, 4, 5]
    assert p._indexed == len(ctx) - 1


def test_accept_length():
    assert accept_length([1, 2, 3], [1, 2, 3, 9]) == 3   # full accept
    assert accept_length([1, 2, 3], [1, 7, 3, 9]) == 1   # mismatch stops
    assert accept_length([1, 2], [5, 1, 2]) == 0         # first rejected
    assert accept_length([], [5]) == 0                   # no draft
    assert accept_length([1, 2, 3], [1, 2]) == 2         # short verify


# ---------------------------------------------------------------------------
# engine: greedy identity + acceptance accounting
# ---------------------------------------------------------------------------


REPETITIVE = "abc abc abc abc abc"  # byte tokens; suffix n-grams recur


def _run_engine(cfg, prompts, max_tokens, temperature=0.0):
    from ray_tpu.serve.llm import LLMEngine

    eng = LLMEngine(cfg, rng_seed=0)
    eng.start()
    try:
        rids = [eng.submit(p, max_tokens=max_tokens,
                           temperature=temperature) for p in prompts]
        outs = [eng.result(r, timeout=120.0) for r in rids]
        stats = eng.engine_stats()
    finally:
        eng.shutdown()
    return outs, stats


def test_spec_greedy_tokens_identical_to_baseline():
    prompts = [REPETITIVE, "the cat sat on the mat the cat",
               "no repeats here 123"]
    base, _ = _run_engine(_tiny_cfg(max_tokens=32), prompts, 32)
    spec, stats = _run_engine(
        _tiny_cfg(max_tokens=32, spec_decode_enabled=True), prompts, 32)
    assert all(o["error"] is None for o in base + spec)
    assert [o["tokens"] for o in spec] == [o["tokens"] for o in base]
    # the repetitive prompts must actually exercise the verify path
    assert stats["spec_rounds"] > 0
    assert stats["spec_drafted_tokens"] > 0


def test_spec_accepts_more_than_one_token_per_round_on_repetitive():
    """The whole point: on a repetitive workload a verify round must emit
    more than its one guaranteed token on average (tokens emitted per
    round = accepted/rounds + 1)."""
    _, stats = _run_engine(
        _tiny_cfg(max_tokens=48, spec_decode_enabled=True),
        [REPETITIVE], 48)
    assert stats["spec_rounds"] > 0
    emitted_per_round = stats["spec_accepted_tokens"] / stats[
        "spec_rounds"] + 1.0
    assert emitted_per_round > 1.0
    assert stats["spec_accepted_tokens"] > 0


def test_spec_concurrent_batch_identity():
    """Mixed batch: drafting and non-drafting slots decode concurrently
    (verify + fallback decode in the same loop iteration); every slot's
    greedy output must match the spec-off engine."""
    prompts = ["abc abc abc abc", "the cat sat on the mat the cat sat",
               "xyzzy", "repeat repeat repeat repeat", "one two one two"]
    base, _ = _run_engine(_tiny_cfg(max_tokens=24), prompts, 24)
    spec, stats = _run_engine(
        _tiny_cfg(max_tokens=24, spec_decode_enabled=True), prompts, 24)
    assert [o["tokens"] for o in spec] == [o["tokens"] for o in base]
    assert stats["spec_rounds"] > 0


def test_spec_never_drafts_non_greedy_slots():
    _, stats = _run_engine(
        _tiny_cfg(max_tokens=16, spec_decode_enabled=True),
        [REPETITIVE, "abc abc abc"], 16, temperature=0.8)
    assert stats["spec_rounds"] == 0
    assert stats["spec_drafted_tokens"] == 0


def test_spec_respects_max_tokens_exactly():
    """A fully accepted draft run must not overshoot max_tokens: the
    proposer's draft is capped at remaining-1, so round output (accepted +
    bonus) lands exactly on the cap."""
    outs, _ = _run_engine(
        _tiny_cfg(max_tokens=17, spec_decode_enabled=True),
        [REPETITIVE], 17)
    assert outs[0]["error"] is None
    assert outs[0]["num_generated_tokens"] <= 17


def test_spec_stats_keys_and_off_by_default():
    from ray_tpu.serve.llm import LLMEngine

    off = LLMEngine(_tiny_cfg(), rng_seed=0)
    assert not off._spec_on  # default OFF: the flag is opt-in
    st = off.engine_stats()
    # counters exist (dashboards can always subscribe) but the derived
    # rate only appears when the feature is on
    for key in ("spec_rounds", "spec_drafted_tokens",
                "spec_accepted_tokens", "decode_block_effective",
                "pending_pipeline_depth"):
        assert key in st
    assert "spec_accept_rate" not in st

    on = LLMEngine(_tiny_cfg(spec_decode_enabled=True), rng_seed=0)
    assert on.engine_stats()["spec_accept_rate"] == 0.0


def test_verify_program_compiles_once_per_width():
    """The verify-k program must stay ONE compiled program per bucket
    width (k and the draft matrix shape are static): compile-cache growth
    here would mean mid-traffic stalls."""
    from ray_tpu.serve.llm import LLMEngine

    cfg = _tiny_cfg(max_batch_size=4, spec_decode_enabled=True,
                    warmup_compile=True, max_tokens=24)
    eng = LLMEngine(cfg, rng_seed=0)
    eng.start()
    try:
        assert eng._verify._cache_size() == 1  # warmup compiled it
        rids = [eng.submit(REPETITIVE, max_tokens=24, temperature=0.0)
                for _ in range(3)]
        outs = [eng.result(r, timeout=120.0) for r in rids]
        assert all(o["error"] is None for o in outs)
        assert eng.engine_stats()["spec_rounds"] > 0
        assert eng._verify._cache_size() == 1  # no recompilation
    finally:
        eng.shutdown()


# ---------------------------------------------------------------------------
# disagg: prefill bypass by decision, decode support
# ---------------------------------------------------------------------------


def test_disagg_prefill_bypasses_spec_decode_side_keeps_it():
    from ray_tpu.serve.llm import disagg

    cfg = _tiny_cfg(spec_decode_enabled=True)
    assert not disagg._disable_spec_decode(cfg).spec_decode_enabled
    off = _tiny_cfg()
    assert disagg._disable_spec_decode(off) is off  # idempotent

    pre = disagg.PrefillServer(cfg)
    assert not pre.engine._spec_on
    dec = disagg.DecodeEngine(cfg, rng_seed=0)
    assert dec._spec_on  # decode tier keeps the caller's setting


def test_disagg_decode_spec_identity():
    """A handed-off request decoded with spec on must emit the same greedy
    tokens as a spec-off decode engine: the KV-blob admission satisfies
    the spec path's length invariant like a local prefill does."""
    import jax

    from ray_tpu.models import llama
    from ray_tpu.serve.llm.disagg import DecodeEngine, prefill_only
    from ray_tpu.serve.llm.engine import LLMEngine

    cfg = _tiny_cfg(max_tokens=24)
    mc = cfg.llama()
    params = llama.init_params(jax.random.PRNGKey(3), mc)
    prompt = [7, 3, 9, 1] * 5  # repetitive: drafts will fire

    pre = LLMEngine(cfg, params=params)
    dec_off = DecodeEngine(cfg, params=params)
    dec_off.start()
    try:
        state = prefill_only(pre, prompt, temperature=0.0)
        rid = dec_off.submit_prefilled(state, max_tokens=24)
        want = dec_off.result(rid, timeout=120.0)["tokens"]
    finally:
        dec_off.shutdown()

    spec_cfg = _tiny_cfg(max_tokens=24, spec_decode_enabled=True)
    dec_on = DecodeEngine(spec_cfg, params=params)
    dec_on.start()
    try:
        state = prefill_only(pre, prompt, temperature=0.0)
        rid = dec_on.submit_prefilled(state, max_tokens=24)
        got = dec_on.result(rid, timeout=120.0)
        assert got["error"] is None
        assert got["tokens"] == want
        assert dec_on.engine_stats()["spec_rounds"] > 0
    finally:
        dec_on.shutdown()
