"""Power-of-two-choices request router with retries and ejection.

TPU-native analog of the reference's router
(/root/reference/python/ray/serve/_private/router.py — AsyncioRouter:457,
assign_request:838; request_router/pow_2_router.py): pick two random
replicas, probe cached queue lengths, route to the shorter queue. Queue
lengths are refreshed in the background; routing table updates come from the
controller via versioned polls (the reference uses long-poll, long_poll.py).

Robustness layer (Dean & Barroso, "The Tail at Scale", CACM 2013):

- `call()` retries replica-fault failures (dead/unreachable replica — never
  user exceptions) on a different replica, gated by a Finagle-style
  RetryBudget so retries stay bounded at ~10% of traffic instead of
  storming a degraded cluster.
- Consecutive failures eject a replica from routing (circuit breaker);
  after a cooldown it must pass a health probe before taking traffic again.
- Every wait is bounded by the ambient request deadline
  (core/deadline.py); expired requests are refused before a replica is
  picked.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Optional

import ray_tpu
from ray_tpu.core import deadline as request_deadline
from ray_tpu.util import metrics as _metrics
from ray_tpu.exceptions import (ActorDiedError, ActorUnavailableError,
                                DeadlineExceededError, GetTimeoutError,
                                NodeDiedError, ObjectLostError, TaskError,
                                WorkerCrashedError)
from ray_tpu.serve.config import RouterConfig

# fault classes meaning "the REPLICA is broken, the request may be fine":
# safe to retry elsewhere. User exceptions and deadline/timeout errors are
# excluded — retrying those wastes budget on work that will fail again.
# ObjectLostError counts: the reply object died with the replica's node, so
# the outcome is unusable and re-execution elsewhere is the recovery.
_REPLICA_FAULTS = (ActorDiedError, ActorUnavailableError, WorkerCrashedError,
                   NodeDiedError, ObjectLostError)

# Built-in router metrics (ISSUE 4): flushed to the CP time-series store by
# the hosting process's MetricsFlusher.
_RETRY_SPEND = _metrics.Counter(
    "ray_tpu_serve_router_retries_total",
    "retry-budget spend: requests retried on another replica",
    tag_keys=("deployment",))
_EJECTION_COUNTER = _metrics.Counter(
    "ray_tpu_serve_router_ejections_total",
    "replicas ejected from routing by the circuit breaker",
    tag_keys=("deployment",))


def is_replica_fault(exc: BaseException) -> bool:
    if isinstance(exc, _REPLICA_FAULTS):
        return True
    if isinstance(exc, TaskError):
        return isinstance(exc.cause, _REPLICA_FAULTS)
    return False


class RetryBudget:
    """Token bucket bounding retries to a fraction of request volume
    (Finagle's RetryBudget): each request deposits `ratio` tokens, each
    retry withdraws 1.0, balance capped at `cap`. Thread-safe."""

    def __init__(self, ratio: float = 0.1, cap: float = 10.0):
        self._ratio = ratio
        self._cap = cap
        self._balance = cap  # start full: a cold router may retry
        self._lock = threading.Lock()

    def deposit(self) -> None:
        with self._lock:
            self._balance = min(self._cap, self._balance + self._ratio)

    def withdraw(self) -> bool:
        with self._lock:
            if self._balance >= 1.0:
                self._balance -= 1.0
                return True
            return False

    def balance(self) -> float:
        with self._lock:
            return self._balance


class ReplicaSet:
    """Cached view of one deployment's replicas + queue lengths + per-replica
    circuit-breaker state (keyed by actor id, so state survives routing-table
    refreshes that rebuild the handle list)."""

    def __init__(self, config: Optional[RouterConfig] = None):
        self.config = config or RouterConfig()
        self.replicas: list = []           # actor handles
        self.version: int = -1
        self._qlen: dict[int, tuple[float, int]] = {}  # idx -> (ts, len)
        # circuit breaker, keyed by actor id hex
        self._fails: dict[str, int] = {}          # consecutive failures
        self._ejected: dict[str, float] = {}      # key -> ejected-at ts
        self._cb_lock = threading.Lock()
        self.ejections = 0
        self.readmissions = 0

    @staticmethod
    def _key(replica) -> str:
        aid = getattr(replica, "_actor_id", None)
        return aid.hex() if hasattr(aid, "hex") else str(id(replica))

    def update(self, replicas: list, version: int):
        self.replicas = replicas
        self.version = version
        self._qlen = {}
        live = {self._key(r) for r in replicas}
        with self._cb_lock:
            # controller replaced dead replicas: drop breaker state for
            # handles that no longer route
            self._fails = {k: v for k, v in self._fails.items() if k in live}
            self._ejected = {k: v for k, v in self._ejected.items()
                             if k in live}

    # ---- circuit breaker ------------------------------------------------
    def record_success(self, replica) -> None:
        with self._cb_lock:
            self._fails.pop(self._key(replica), None)

    def record_failure(self, replica) -> bool:
        """Count a replica-fault failure; returns True if this ejected the
        replica from routing."""
        key = self._key(replica)
        with self._cb_lock:
            n = self._fails.get(key, 0) + 1
            self._fails[key] = n
            if n >= self.config.ejection_threshold \
                    and key not in self._ejected:
                self._ejected[key] = time.monotonic()
                self.ejections += 1
                return True
        return False

    def _routable(self) -> list:
        """Replicas not currently ejected; cooled-down ejectees are health
        probed and readmitted when they pass (re-armed when they don't)."""
        now = time.monotonic()
        out = []
        for r in self.replicas:
            key = self._key(r)
            with self._cb_lock:
                ejected_at = self._ejected.get(key)
            if ejected_at is None:
                out.append(r)
                continue
            if now - ejected_at < self.config.ejection_cooldown_s:
                continue
            # cooldown over: one synchronous health probe decides (bounded
            # by the ambient deadline — readmission must not burn the
            # caller's remaining budget)
            try:
                ray_tpu.get(r.check_health.remote(),
                            timeout=request_deadline.bound(
                                self.config.health_probe_timeout_s))
                ok = True
            except Exception:  # noqa: BLE001 — still broken
                ok = False
            with self._cb_lock:
                if ok:
                    self._ejected.pop(key, None)
                    self._fails.pop(key, None)
                    self.readmissions += 1
                else:
                    self._ejected[key] = time.monotonic()  # re-arm cooldown
            if ok:
                out.append(r)
        return out

    # ---- selection ------------------------------------------------------
    _QLEN_DEAD = 1 << 30  # probe-failed sentinel: replica looks infinitely busy

    def _probe(self, idx: int) -> int:
        now = time.monotonic()
        cached = self._qlen.get(idx)
        if cached and now - cached[0] < self.config.queue_len_staleness_s:
            return cached[1]
        try:
            # bounded by the ambient deadline too: probing a dead replica
            # must not burn the caller's remaining budget
            qlen = ray_tpu.get(self.replicas[idx].get_queue_len.remote(),
                               timeout=request_deadline.bound(
                                   self.config.queue_probe_timeout_s))
        except Exception:  # noqa: BLE001 - dead replica looks busy
            qlen = self._QLEN_DEAD
        self._qlen[idx] = (now, qlen)
        return qlen

    def choose(self, model_id: str = "") -> Optional[object]:
        candidates = self._routable()
        n = len(candidates)
        if n == 0:
            return None
        if model_id:
            # multiplexed request: rendezvous-hash affinity keeps the model's
            # per-replica cache hot (serve/multiplex.py)
            from ray_tpu.serve.multiplex import rendezvous_pick
            return candidates[rendezvous_pick(candidates, model_id)]
        if n == 1:
            return candidates[0]
        i, j = random.sample(range(n), 2)
        # probe cache is indexed into self.replicas (stable across choose
        # calls within one table version)
        pi = self.replicas.index(candidates[i])
        pj = self.replicas.index(candidates[j])
        qi, qj = self._probe(pi), self._probe(pj)
        if min(qi, qj) < self._QLEN_DEAD:
            return candidates[i if qi <= qj else j]
        # both sampled candidates look dead (a node just died): fall back
        # to a full scan — any live replica beats two dead ones
        best, best_q = candidates[i], qi
        for c in candidates:
            q = self._probe(self.replicas.index(c))
            if q < best_q:
                best, best_q = c, q
        return best


class Router:
    """Routes requests for any deployment in one application.

    Config updates arrive by LONG-POLL push from the controller (reference
    long_poll.py): a background thread hangs on poll_routing_table and
    applies changes the moment versions bump — the request path reads only
    the local cache, no controller RPC per request."""

    def __init__(self, controller, app_name: str,
                 config: Optional[RouterConfig] = None):
        self._controller = controller
        self._app = app_name
        self.config = config or RouterConfig()
        self._sets: dict[str, ReplicaSet] = {}
        self._lock = threading.Lock()
        self._stopped = threading.Event()
        self._budget = RetryBudget(self.config.retry_budget_ratio,
                                   self.config.retry_budget_cap)
        self._stats_lock = threading.Lock()
        self.stats = {"requests": 0, "retries": 0, "retries_denied": 0,
                      "deadline_exceeded": 0}
        # DEGRADED mode (tentpole b): the controller (or the CP under it)
        # is unreachable, so the router keeps serving from its cached
        # routing tables instead of failing requests. Flag + since-ts are
        # surfaced via stats_snapshot for the proxy /-/stats and tests.
        self._degraded = False
        self._degraded_since: Optional[float] = None
        self._poll_thread = threading.Thread(
            target=self._long_poll_loop, name=f"router-poll-{app_name}",
            daemon=True)
        self._poll_thread.start()

    def _bump(self, key: str, n: int = 1) -> None:
        with self._stats_lock:
            self.stats[key] += n

    def _set_degraded(self, degraded: bool) -> None:
        with self._stats_lock:
            if degraded and not self._degraded:
                self._degraded = True
                self._degraded_since = time.monotonic()
            elif not degraded and self._degraded:
                self._degraded = False
                self._degraded_since = None

    def stats_snapshot(self) -> dict:
        with self._stats_lock:
            out = dict(self.stats)
            out["degraded"] = self._degraded
            out["degraded_for_s"] = (
                time.monotonic() - self._degraded_since
                if self._degraded_since is not None else 0.0)
        out["retry_budget"] = self._budget.balance()
        with self._lock:
            out["ejections"] = sum(rs.ejections for rs in self._sets.values())
            out["readmissions"] = sum(rs.readmissions
                                      for rs in self._sets.values())
        return out

    def _apply_table(self, table: dict) -> None:
        with self._lock:
            for dep, (replicas, version) in table.items():
                cur = self._sets.setdefault(dep, ReplicaSet(self.config))
                if version != cur.version:
                    cur.update(replicas, version)
            # the table is the app's FULL routing state: deployments that
            # were deleted must drop out of the cache, or the long-poll
            # version handshake never converges
            for dep in [d for d, rs in self._sets.items()
                        if d not in table and rs.version >= 0]:
                del self._sets[dep]

    def _long_poll_loop(self) -> None:
        while not self._stopped.is_set():
            with self._lock:
                known = {d: rs.version for d, rs in self._sets.items()}
            try:
                table = ray_tpu.get(
                    self._controller.poll_routing_table.remote(
                        self._app, known, 30.0), timeout=40.0)
            except Exception:  # noqa: BLE001 - controller/CP briefly away:
                # DEGRADED — keep routing from the cached tables; requests
                # must not fail just because the control plane blinked
                self._set_degraded(True)
                time.sleep(0.5)
                continue
            self._set_degraded(False)
            if table:
                self._apply_table(table)

    def stop(self) -> None:
        self._stopped.set()

    def _maybe_refresh(self, deployment: str, force: bool = False):
        with self._lock:
            rs = self._sets.setdefault(deployment, ReplicaSet(self.config))
            if rs.replicas and not force:
                return rs
        # cold start / forced: one synchronous fetch. During a controller /
        # CP outage this fails — serve from whatever table we already have
        # (degraded) rather than failing the request.
        try:
            table = ray_tpu.get(self._controller.get_routing_table.remote(
                self._app), timeout=10.0)
        except Exception:  # noqa: BLE001 — degraded: cached table stands
            self._set_degraded(True)
        else:
            self._set_degraded(False)
            self._apply_table(table)
        with self._lock:
            return self._sets.setdefault(deployment, ReplicaSet(self.config))

    def _pick(self, deployment: str, multiplexed_model_id: str,
              timeout_s: float):
        """Block until a routable replica exists (bounded by `timeout_s`
        AND the ambient deadline). Returns (replica_set, replica)."""
        wait_until = time.monotonic() \
            + request_deadline.bound(timeout_s)
        while True:
            request_deadline.raise_if_expired("request")
            rs = self._maybe_refresh(deployment)
            replica = rs.choose(multiplexed_model_id)
            if replica is not None:
                return rs, replica
            if time.monotonic() > wait_until:
                raise TimeoutError(
                    f"no replicas available for deployment "
                    f"{deployment!r} after {timeout_s}s")
            self._maybe_refresh(deployment, force=True)
            time.sleep(0.1)

    def assign(self, deployment: str, method: str, args: tuple,
               kwargs: dict, *, streaming: bool = False,
               timeout_s: float = 30.0, multiplexed_model_id: str = ""):
        """Pick a replica and submit; returns the reply ObjectRef.

        No retries — the caller owns the ref (DeploymentHandle path).
        `call()` is the retrying variant for request/response traffic."""
        rs, replica = self._pick(deployment, multiplexed_model_id, timeout_s)
        if streaming:
            # streaming-generator call: returns an ObjectRefGenerator
            # whose items land as the replica yields them
            return replica.handle_request_streaming.options(
                num_returns="streaming").remote(method, args, kwargs)
        return replica.handle_request.remote(method, args, kwargs)

    def call(self, deployment: str, method: str, args: tuple, kwargs: dict,
             *, timeout_s: Optional[float] = None,
             multiplexed_model_id: str = "") -> tuple:
        """Submit and WAIT for the reply, absorbing replica faults: a
        dead/unreachable replica is recorded against the circuit breaker
        and the request is retried on another replica, gated by the retry
        budget and `max_retries_per_request`. Waits are bounded by the
        ambient deadline. Returns (result, attempts_used).

        Raises the final error when retries are exhausted/denied; user
        exceptions and deadline expiry propagate immediately (retrying
        them would fail again and burn budget)."""
        self._bump("requests")
        self._budget.deposit()
        attempts = 0
        no_replica_timeout = (timeout_s if timeout_s is not None
                              else self.config.no_replica_timeout_s)
        while True:
            try:
                request_deadline.raise_if_expired("request")
            except DeadlineExceededError:
                self._bump("deadline_exceeded")
                raise
            rs, replica = self._pick(deployment, multiplexed_model_id,
                                     no_replica_timeout)
            ref = replica.handle_request.remote(method, args, kwargs)
            attempts += 1
            try:
                result = ray_tpu.get(
                    ref, timeout=request_deadline.bound(timeout_s))
                rs.record_success(replica)
                return result, attempts
            except (GetTimeoutError, DeadlineExceededError):
                # the replica may still be healthy — just slow/over-deadline;
                # don't charge the breaker, don't retry (no budget left in
                # the deadline anyway)
                self._bump("deadline_exceeded")
                try:
                    ray_tpu.cancel(ref)  # stop computing an answer nobody reads
                except Exception:  # noqa: BLE001 — best-effort
                    pass
                raise
            except Exception as e:  # noqa: BLE001 — classify below
                if isinstance(e, TaskError) and isinstance(
                        e.cause, DeadlineExceededError):
                    # replica shed it at dequeue: too late to retry
                    self._bump("deadline_exceeded")
                    raise
                if not is_replica_fault(e):
                    rs.record_success(replica)  # replica fine; request isn't
                    raise
                if rs.record_failure(replica):
                    _EJECTION_COUNTER.inc(tags={"deployment": deployment})
                if attempts > self.config.max_retries_per_request:
                    raise
                if not self._budget.withdraw():
                    self._bump("retries_denied")
                    raise
                self._bump("retries")
                _RETRY_SPEND.inc(tags={"deployment": deployment})
                self._maybe_refresh(deployment, force=True)
