"""Mixtral-family MoE transformer, TPU-first (expert-parallel native).

BASELINE config 5 is "Mixtral 8x7B MoE expert-parallel across Ray actors
(v5p-128)". The reference has no in-tree MoE execution (SURVEY.md §2.3 row
EP — it would run one expert per NCCL-grouped actor); here experts are a mesh
axis: expert weights shard over the "expert" axis and token buckets move with
`lax.all_to_all` over ICI (ray_tpu.parallel.expert).

Architecture = Llama block with the dense MLP swapped for a top-k router +
SwiGLU experts (Mixtral): GQA attention, RoPE, RMSNorm, stacked-layer scan.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models import llama as _llama
from ray_tpu.parallel.expert import moe_layer, top_k_gating


@dataclasses.dataclass(frozen=True)
class MixtralConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_dim: int = 14336
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    max_seq_len: int = 8192
    rope_theta: float = 1e6
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    attn_impl: str = "dense"
    remat: bool = True

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    def as_llama(self) -> _llama.LlamaConfig:
        """Attention-side view (reuses llama attention/norm/rope code)."""
        return _llama.LlamaConfig(
            vocab_size=self.vocab_size, dim=self.dim, n_layers=self.n_layers,
            n_heads=self.n_heads, n_kv_heads=self.n_kv_heads,
            ffn_dim=self.ffn_dim, max_seq_len=self.max_seq_len,
            rope_theta=self.rope_theta, norm_eps=self.norm_eps,
            dtype=self.dtype, attn_impl=self.attn_impl, remat=self.remat)


def mixtral_8x7b(**kw) -> MixtralConfig:
    return MixtralConfig(**kw)


def mixtral_tiny(**kw) -> MixtralConfig:
    """Test config: runs on the 8-device CPU mesh in seconds."""
    d = dict(vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
             ffn_dim=128, num_experts=4, top_k=2, max_seq_len=128,
             dtype=jnp.float32, remat=False)
    d.update(kw)
    return MixtralConfig(**d)


def num_params(cfg: MixtralConfig) -> int:
    attn = cfg.dim * (cfg.n_heads + 2 * cfg.n_kv_heads) * cfg.head_dim \
        + cfg.n_heads * cfg.head_dim * cfg.dim
    expert = 3 * cfg.dim * cfg.ffn_dim
    per_layer = attn + cfg.num_experts * expert + cfg.dim * cfg.num_experts \
        + 2 * cfg.dim
    return cfg.vocab_size * cfg.dim * 2 + cfg.dim + cfg.n_layers * per_layer


def init_params(rng, cfg: MixtralConfig):
    k_embed, k_layers, k_out = jax.random.split(rng, 3)
    hd = cfg.head_dim

    def dense(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                * (1.0 / np.sqrt(fan_in))).astype(cfg.dtype)

    def layer(key):
        ks = jax.random.split(key, 8)
        e = cfg.num_experts
        return {
            "attn": {
                "wq": dense(ks[0], (cfg.dim, cfg.n_heads, hd), cfg.dim),
                "wk": dense(ks[1], (cfg.dim, cfg.n_kv_heads, hd), cfg.dim),
                "wv": dense(ks[2], (cfg.dim, cfg.n_kv_heads, hd), cfg.dim),
                "wo": dense(ks[3], (cfg.n_heads, hd, cfg.dim), cfg.dim),
            },
            "gate": dense(ks[4], (cfg.dim, e), cfg.dim).astype(jnp.float32),
            "experts": {
                "w_gate": dense(ks[5], (e, cfg.dim, cfg.ffn_dim), cfg.dim),
                "w_up": dense(ks[6], (e, cfg.dim, cfg.ffn_dim), cfg.dim),
                "w_down": dense(ks[7], (e, cfg.ffn_dim, cfg.dim), cfg.ffn_dim),
            },
            "attn_norm": jnp.ones((cfg.dim,), jnp.float32),
            "mlp_norm": jnp.ones((cfg.dim,), jnp.float32),
        }

    layers = jax.vmap(layer)(jax.random.split(k_layers, cfg.n_layers))
    return {
        "embed": dense(k_embed, (cfg.vocab_size, cfg.dim), cfg.dim),
        "layers": layers,
        "final_norm": jnp.ones((cfg.dim,), jnp.float32),
        "lm_head": dense(k_out, (cfg.dim, cfg.vocab_size), cfg.dim),
    }


def logical_axes(cfg: MixtralConfig):
    return {
        "embed": ("vocab", "embed"),
        "layers": {
            "attn": {
                "wq": ("layers", "embed", "heads", "head_dim"),
                "wk": ("layers", "embed", "kv_heads", "head_dim"),
                "wv": ("layers", "embed", "kv_heads", "head_dim"),
                "wo": ("layers", "heads", "head_dim", "embed"),
            },
            "gate": ("layers", "embed", None),
            "experts": {
                "w_gate": ("layers", "expert", "embed", "mlp"),
                "w_up": ("layers", "expert", "embed", "mlp"),
                "w_down": ("layers", "expert", "mlp", "embed"),
            },
            "attn_norm": ("layers", None),
            "mlp_norm": ("layers", None),
        },
        "final_norm": (None,),
        "lm_head": ("embed", "vocab"),
    }


def _expert_ffn(p, tokens):
    """One expert's SwiGLU over a token bucket [C, D]."""
    gate = jax.nn.silu(tokens @ p["w_gate"])
    up = tokens @ p["w_up"]
    return (gate * up) @ p["w_down"]


def _moe_block(x, layer, cfg: MixtralConfig, mesh):
    """Router + expert-parallel SwiGLU experts (residual applied by caller)."""
    b, s, d = x.shape
    if mesh is not None:
        return moe_layer(
            x, layer["gate"].astype(x.dtype), _expert_ffn, layer["experts"],
            mesh, num_experts=cfg.num_experts, top_k=cfg.top_k,
            capacity_factor=cfg.capacity_factor)
    # meshless fallback: dense top-k mixture (exact, no capacity drop)
    tokens = x.reshape(b * s, d)
    logits = (tokens @ layer["gate"].astype(x.dtype)).astype(jnp.float32)
    top_p, top_i = top_k_gating(logits, cfg.top_k)
    all_out = jax.vmap(lambda p: _expert_ffn(p, tokens))(layer["experts"])
    picked = jnp.take_along_axis(
        all_out.transpose(1, 0, 2), top_i[..., None], axis=1)  # [T,k,D]
    out = jnp.sum(picked * top_p[..., None].astype(x.dtype), axis=1)
    return out.reshape(b, s, d)


def forward(params, tokens, cfg: MixtralConfig, mesh=None):
    """tokens [B, T] → logits [B, T, vocab]."""
    lcfg = cfg.as_llama()
    b, t = tokens.shape
    x = params["embed"][tokens].astype(cfg.dtype)
    positions = jnp.broadcast_to(jnp.arange(t), (b, t))
    cos, sin = _llama.rope_freqs(lcfg, positions)

    def body(x, layer):
        h = _llama.rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        q = jnp.einsum("btd,dhk->bthk", h, layer["attn"]["wq"])
        k = jnp.einsum("btd,dhk->bthk", h, layer["attn"]["wk"])
        v = jnp.einsum("btd,dhk->bthk", h, layer["attn"]["wv"])
        q = _llama.apply_rope(q, cos, sin)
        k = _llama.apply_rope(k, cos, sin)
        attn = _llama._attention(q, k, v, lcfg, mesh)
        x = x + jnp.einsum("bthk,hkd->btd", attn, layer["attn"]["wo"])
        h = _llama.rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
        x = x + _moe_block(h, layer, cfg, mesh)
        return x, None

    if cfg.remat:
        body = jax.checkpoint(body)
    x, _ = jax.lax.scan(body, x, params["layers"])
    x = _llama.rms_norm(x, params["final_norm"], cfg.norm_eps)
    return (x @ params["lm_head"]).astype(jnp.float32)


def loss_fn(params, batch, cfg: MixtralConfig, mesh=None):
    tokens = batch["tokens"] if isinstance(batch, dict) else batch
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    logits = forward(params, inputs, cfg, mesh)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)
