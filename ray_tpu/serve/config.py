"""Serve configuration types (reference:
/root/reference/python/ray/serve/config.py — AutoscalingConfig,
DeploymentConfig fields on @serve.deployment api.py:333)."""

from __future__ import annotations

import dataclasses
from typing import Any, Optional


@dataclasses.dataclass
class AutoscalingConfig:
    """Queue-length driven replica autoscaling (reference
    autoscaling_policy.py:86 replica_queue_length_autoscaling_policy)."""

    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 3.0
    downscale_delay_s: float = 30.0

    def decide(self, current: int, total_ongoing: float) -> int:
        if current == 0:
            return self.min_replicas
        desired = total_ongoing / max(self.target_ongoing_requests, 1e-9)
        import math
        target = int(math.ceil(desired))
        return max(self.min_replicas, min(self.max_replicas, target))


@dataclasses.dataclass
class DeploymentConfig:
    num_replicas: int = 1
    max_ongoing_requests: int = 100
    user_config: Any = None
    autoscaling_config: Optional[AutoscalingConfig] = None
    health_check_period_s: float = 2.0
    health_check_timeout_s: float = 30.0
    graceful_shutdown_timeout_s: float = 20.0
    ray_actor_options: dict = dataclasses.field(default_factory=dict)

    def target_replicas(self) -> int:
        if self.autoscaling_config:
            return self.autoscaling_config.min_replicas
        return self.num_replicas
