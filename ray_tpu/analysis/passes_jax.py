"""graftlint JAX passes: host-sync-in-hot-path and jit-boundary hygiene.

host-sync guards the engine-loop design invariant from PR 6: dispatch
phases are host-cost-only, and the device sync lives in the designated
harvest methods (``_harvest_one`` / ``_apply_verify`` / the tier flush).
jit-hygiene guards against the mid-traffic-recompile class PR 6 had to
build runtime detection for: jitted callables that close over mutable
``self`` state or branch in Python on traced values re-trace silently
when that state drifts.
"""

from __future__ import annotations

import ast
import re
from typing import Optional

from ray_tpu.analysis.core import ModuleSource, Pass, iter_functions, register

# Engine hot-path methods: the loop's admit/prefill/dispatch family.
# Harvest-designated methods (_harvest_one, _apply_verify), warmup, and
# the tier spill/restore slow paths are exempt by name.
HOT_METHOD_RE = re.compile(
    r"^(_admit|_prefill|_prefill_chunks|_decode_step|_spec_step|"
    r"_dispatch_verify|_select_block|_record_token|_flush_slot_patches|"
    r"_propose_locked|_shed_expired_waiting|_step|_loop|submit)$")

# modules the host-sync pass applies to (the paged engine + its kin)
HOT_PATH_RE = re.compile(r"serve/llm/")


def _is_np_attr(fn: ast.AST, attrs: tuple) -> bool:
    return (isinstance(fn, ast.Attribute) and fn.attr in attrs
            and isinstance(fn.value, ast.Name)
            and fn.value.id in ("np", "numpy", "onp"))


@register
class HostSyncPass(Pass):
    """Device->host syncs inside engine dispatch/decode/verify methods.

    ``np.asarray`` / ``np.array`` on a device array, ``.item()``,
    ``jax.device_get`` and ``.block_until_ready()`` stall the engine loop
    on the device stream; they belong in the harvest phase (PR 6 phase
    timers attribute device wait there on purpose). ``jnp.asarray`` is
    host->device and fine.
    """

    id = "host-sync"
    title = "host sync in an engine hot path"
    hint = ("harvest device values in _harvest_one/_apply_verify (the "
            "designated sync points) or pragma "
            "`# graftlint: disable=host-sync` with a justification")

    def run(self, module: ModuleSource) -> list:
        if not HOT_PATH_RE.search(module.relpath):
            return []
        findings = []
        for fn, qualname, cls in iter_functions(module.tree):
            if cls is None or not HOT_METHOD_RE.match(fn.name):
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                tag = self._sync_tag(node)
                if tag is not None:
                    findings.append(self.emit(
                        module, node, qualname,
                        f"{tag} forces a device->host sync inside "
                        f"{fn.name} (hot path)", tag,
                        extra_pragma_lines=(fn.lineno,)))
        return [f for f in findings if f is not None]

    @staticmethod
    def _sync_tag(call: ast.Call) -> Optional[str]:
        fn = call.func
        if _is_np_attr(fn, ("asarray", "array")):
            return f"np.{fn.attr}"
        if isinstance(fn, ast.Attribute):
            if fn.attr == "block_until_ready":
                return "block_until_ready"
            if fn.attr == "device_get" and isinstance(fn.value, ast.Name) \
                    and fn.value.id == "jax":
                return "jax.device_get"
            if fn.attr == "item" and not call.args:
                return ".item()"
        if isinstance(fn, ast.Name) and fn.id in ("float", "int") \
                and call.args and isinstance(call.args[0], ast.Subscript):
            # float(logits[0])-style scalar pulls
            return f"{fn.id}(x[...])"
        return None


# ---------------------------------------------------------------------------


def _jit_targets(tree: ast.AST):
    """Yield (callable_node_or_name, jit_call_node, static_argnums) for
    every function handed to jax.jit / jit / pjit, plus decorated defs."""
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_jit(node.func) and node.args:
            out.append((node.args[0], node, _static_argnums(node)))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _is_jit(dec):
                    out.append((node, dec, ()))
                elif isinstance(dec, ast.Call):
                    if _is_jit(dec.func):
                        out.append((node, dec, _static_argnums(dec)))
                    elif isinstance(dec.func, ast.Attribute) \
                            and dec.func.attr == "partial" or \
                            isinstance(dec.func, ast.Name) \
                            and dec.func.id == "partial":
                        if dec.args and _is_jit(dec.args[0]):
                            out.append((node, dec, _static_argnums(dec)))
    return out


def _is_jit(fn: ast.AST) -> bool:
    if isinstance(fn, ast.Name):
        return fn.id in ("jit", "pjit")
    if isinstance(fn, ast.Attribute):
        return fn.attr in ("jit", "pjit")
    return False


def _static_argnums(call: ast.Call) -> tuple:
    for kw in call.keywords:
        if kw.arg in ("static_argnums", "static_argnames"):
            if isinstance(kw.value, ast.Tuple):
                return tuple(e.value for e in kw.value.elts
                             if isinstance(e, ast.Constant))
            if isinstance(kw.value, ast.Constant):
                return (kw.value.value,)
    return ()


@register
class JitHygienePass(Pass):
    """Functions passed to jax.jit/pjit that read mutable state or branch
    in Python on traced values.

    Checks the jitted callable's own body (one level — called helpers are
    the callee's responsibility): reads of ``self.X`` where ``X`` is
    assigned outside ``__init__`` (mutated at runtime => silent re-trace
    or stale capture), reads of mutable module globals, and ``if``/
    ``while`` tests on non-static parameters (TracerBoolConversionError
    at best, shape-specialized silent recompiles at worst).
    """

    id = "jit-hygiene"
    title = "jit-boundary hygiene"
    hint = ("pass mutable state as an explicit argument (donate if "
            "large), mark config args static_argnums, and replace "
            "Python branches on traced values with lax.cond/jnp.where")

    def run(self, module: ModuleSource) -> list:
        findings = []
        mutable_globals = self._mutable_globals(module.tree)
        class_mutables = self._class_mutable_attrs(module.tree)
        # map: function name -> def node (module + class scope), for
        # resolving jax.jit(name) / jax.jit(self._name) references
        defs: dict[str, ast.AST] = {}
        owner: dict[str, Optional[ast.ClassDef]] = {}
        for fn, qualname, cls in iter_functions(module.tree):
            defs.setdefault(fn.name, fn)
            owner.setdefault(fn.name, cls)

        seen: set[int] = set()
        for target, jit_call, static in _jit_targets(module.tree):
            fn_node, cls = self._resolve(target, defs, owner)
            if fn_node is None or id(fn_node) in seen:
                continue
            seen.add(id(fn_node))
            symbol = getattr(fn_node, "name", "<lambda>")
            mut_attrs = class_mutables.get(cls, set()) if cls else set()
            findings.extend(self._check_fn(
                module, fn_node, symbol, mut_attrs, mutable_globals, static))
        return [f for f in findings if f is not None]

    # -- resolution ------------------------------------------------------
    @staticmethod
    def _resolve(target, defs, owner):
        """(function_node, owning_class_node) for a jit target, best
        effort: lambdas and defs analyzed directly; names / self._m
        resolved within the module."""
        if isinstance(target, (ast.Lambda, ast.FunctionDef,
                               ast.AsyncFunctionDef)):
            # owning class unknown for inline defs; harmless (self-attr
            # checks then key off the lambda's own reads of self)
            return target, None
        name = None
        if isinstance(target, ast.Name):
            name = target.id
        elif isinstance(target, ast.Attribute) \
                and isinstance(target.value, ast.Name) \
                and target.value.id == "self":
            name = target.attr
        if name is not None and name in defs:
            return defs[name], owner.get(name)
        return None, None

    # -- model building --------------------------------------------------
    @staticmethod
    def _mutable_globals(tree: ast.AST) -> set[str]:
        """Module-level names assigned a value (not imports/defs) that are
        not ALL_CAPS constants."""
        out: set[str] = set()
        for node in tree.body if isinstance(tree, ast.Module) else ():
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)) \
                    and node.value is not None:
                targets = [node.target]
            for t in targets:
                if isinstance(t, ast.Name) and not t.id.isupper() \
                        and not t.id.startswith("__"):
                    out.add(t.id)
        return out

    @staticmethod
    def _class_mutable_attrs(tree: ast.AST) -> dict:
        """Per class: self attributes assigned outside __init__ (runtime-
        mutable), including subscript/augmented stores."""
        out: dict = {}
        for node in ast.walk(tree):
            if not isinstance(node, ast.ClassDef):
                continue
            mutable: set[str] = set()
            for meth in node.body:
                if not isinstance(meth, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                if meth.name == "__init__":
                    continue
                for sub in ast.walk(meth):
                    attr = None
                    if isinstance(sub, ast.Assign):
                        for t in sub.targets:
                            attr = attr or _self_attr_target(t)
                    elif isinstance(sub, (ast.AugAssign, ast.AnnAssign)):
                        attr = _self_attr_target(sub.target)
                    if attr:
                        mutable.add(attr)
            out[node] = mutable
        return out

    # -- the actual checks ----------------------------------------------
    def _check_fn(self, module, fn, symbol, mut_attrs, mutable_globals,
                  static) -> list:
        findings = []
        params = self._params(fn)
        static_names = {params[i] for i in static
                        if isinstance(i, int) and i < len(params)}
        static_names.update(s for s in static if isinstance(s, str))
        local_names = set(params)
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
                local_names.add(node.id)
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for node in ast.walk(fn):
            # (a) mutable self attribute reads
            if isinstance(node, ast.Attribute) \
                    and isinstance(node.ctx, ast.Load) \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id == "self" \
                    and node.attr in mut_attrs:
                findings.append(self.emit(
                    module, node, symbol,
                    f"jitted function reads self.{node.attr}, which is "
                    f"reassigned outside __init__ — the trace captures a "
                    f"stale value or re-traces mid-traffic",
                    f"self.{node.attr}",
                    extra_pragma_lines=(fn.lineno,)))
            # (b) mutable module-global reads
            elif isinstance(node, ast.Name) \
                    and isinstance(node.ctx, ast.Load) \
                    and node.id in mutable_globals \
                    and node.id not in local_names:
                findings.append(self.emit(
                    module, node, symbol,
                    f"jitted function reads mutable module global "
                    f"{node.id!r} — captured at trace time, silently stale "
                    f"after", f"global:{node.id}",
                    extra_pragma_lines=(fn.lineno,)))
            # (c) Python branches on traced parameters
            elif isinstance(node, (ast.If, ast.While, ast.IfExp)):
                bad = self._traced_test_param(node.test, set(params),
                                              static_names)
                if bad is not None:
                    findings.append(self.emit(
                        module, node, symbol,
                        f"Python `{'if' if not isinstance(node, ast.While) else 'while'}` "
                        f"on traced parameter {bad!r} inside a jitted "
                        f"function — TracerBoolConversionError or a compile "
                        f"per runtime value", f"branch:{bad}",
                        extra_pragma_lines=(fn.lineno,)))
        return findings

    @staticmethod
    def _params(fn) -> list[str]:
        a = fn.args
        names = [p.arg for p in a.posonlyargs + a.args]
        if names and names[0] == "self":
            names = names[1:]
        return names

    @staticmethod
    def _traced_test_param(test, params: set, static_names: set):
        """Name of a non-static parameter the test truth-depends on, or
        None. `is (not) None` identity checks are Python-level and fine."""
        if isinstance(test, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops):
            return None
        for node in ast.walk(test):
            if isinstance(node, ast.Call):
                # len(x), x.shape checks etc. are static under tracing
                return None
            if isinstance(node, ast.Attribute) and node.attr in (
                    "shape", "ndim", "dtype", "size"):
                return None
        for node in ast.walk(test):
            if isinstance(node, ast.Name) and node.id in params \
                    and node.id not in static_names:
                return node.id
        return None


def _self_attr_target(t) -> Optional[str]:
    if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
            and t.value.id == "self":
        return t.attr
    if isinstance(t, ast.Subscript):
        return _self_attr_target(t.value)
    if isinstance(t, (ast.Tuple, ast.List)):
        for e in t.elts:
            got = _self_attr_target(e)
            if got:
                return got
    return None
