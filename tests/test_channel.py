"""Mutable-object channel tests (reference:
python/ray/tests/experimental/test_mutable_objects.py model — writer/reader
rendezvous, multi-reader, overwrite-in-place)."""

import threading
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.core.channel import (Channel, ChannelClosedError,
                                  ChannelTimeoutError)


def test_channel_roundtrip_same_process():
    ch = Channel(capacity=1 << 16, num_readers=1)
    r = ch.reader(0)
    ch.write({"x": 1})
    assert r.read() == {"x": 1}
    ch.write([1, 2, 3])
    assert r.read() == [1, 2, 3]
    ch.unlink()


def test_channel_backpressure_and_order():
    ch = Channel(capacity=1 << 16, num_readers=1)
    r = ch.reader(0)
    got = []

    def consume():
        for _ in range(20):
            got.append(r.read(timeout=10.0))

    t = threading.Thread(target=consume)
    t.start()
    for i in range(20):
        ch.write(i, timeout=10.0)
    t.join(10.0)
    assert got == list(range(20))  # every value seen exactly once, in order
    ch.unlink()


def test_channel_writer_blocks_on_slow_reader():
    ch = Channel(capacity=1 << 12, num_readers=1)
    ch.write("first")
    with pytest.raises(ChannelTimeoutError):
        ch.write("second", timeout=0.2)  # reader never consumed "first"
    assert ch.reader(0).read() == "first"
    ch.write("second", timeout=5.0)  # now it fits
    ch.unlink()


def test_channel_multi_reader_broadcast():
    ch = Channel(capacity=1 << 14, num_readers=3)
    readers = [ch.reader(i) for i in range(3)]
    ch.write("v0")
    assert [r.read() for r in readers] == ["v0"] * 3
    ch.write("v1")
    assert [r.read() for r in readers] == ["v1"] * 3
    ch.unlink()


def test_channel_too_large_value():
    ch = Channel(capacity=64, num_readers=1)
    with pytest.raises(ValueError):
        ch.write(np.zeros(1024))
    ch.unlink()


def test_channel_close_wakes_reader():
    ch = Channel(capacity=1 << 12, num_readers=1)
    r = ch.reader(0)
    err = []

    def consume():
        try:
            r.read(timeout=10.0)
        except ChannelClosedError as e:
            err.append(e)

    t = threading.Thread(target=consume)
    t.start()
    time.sleep(0.1)
    ch.close()
    t.join(5.0)
    assert err
    ch.unlink()


def test_channel_cross_process_pipeline(ray_start_regular):
    """Producer/consumer actor pipeline over one channel — the host-side
    pipelining pattern compiled-graph channels exist for."""

    @ray_tpu.remote
    class Producer:
        def __init__(self, ch):
            self.ch = ch

        def run(self, n):
            for i in range(n):
                self.ch.write(np.full(128, i, np.float32), timeout=30.0)
            return n

    @ray_tpu.remote
    class Consumer:
        def __init__(self, reader):
            self.reader = reader

        def run(self, n):
            total = 0.0
            for _ in range(n):
                total += float(self.reader.read(timeout=30.0)[0])
            return total

    ch = Channel(capacity=1 << 16, num_readers=1)
    prod = Producer.remote(ch)
    cons = Consumer.remote(ch.reader(0))
    n = 50
    pf = prod.run.remote(n)
    cf = cons.run.remote(n)
    assert ray_tpu.get(pf, timeout=60.0) == n
    assert ray_tpu.get(cf, timeout=60.0) == float(sum(range(n)))
    ch.unlink()
