"""Cluster-wide tiered KV cache: spill evicted prefix pages, restore
anywhere.

PR 3's prefix cache is per-replica: a page chain evicted under pool
pressure is simply freed, and a cold replica re-prefills prefixes a
sibling already computed. This module keeps those chains alive in two
lower tiers and publishes them cluster-wide (Mooncake's KV-cache-centric
store, CacheGen's cache-across-machines result — see PAPERS.md):

- **shm tier**: spilled page chains are ``put()`` into the node's shm
  object plane (the same blob layout disagg's KV handoff ships:
  ``[L, Hkv, pages, page, D]`` per k/v). The store holds the ObjectRef,
  so the bytes stay pinned in shared memory until demoted or expired.
  Outside a cluster (unit tests, standalone engines) the tier degrades
  to an in-process dict with identical accounting.
- **disk tier**: a bounded local directory backs shm under pressure —
  the LRU shm blob demotes to disk instead of dying. Disk blobs are
  local-only: their cluster-index entries lose the object ref, so
  remote replicas skip them while the owner can still restore.
- **cluster index**: every spilled page registers a CP KV entry
  ``kv_tier:<ns>:<chain-digest-hex>`` -> JSON {owner, node, store,
  blob, off, tokens, nbytes, tier, ts, ttl_s, ref, ns}. ``ns`` is a
  model-identity namespace (the engine hashes model id, checkpoint,
  architecture config, KV dtype and page size): two replicas only see
  each other's entries when their KV bytes are actually interchangeable
  — a digest alone encodes the token prefix, not which model produced
  the KV. Entries are retracted when the owning worker or node dies
  (control_plane worker_died/_on_node_dead, exactly like the
  metrics-store GC) and lazily on TTL expiry (``ray-tpu kvtier --gc``).

Both caps are byte caps enforced at put time; eviction within a tier is
LRU; every entry carries a TTL. All failure paths degrade: a failed
spill leaves eviction a plain free, a failed restore is a plain cache
miss.

Concurrency: ``self._lock`` guards only in-memory bookkeeping — never
I/O. Disk writes (demotion), disk reads and object-plane gets (restore)
run on snapshots taken under the lock, so a slow tier never serializes
concurrent spills, probes, or stats readers. All cluster-index traffic
(register on put/demote, retract on drop) flows through ONE background
publisher thread fed by an ordered queue: snapshots are enqueued under
the lock in mutation order, so a retract can never race past the
register it supersedes.
"""

from __future__ import annotations

import json
import logging
import os
import pickle
import queue
import threading
import time
import uuid
from collections import OrderedDict
from typing import Optional

import numpy as np

logger = logging.getLogger(__name__)

_KEY_PREFIX = "kv_tier:"

# Restore-path fetch budgets. A restore replaces (part of) a prefill, so
# it only pays while it's cheaper than recomputing: a dead peer or stale
# index entry must degrade to a plain miss in O(prefill) time, not stall
# the engine loop (and every active decode behind it) for tens of
# seconds. Sized to replace-a-prefill economics.
_REMOTE_FETCH_TIMEOUT_S = 2.0   # object-plane get of a peer's blob
_LOCAL_REF_TIMEOUT_S = 2.0      # object-plane get of our own shm blob

# idle exit for the lazily-started index-publisher thread
_PUB_IDLE_EXIT_S = 5.0

# Prefetch-hint buffer (ISSUE 10): pages fetched ahead of the request by
# the router's affinity-miss hint. Bounded by page count + TTL so a storm
# of hints (or hints for requests that never arrive) can't grow host
# memory — the buffer is pure opportunism, fetch_chain falls through to
# the normal remote path on a miss.
_HINT_MAX_PAGES = 512
_HINT_TTL_S = 30.0
_HINT_QUEUE_MAX = 8  # pending prefetch jobs; extra hints drop, not queue


def _now() -> float:
    return time.time()


class KVTierStore:
    """Local spill store (shm + disk tiers) plus cluster-index client.

    One instance per engine. All device work stays in the engine — this
    class only ever sees host numpy blobs. Thread-safe; the engine loop
    is the only writer, stats/CLI readers may probe concurrently.

    ``namespace`` scopes the cluster index to one model identity; the
    engine passes a hash of (model id, checkpoint, architecture, KV
    dtype, page size). Empty namespace (unit tests, standalone stores)
    means un-scoped keys.
    """

    def __init__(self, max_bytes: int, disk_dir: Optional[str],
                 disk_max_bytes: int, ttl_s: float, page_size: int,
                 namespace: str = ""):
        self.max_bytes = int(max_bytes)
        self.disk_dir = disk_dir
        self.disk_max_bytes = int(disk_max_bytes)
        self.ttl_s = float(ttl_s)
        self.page_size = int(page_size)
        self.namespace = str(namespace)
        # distinct from the worker id: several engines (serve replicas,
        # tests) can share one worker process, and "is this entry mine"
        # must mean THIS store, while death-GC keys on the worker
        self.store_id = uuid.uuid4().hex[:12]
        self._lock = threading.Lock()
        # blob_id -> record; OrderedDict is the shm-tier LRU (disk-tier
        # records stay members but carry tier="disk")
        self._blobs: OrderedDict[str, dict] = OrderedDict()
        self._by_digest: dict[str, tuple[str, int]] = {}  # digest -> (blob, off)
        self._shm_bytes = 0
        self._disk_bytes = 0
        self.counters = {"put_blobs": 0, "put_pages": 0, "demoted_blobs": 0,
                         "dropped_blobs": 0, "expired_blobs": 0,
                         "local_hits": 0, "remote_hits": 0,
                         "prefetch_hints": 0, "prefetch_pages": 0,
                         "prefetch_hit_pages": 0, "prefetch_dropped": 0}
        # ordered cluster-index publisher (see module docstring)
        self._pub_q: queue.Queue = queue.Queue()
        self._pub_thread: Optional[threading.Thread] = None
        # prefetch-hint buffer: digest -> {"k","v" [L,Hkv,1,page,D], "ts"}
        # (cap + TTL above); filled by the background prefetch worker,
        # consumed (and kept until TTL/cap) by fetch_chain
        self._hints: OrderedDict[str, dict] = OrderedDict()
        self._prefetch_q: queue.Queue = queue.Queue(
            maxsize=_HINT_QUEUE_MAX)
        self._prefetch_thread: Optional[threading.Thread] = None

    # ---- runtime plumbing ----------------------------------------------
    @staticmethod
    def _runtime():
        from ray_tpu.core import api
        return api._try_get_runtime()

    def _cp_call(self, method: str, body, timeout: float = 5.0):
        rt = self._runtime()
        if rt is None:
            return None
        return rt.cp_client.call(method, body, timeout=timeout)

    def _key(self, digest_hex: str) -> str:
        if self.namespace:
            return _KEY_PREFIX + self.namespace + ":" + digest_hex
        return _KEY_PREFIX + digest_hex

    # ---- spill ----------------------------------------------------------
    def put(self, k_np: np.ndarray, v_np: np.ndarray,
            digests: list[str], tokens: list[int]) -> int:
        """Store one spilled chain batch. ``k_np``/``v_np`` are host
        arrays shaped [L, Hkv, n, page, D]; ``digests[i]``/``tokens[i]``
        are page i's chain digest (hex) and its cumulative token length.
        Returns how many pages were registered (0 when the batch doesn't
        fit the shm cap at all)."""
        nbytes = int(k_np.nbytes) + int(v_np.nbytes)
        if nbytes > self.max_bytes or not digests:
            return 0
        blob = {"k": k_np, "v": v_np, "page_size": self.page_size,
                "digests": list(digests), "tokens": list(tokens)}
        bid = uuid.uuid4().hex[:16]
        rt = self._runtime()
        ref = rt.put(blob) if rt is not None else None
        rec = {"id": bid, "nbytes": nbytes, "tier": "shm", "ts": _now(),
               "digests": list(digests), "tokens": list(tokens),
               "ref": ref, "data": blob if ref is None else None,
               "path": None}
        with self._lock:
            self._expire_locked()
        # demotion does disk I/O, so it runs its own lock/unlock cycles
        self._make_room(nbytes)
        with self._lock:
            self._blobs[bid] = rec
            self._shm_bytes += nbytes
            for i, d in enumerate(digests):
                self._by_digest[d] = (bid, i)
            self.counters["put_blobs"] += 1
            self.counters["put_pages"] += len(digests)
            self._pub_enqueue_locked("register", rec)
        return len(digests)

    # ---- cluster-index publisher ----------------------------------------
    def _pub_enqueue_locked(self, op: str, rec: dict) -> None:
        """Queue one register/retract for the publisher thread. Caller
        holds the lock: the snapshot taken HERE is what the thread sends,
        so it never reads rec fields that a later demotion/drop mutates,
        and queue order == mutation order (a retract can't overtake the
        register it supersedes)."""
        snap = {"id": rec["id"], "nbytes": rec["nbytes"],
                "tier": rec["tier"], "ts": rec["ts"],
                "digests": list(rec["digests"]),
                "tokens": list(rec["tokens"]), "ref": rec["ref"]}
        self._pub_q.put((op, snap))
        t = self._pub_thread
        if t is None or not t.is_alive():
            t = threading.Thread(target=self._pub_loop, daemon=True,
                                 name="kv-tier-pub")
            self._pub_thread = t
            t.start()

    def _pub_loop(self) -> None:
        while True:
            try:
                op, snap = self._pub_q.get(timeout=_PUB_IDLE_EXIT_S)
            except queue.Empty:
                # exit decision under the lock so an enqueuer can't slip
                # an item in between the emptiness check and the return
                with self._lock:
                    if self._pub_q.empty():
                        self._pub_thread = None
                        return
                continue
            if op is None:  # close() sentinel
                return
            try:
                if op == "register":
                    self._register_cp(snap)
                else:
                    self._retract_cp(snap)
            except Exception:
                logger.debug("kv-tier: index %s failed", op, exc_info=True)

    def _register_cp(self, snap: dict) -> None:
        """Publish every page of one blob into the CP ``kv_tier:``
        namespace. Best-effort — index registration must never break
        serving (an unregistered spill is still locally restorable)."""
        rt = self._runtime()
        if rt is None:
            return
        try:
            whex = rt.worker_id.hex()
            nhex = rt.node_id.hex() if rt.node_id is not None else ""
            ref_hex = (pickle.dumps(snap["ref"]).hex()
                       if snap["tier"] == "shm" and snap["ref"] is not None
                       else None)
            per_page = snap["nbytes"] // max(1, len(snap["digests"]))
            for i, d in enumerate(snap["digests"]):
                entry = {"owner": whex, "node": nhex,
                         "store": self.store_id, "blob": snap["id"],
                         "off": i, "tokens": snap["tokens"][i],
                         "nbytes": per_page, "tier": snap["tier"],
                         "ts": snap["ts"], "ttl_s": self.ttl_s,
                         "ref": ref_hex, "ns": self.namespace}
                self._cp_call("kv_put", {
                    "key": self._key(d),
                    "value": json.dumps(entry).encode(),
                    "overwrite": True})
        except Exception:
            logger.debug("kv-tier: CP index registration failed",
                         exc_info=True)

    def _retract_cp(self, snap: dict) -> None:
        """Compare-and-delete our own index entries. The CP only drops a
        key when its entry still carries OUR (store, blob) — when the
        digest was re-spilled into a newer blob, the newer registration
        survives (same guard _drop_locked applies to _by_digest). A
        transient CP failure skips just that digest: the TTL sweep and
        worker-death GC collect what we miss."""
        for d in snap["digests"]:
            try:
                self._cp_call("kv_tier_del", {
                    "key": self._key(d), "store": self.store_id,
                    "blob": snap["id"]}, timeout=2.0)
            except Exception:
                continue

    # ---- tier maintenance ------------------------------------------------
    def _expire_locked(self) -> None:
        if self.ttl_s <= 0:
            return
        cutoff = _now() - self.ttl_s
        dead = [b for b, r in self._blobs.items() if r["ts"] < cutoff]
        for bid in dead:
            self._drop_locked(bid, reason="expired")

    def _make_room(self, nbytes: int) -> None:
        """Demote (or drop) LRU shm blobs until ``nbytes`` fits the shm
        cap. The disk write is staged OUTSIDE the lock — the victim is
        marked "demoting" so concurrent callers skip it, and the tier
        flip (accounting + re-registration) happens under the lock only
        once the bytes are safely on disk. When nothing is demotable the
        caller inserts over-cap, same best-effort as a failed demotion
        (the engine loop is the only writer)."""
        while True:
            with self._lock:
                if self._shm_bytes + nbytes <= self.max_bytes:
                    return
                oldest = next((b for b, r in self._blobs.items()
                               if r["tier"] == "shm"
                               and not r.get("demoting")), None)
                if oldest is None:
                    return
                rec = self._blobs[oldest]
                if (self.disk_dir is None
                        or rec["nbytes"] > self.disk_max_bytes):
                    self._drop_locked(oldest, reason="dropped")
                    continue
                rec["demoting"] = True
                handle = {"data": rec["data"], "path": rec["path"],
                          "ref": rec["ref"]}
            path: Optional[str] = None
            try:
                blob = self._load_handle(handle)
                os.makedirs(self.disk_dir, exist_ok=True)
                path = os.path.join(self.disk_dir, rec["id"] + ".kvt")
                with open(path, "wb") as f:
                    pickle.dump(blob, f)
            except Exception:
                logger.warning("kv-tier: demotion to disk failed; dropping",
                               exc_info=True)
                path = None
            with self._lock:
                rec.pop("demoting", None)
                live = rec["id"] in self._blobs
                if live and path is not None:
                    while self._disk_bytes + rec["nbytes"] \
                            > self.disk_max_bytes:
                        victim = next((b for b, r in self._blobs.items()
                                       if r["tier"] == "disk"), None)
                        if victim is None:
                            break
                        self._drop_locked(victim, reason="dropped")
                    rec.update(tier="disk", path=path, ref=None, data=None)
                    self._shm_bytes -= rec["nbytes"]
                    self._disk_bytes += rec["nbytes"]
                    self.counters["demoted_blobs"] += 1
                    # remote replicas must stop trying to fetch the gone
                    # object ref — re-register (queue order keeps this
                    # behind any earlier retract of the same digests)
                    self._pub_enqueue_locked("register", rec)
                    path = None
                elif live:
                    self._drop_locked(rec["id"], reason="dropped")
            if path is not None:
                # blob was dropped while we wrote: the file is an orphan
                try:
                    os.unlink(path)
                except OSError:
                    pass

    def _drop_locked(self, bid: str, reason: str) -> None:
        rec = self._blobs.pop(bid, None)
        if rec is None:
            return
        if rec["tier"] == "shm":
            self._shm_bytes -= rec["nbytes"]
        else:
            self._disk_bytes -= rec["nbytes"]
            if rec["path"]:
                try:
                    os.unlink(rec["path"])
                except OSError:
                    pass
        for d in rec["digests"]:
            if self._by_digest.get(d, (None,))[0] == bid:
                del self._by_digest[d]
        self.counters["%s_blobs" % reason] += 1
        self._pub_enqueue_locked("retract", rec)

    def _load_handle(self, handle: dict) -> dict:
        """Materialize a blob from a snapshot taken under the lock. Runs
        WITHOUT the lock — disk reads and object-plane gets must never
        serialize other store users."""
        if handle["data"] is not None:
            return handle["data"]
        if handle["path"] is not None:
            with open(handle["path"], "rb") as f:
                return pickle.load(f)
        rt = self._runtime()
        if rt is None:
            raise RuntimeError("kv-tier blob held by ref but no runtime")
        return rt.get([handle["ref"]], timeout=_LOCAL_REF_TIMEOUT_S)[0]

    # ---- restore ---------------------------------------------------------
    def fetch_chain(self, digests: list[str], start: int):
        """Longest restorable run of chain pages beginning at ``start``.

        ``digests`` are the prompt's full-page chain digests (hex),
        position 0 first. Local tiers are probed before the cluster
        index; a local run and a remote run are never mixed. Returns
        ``(t, k_np, v_np)`` with the arrays shaped [L, Hkv, t, page, D],
        or ``(0, None, None)``."""
        run: list[tuple[str, int]] = []
        handles: dict[str, dict] = {}
        with self._lock:
            self._expire_locked()
            i = start
            while i < len(digests):
                loc = self._by_digest.get(digests[i])
                if loc is None:
                    break
                run.append(loc)
                i += 1
            # touch for LRU recency and snapshot each blob's load handle
            # under the lock; the actual disk/ref loads happen below,
            # lock released
            for bid, _off in run:
                if bid not in handles:
                    self._blobs.move_to_end(bid)
                    rec = self._blobs[bid]
                    handles[bid] = {"data": rec["data"],
                                    "path": rec["path"], "ref": rec["ref"]}
        if run:
            try:
                blobs = {bid: self._load_handle(h)
                         for bid, h in handles.items()}
                parts_k = [blobs[bid]["k"][:, :, off:off + 1]
                           for bid, off in run]
                parts_v = [blobs[bid]["v"][:, :, off:off + 1]
                           for bid, off in run]
                with self._lock:
                    self.counters["local_hits"] += len(run)
                return (len(run), np.concatenate(parts_k, axis=2),
                        np.concatenate(parts_v, axis=2))
            except Exception:
                # the blob moved (dropped/demoted, ref freed, file gone)
                # between snapshot and load: treat as a local miss and
                # fall through to the cluster probe
                logger.debug("kv-tier: local chain load failed",
                             exc_info=True)
        hit = self._hint_chain(digests, start)
        if hit is not None:
            return hit
        return self._fetch_remote(digests, start)

    # ---- hinted prefetch (ISSUE 10) --------------------------------------
    def _hint_chain(self, digests: list[str], start: int):
        """Serve a restore run out of the prefetch-hint buffer: pages the
        router's affinity-miss hint already pulled over the object plane.
        Pure memory — no I/O, no CP call. Returns (t, k, v) or None."""
        with self._lock:
            self._expire_hints_locked()
            parts_k, parts_v = [], []
            i = start
            while i < len(digests):
                h = self._hints.get(digests[i])
                if h is None:
                    break
                parts_k.append(h["k"])
                parts_v.append(h["v"])
                i += 1
            if not parts_k:
                return None
            self.counters["prefetch_hit_pages"] += len(parts_k)
        return (len(parts_k), np.concatenate(parts_k, axis=2),
                np.concatenate(parts_v, axis=2))

    def _expire_hints_locked(self) -> None:
        cutoff = _now() - _HINT_TTL_S
        while self._hints:
            d, h = next(iter(self._hints.items()))
            if h["ts"] >= cutoff:
                break
            del self._hints[d]

    def prefetch(self, digests: list[str], start: int) -> bool:
        """Queue a background fetch of ``digests[start:]`` into the hint
        buffer (router affinity-miss hint). Never blocks the caller: a
        full queue drops the hint — the request's own restore path is the
        fallback. Returns whether the job was accepted."""
        with self._lock:
            self._expire_hints_locked()
            # skip pages already hinted; an all-hinted chain needs no job
            while start < len(digests) and digests[start] in self._hints:
                start += 1
            if start >= len(digests):
                return False
            try:
                self._prefetch_q.put_nowait((list(digests), start))
            except queue.Full:
                self.counters["prefetch_dropped"] += 1
                return False
            self.counters["prefetch_hints"] += 1
            # enqueue and worker-liveness check run under the same lock
            # as the worker's exit decision in _prefetch_loop: without
            # this, a hint slipped between the worker's empty-check and
            # its exit could observe the old thread as alive, start no
            # replacement, and strand the job until the next hint
            t = self._prefetch_thread
            if t is None or not t.is_alive():
                t = threading.Thread(target=self._prefetch_loop,
                                     daemon=True, name="kv-tier-prefetch")
                self._prefetch_thread = t
                t.start()
        return True

    def _prefetch_loop(self) -> None:
        while True:
            try:
                job = self._prefetch_q.get(timeout=_PUB_IDLE_EXIT_S)
            except queue.Empty:
                with self._lock:
                    if self._prefetch_q.empty():
                        self._prefetch_thread = None
                        return
                continue
            if job is None:  # close() sentinel
                return
            digests, start = job
            try:
                t, k_np, v_np = self._fetch_remote(digests, start)
            except Exception:  # noqa: BLE001 — prefetch is best-effort
                logger.debug("kv-tier: prefetch fetch failed",
                             exc_info=True)
                continue
            if t <= 0:
                continue
            now = _now()
            with self._lock:
                for i in range(t):
                    # per-page copies, not views: a view would pin the
                    # whole fetched chain array alive until every sibling
                    # page is evicted, so the _HINT_MAX_PAGES cap would
                    # bound entry count but not bytes
                    self._hints[digests[start + i]] = {
                        "k": k_np[:, :, i:i + 1].copy(),
                        "v": v_np[:, :, i:i + 1].copy(),
                        "ts": now}
                    self._hints.move_to_end(digests[start + i])
                self.counters["prefetch_pages"] += t
                while len(self._hints) > _HINT_MAX_PAGES:
                    self._hints.popitem(last=False)

    def _fetch_remote(self, digests: list[str], start: int):
        rt = self._runtime()
        if rt is None:
            return 0, None, None
        resp = self._cp_call("kv_tier_match", {"digests": digests[start:],
                                               "ns": self.namespace})
        raw = (resp or {}).get("entries") or []
        entries = []
        for v in raw:
            try:
                e = json.loads(v.decode() if isinstance(v, bytes) else v)
            except (ValueError, AttributeError):
                break
            # disk-tier entries are owner-local; our own stale entries
            # (already missed the local probe above) are unusable too;
            # a namespace mismatch (pre-namespace entry, hash collision)
            # would hand us another model's KV
            if e.get("tier") != "shm" or not e.get("ref") \
                    or e.get("store") == self.store_id \
                    or e.get("ns", "") != self.namespace:
                break
            entries.append(e)
        if not entries:
            return 0, None, None
        refs: dict[str, object] = {}
        for e in entries:
            if e["ref"] not in refs:
                refs[e["ref"]] = pickle.loads(bytes.fromhex(e["ref"]))
        fetched = rt.get(list(refs.values()),
                         timeout=_REMOTE_FETCH_TIMEOUT_S)
        blobs = dict(zip(refs.keys(), fetched))
        parts_k, parts_v = [], []
        for e in entries:
            blob = blobs[e["ref"]]
            off = int(e["off"])
            parts_k.append(blob["k"][:, :, off:off + 1])
            parts_v.append(blob["v"][:, :, off:off + 1])
        with self._lock:
            self.counters["remote_hits"] += len(entries)
        return (len(entries), np.concatenate(parts_k, axis=2),
                np.concatenate(parts_v, axis=2))

    # ---- observability / lifecycle --------------------------------------
    def stats(self) -> dict:
        with self._lock:
            shm = sum(1 for r in self._blobs.values() if r["tier"] == "shm")
            return {**self.counters,
                    "shm_bytes": self._shm_bytes,
                    "disk_bytes": self._disk_bytes,
                    "blobs_shm": shm,
                    "blobs_disk": len(self._blobs) - shm,
                    "indexed_pages": len(self._by_digest),
                    "hint_pages": len(self._hints)}

    def close(self) -> None:
        """Drop every blob and retract our index entries (clean engine
        shutdown; crash cleanup is the CP's worker-death GC)."""
        with self._lock:
            for bid in list(self._blobs):
                self._drop_locked(bid, reason="dropped")
            t = self._pub_thread
            self._pub_q.put((None, None))  # drains behind the retracts
            pt = self._prefetch_thread
            self._hints.clear()
        try:
            self._prefetch_q.put_nowait(None)
        except queue.Full:
            pass
        if t is not None and t.is_alive():
            t.join(timeout=5.0)
        if pt is not None and pt.is_alive():
            pt.join(timeout=5.0)
