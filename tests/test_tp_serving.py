"""Tensor-parallel serving engine (ISSUE 20): the paged engine sharded
over a "tensor" mesh axis.

Every test here drives a REAL TP=2 mesh: conftest.py forces 8 virtual
CPU host devices (XLA_FLAGS=--xla_force_host_platform_device_count=8)
before jax imports, so the engine's pjit/GSPMD programs and the
shard_map-wrapped pallas kernels compile genuinely partitioned.

Pins the PR's acceptance invariants:
- TP=2 greedy decode is TOKEN-IDENTICAL to TP=1 on the lossless path
  with prefix cache + speculative decoding + kv-tier restore all on,
  under both attention backends (gather/GSPMD and pallas/shard_map);
- a sharded tier store writes per-shard encoded sub-payloads under ONE
  chain digest (mode="shards" pages — the shard split lives inside the
  payload, never in the chain structure), restores reassemble
  bit-exactly, and mid-stream failover resume over a sharded chain is
  token-identical (PR 14's guarantee survives sharding);
- TP=1 and TP=2 engines index under DIFFERENT tier namespaces (the
  `|tp{N}` suffix — same precedent as `|int8`), so blob layouts never
  mix across stores;
- the engine's device state is genuinely sharded (per-KV-head pool
  split, Megatron-split weights) and the per-shard byte gauges report
  one chip's slice while page counts stay whole-replica.
"""

import time

import numpy as np
import pytest

from ray_tpu.models import llama
from ray_tpu.serve.llm import LLMConfig, LLMEngine
from ray_tpu.serve.llm.engine import kv_tier_namespace

PROMPT = "the quick brown fox jumps over the lazy dog"   # 43 byte-tokens
LONG = PROMPT + " " + PROMPT                             # 87 -> 5 full pages
REPETITIVE = "abc abc abc abc abc abc abc"               # n-gram drafts recur


def _tp_cfg(tp=2, **kw):
    # llama_tiny: n_heads=4, n_kv_heads=2, ffn_dim=128 — all divisible by
    # tp=2, and vocab 512 for the vocab-sharded lm_head. Same page/pool
    # geometry as test_kv_tier.py so the spill/restore choreography
    # (cap-2 prefix cache evicts the 3-page chain head) carries over.
    d = dict(model_config=llama.llama_tiny(vocab_size=512),
             tp_degree=tp, max_batch_size=4, page_size=16, num_pages=64,
             max_prompt_len=96, max_seq_len=160, max_tokens=8,
             prefix_cache_max_pages=2, kv_tier_enabled=True)
    d.update(kw)
    return LLMConfig(**d)


_WANT: dict = {}


def _want_tokens(prompt, max_tokens=8):
    """Greedy ground truth from a single-chip, cache-off, tier-off
    engine — the pre-TP baseline every TP run must reproduce exactly."""
    key = (prompt, max_tokens)
    if key not in _WANT:
        off = LLMEngine(_tp_cfg(tp=1, kv_tier_enabled=False,
                                prefix_cache_enabled=False), rng_seed=0)
        off.start()
        try:
            _WANT[key] = off.generate(prompt, max_tokens=max_tokens,
                                      temperature=0.0)["tokens"]
        finally:
            off.shutdown()
    return _WANT[key]


def _wait(pred, timeout=60.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return pred()


# ---------------------------------------------------------------------------
# sharded device state + gauges
# ---------------------------------------------------------------------------


def test_tp_engine_state_is_sharded():
    eng = LLMEngine(_tp_cfg(tp=2), rng_seed=0)
    try:
        # pool [L, Hkv, P, page, D] splits per-KV-head: each shard holds
        # Hkv/2 heads of every page
        k = eng.kv["k"]
        assert k.sharding.shard_shape(k.shape)[1] == k.shape[1] // 2
        assert k.sharding.shard_shape(k.shape)[2] == k.shape[2]
        # Megatron weight split: wq [L, D, H, hd] column-parallel on H,
        # wo [L, H, hd, D] row-parallel, norms replicated
        wq = eng.params["layers"]["attn"]["wq"]
        assert wq.sharding.shard_shape(wq.shape)[2] == wq.shape[2] // 2
        wo = eng.params["layers"]["attn"]["wo"]
        assert wo.sharding.shard_shape(wo.shape)[1] == wo.shape[1] // 2
        fn = eng.params["final_norm"]
        assert fn.sharding.shard_shape(fn.shape) == fn.shape
        # small decode state rides the mesh replicated
        pt = eng._pt_dev
        assert pt.sharding.shard_shape(pt.shape) == pt.shape

        st = eng.engine_stats()
        assert st["tp_degree"] == 2
        assert st["mesh_shape"] == "tensor=2"
        pool = int(eng.kv["k"].nbytes + eng.kv["v"].nbytes)
        assert st["kv_shard_pool_bytes"] == pool // 2
        # page counts stay whole-replica: free_pages is not divided
        assert st["free_pages"] == eng.allocator.available()
    finally:
        eng.shutdown()


def test_tp1_builds_no_mesh_and_default_namespace():
    eng = LLMEngine(_tp_cfg(tp=1), rng_seed=0)
    try:
        assert eng._mesh is None and eng._tp == 1
        st = eng.engine_stats()
        assert st["tp_degree"] == 1 and st["mesh_shape"] == "none"
        assert st["kv_shard_pool_bytes"] == int(
            eng.kv["k"].nbytes + eng.kv["v"].nbytes)
    finally:
        eng.shutdown()


def test_tp_degree_must_divide_heads():
    with pytest.raises(ValueError, match="n_kv_heads"):
        LLMEngine(_tp_cfg(tp=6), rng_seed=0)


# ---------------------------------------------------------------------------
# greedy token identity: TP=2 == TP=1, full stack on, both backends
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("backend", ["gather", "pallas"])
def test_tp2_greedy_identity_full_stack(backend):
    """The PR's headline invariant: with prefix cache + spec decode +
    kv-tier restore ALL on, a TP=2 engine's greedy tokens equal the
    single-chip baseline — cold, and again through a sharded tier
    restore."""
    want = _want_tokens(LONG)
    eng = LLMEngine(_tp_cfg(tp=2, attention_kernel=backend,
                            spec_decode_enabled=True, spec_draft_len=2),
                    rng_seed=0)
    eng.start()
    try:
        assert eng.engine_stats()["attention_backend"] == backend
        cold = eng.generate(LONG, temperature=0.0)
        assert cold["error"] is None
        assert cold["tokens"] == want, "TP=2 cold decode diverged"
        # chain head evicted + spilled sharded; the rerun restores it
        assert _wait(lambda: eng.engine_stats()["spilled_pages"] >= 3)
        hot = eng.generate(LONG, temperature=0.0)["tokens"]
        assert hot == want, "TP=2 decode over sharded restore diverged"
        st = eng.engine_stats()
        assert st["restored_pages"] >= 3
        assert st["tier_hit_tokens"] >= 3 * 16
    finally:
        eng.shutdown()


def test_tp2_spec_decode_identity_and_acceptance():
    """The verify-k program under TP: drafts accepted on a repetitive
    prompt, tokens still identical to the single-chip baseline."""
    want = _want_tokens(REPETITIVE, 32)
    eng = LLMEngine(_tp_cfg(tp=2, spec_decode_enabled=True,
                            max_tokens=32), rng_seed=0)
    eng.start()
    try:
        out = eng.generate(REPETITIVE, max_tokens=32, temperature=0.0)
        assert out["error"] is None
        assert out["tokens"] == want, "TP=2 speculative decode diverged"
        st = eng.engine_stats()
        assert st["spec_rounds"] > 0
        assert st["spec_drafted_tokens"] > 0
    finally:
        eng.shutdown()


# ---------------------------------------------------------------------------
# sharded tier blobs: per-shard payloads under one chain digest
# ---------------------------------------------------------------------------


def test_sharded_store_blob_layout_and_roundtrip():
    from ray_tpu.serve.llm.kv_cache import _chain_digest
    from ray_tpu.serve.llm.kv_tier import KVTierStore

    rng = np.random.default_rng(0)
    shape = (2, 2, 3, 4, 8)                    # [L, Hkv=2, n, page, D]
    k = rng.standard_normal(shape).astype(np.float32)
    v = rng.standard_normal(shape).astype(np.float32)
    digest, digs = b"", []
    for i in range(3):
        digest = _chain_digest(digest, [100 + i])
        digs.append(digest.hex())
    toks = [(i + 1) * 4 for i in range(3)]

    s = KVTierStore(max_bytes=1 << 20, disk_dir=None, disk_max_bytes=0,
                    ttl_s=600.0, page_size=4, codec="lossless", shards=2)
    assert s.put(k, v, digs, toks) == 3
    # ONE blob, chain digests untouched, but each page payload carries
    # the per-shard split (mode="shards", one sub-payload per kv-head
    # shard) — the wire unit ChainStream fans to every shard
    (rec,) = s._blobs.values()
    pages = rec["data"]["pages"]
    assert len(pages) == 3
    for ek, ev in pages:
        assert ek["mode"] == "shards" and len(ek["shards"]) == 2
        assert ev["mode"] == "shards" and len(ev["shards"]) == 2
    # restore reassembles the full per-KV-head pages bit-exactly
    t, gk, gv = s.fetch_chain(digs, start=0)
    assert t == 3
    np.testing.assert_array_equal(gk, k)
    np.testing.assert_array_equal(gv, v)


def test_sharded_store_codec_none_also_shards():
    """shards>1 forces the per-page payload layout even with codec
    "none": the shard split lives inside the payload, so a raw-codec TP
    store still writes independently decodable per-shard slices."""
    from ray_tpu.serve.llm.kv_tier import KVTierStore

    rng = np.random.default_rng(1)
    k = rng.standard_normal((2, 2, 2, 4, 8)).astype(np.float32)
    v = rng.standard_normal((2, 2, 2, 4, 8)).astype(np.float32)
    digs = ["aa" * 16, "bb" * 16]
    s = KVTierStore(max_bytes=1 << 20, disk_dir=None, disk_max_bytes=0,
                    ttl_s=600.0, page_size=4, codec="none", shards=2)
    assert s.put(k, v, digs, [4, 8]) == 2
    (rec,) = s._blobs.values()
    assert "pages" in rec["data"], "sharded store must use payload layout"
    t, gk, gv = s.fetch_chain(digs, start=0)
    assert t == 2
    np.testing.assert_array_equal(gk, k)
    np.testing.assert_array_equal(gv, v)


def test_tp_engine_spills_sharded_blobs():
    eng = LLMEngine(_tp_cfg(tp=2), rng_seed=0)
    eng.start()
    try:
        want = _want_tokens(LONG)
        assert eng.generate(LONG, temperature=0.0)["tokens"] == want
        assert _wait(lambda: eng.engine_stats()["spilled_pages"] >= 3)
        blobs = list(eng._kv_tier._blobs.values())
        assert blobs
        for rec in blobs:
            for ek, ev in rec["data"]["pages"]:
                assert ek["mode"] == "shards" and len(ek["shards"]) == 2
    finally:
        eng.shutdown()


# ---------------------------------------------------------------------------
# namespace isolation: |tpN scopes blob layouts apart
# ---------------------------------------------------------------------------


def test_tp_namespace_isolation():
    cfg1, cfg2 = _tp_cfg(tp=1), _tp_cfg(tp=2)
    mc = cfg1.llama()
    n1 = kv_tier_namespace(cfg1, mc, "float32")
    n2 = kv_tier_namespace(cfg2, mc, "float32")
    n2b = kv_tier_namespace(_tp_cfg(tp=2), mc, "float32")
    n4 = kv_tier_namespace(_tp_cfg(tp=4), mc, "float32")
    assert n1 != n2 and n2 != n4, "tp layouts must not share a namespace"
    assert n2 == n2b, "equal configs must share a namespace"
    # and the live engines inherit it, so their CP index keys never match
    a = LLMEngine(cfg1, rng_seed=0)
    b = LLMEngine(cfg2, rng_seed=0)
    try:
        assert a._kv_tier.namespace == n1
        assert b._kv_tier.namespace == n2
        assert a._kv_tier.namespace != b._kv_tier.namespace
    finally:
        a.shutdown()
        b.shutdown()


# ---------------------------------------------------------------------------
# cluster: mid-stream failover resume over a sharded chain
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tp_cluster(ray_start_module):
    yield ray_start_module


def test_failover_resume_over_sharded_chain(tp_cluster):
    """PR 14's failover guarantee through the sharded KV plane: TP=2
    engine A eagerly spills a LIVE chain as per-shard payloads, TP=2
    engine B streams it back through the CP index + object plane
    (ChainStream plans ONCE per chain — the shard split is inside each
    chunk) and resumes token-identically to the single-chip baseline."""
    want = _want_tokens(LONG, 72)
    cfg = _tp_cfg(tp=2, prefix_cache_max_pages=0, max_tokens=8)
    a = LLMEngine(cfg, rng_seed=0)
    a.start()
    b = None
    try:
        rid = a.submit(LONG, max_tokens=72, temperature=0.0)
        assert _wait(lambda: len(
            (a.request_progress(rid) or {}).get("generated") or ()) >= 12,
            timeout=120.0)
        n = a.spill_inflight()
        assert n >= 6, f"expected prompt+generated pages spilled, got {n}"
        assert _wait(lambda: a.engine_stats()["spilled_pages"] >= 6)

        b = LLMEngine(cfg, rng_seed=0)
        b.start()
        k = 12
        rid_b = b.submit(LONG, resume_tokens=want[:k],
                         max_tokens=72 - k, temperature=0.0)
        out = b.result(rid_b, timeout=180.0)
        assert out["error"] is None, out
        assert out["tokens"] == want[k:], "sharded resumed decode diverged"
        st = b.engine_stats()
        assert st["failover_resumed"] == 1
        assert st["restored_pages"] >= 6
        assert st["restore_partial"] == 0
        assert b._kv_tier.counters["remote_hits"] >= 6
    finally:
        a.shutdown()
        if b is not None:
            b.shutdown()
