"""Node providers: how the autoscaler actually gets machines.

Reference: python/ray/autoscaler/node_provider.py (ABC) + per-cloud
implementations; the fake provider mirrors
autoscaler/_private/fake_multi_node/node_provider.py — "launching" a node
starts a real in-process NodeAgent, so autoscaler end-to-end tests run
without a cloud (SURVEY.md §4 keystone).
"""

from __future__ import annotations

from typing import Optional


class NodeProvider:
    """Launch/terminate worker nodes for one node type."""

    def create_node(self, node_config: dict) -> str:
        """Start a node; returns a provider-scoped node name."""
        raise NotImplementedError

    def terminate_node(self, name: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> list[str]:
        raise NotImplementedError


class FakeNodeProvider(NodeProvider):
    """Launches real in-process NodeAgents against a control plane.

    One provider node may be a MULTI-HOST TPU slice (``hosts`` in the node
    config): a single create_node brings up all of its host agents sharing a
    slice_name label — matching the cloud provider, where one TPU slice
    create yields every host VM at once (GCETPUNodeProvider ssh --worker=all).
    """

    def __init__(self, cp_addr: tuple[str, int], inproc_workers: bool = False):
        self._cp_addr = tuple(cp_addr)
        self._inproc = bool(inproc_workers)
        self._agents: dict[str, list] = {}  # name -> [NodeAgent, ...]
        self._counter = 0

    def create_node(self, node_config: dict) -> str:
        from ray_tpu.core.node_agent import NodeAgent

        self._counter += 1
        name = f"fake-{self._counter}"
        hosts = max(1, int(node_config.get("hosts", 1)))
        agents = []
        for i in range(hosts):
            labels = dict(node_config.get("labels") or {})
            labels["provider_node_name"] = name
            if hosts > 1:
                # slice identity: every host carries the slice name and its
                # worker index (what the real TPU metadata server provides)
                labels.setdefault("slice_name", name)
                labels["tpu_worker_id"] = str(i)
                labels.setdefault("topology", "")
            agents.append(NodeAgent(
                self._cp_addr,
                resources=dict(node_config.get("resources") or {}),
                labels=labels, inproc_workers=self._inproc))
        self._agents[name] = agents
        return name

    def terminate_node(self, name: str) -> None:
        for agent in self._agents.pop(name, []):
            try:
                agent.stop()
            except Exception:  # noqa: BLE001 - drain may have raced parts
                pass

    def non_terminated_nodes(self) -> list[str]:
        return list(self._agents)

    def agent(self, name: str):
        agents = self._agents.get(name)
        return agents[0] if agents else None

    def agents(self, name: str) -> list:
        return list(self._agents.get(name, []))


class GCETPUNodeProvider(NodeProvider):
    """GCE/GKE TPU slice provider (the cloud target for this framework —
    reference: autoscaler/gcp/ + TPU pod scheduling). Shells out to
    `gcloud compute tpus tpu-vm` so no SDK dependency is needed; requires
    credentials + network, so everything is lazy and failures are explicit.
    """

    def __init__(self, project: str, zone: str, cluster_address: str,
                 accelerator_type: str = "v5litepod-8",
                 runtime_version: str = "tpu-ubuntu2204-base"):
        self.project = project
        self.zone = zone
        self.cluster_address = cluster_address
        self.accelerator_type = accelerator_type
        self.runtime_version = runtime_version
        self._nodes: set[str] = set()
        self._counter = 0

    def _gcloud(self, *args: str) -> str:
        import subprocess
        out = subprocess.run(
            ["gcloud", "compute", "tpus", "tpu-vm", *args,
             f"--project={self.project}", f"--zone={self.zone}",
             "--format=json"],
            capture_output=True, text=True, timeout=600)
        if out.returncode != 0:
            raise RuntimeError(f"gcloud failed: {out.stderr[-500:]}")
        return out.stdout

    def create_node(self, node_config: dict) -> str:
        self._counter += 1
        name = node_config.get("name") or f"ray-tpu-node-{self._counter}"
        accel = node_config.get("accelerator_type", self.accelerator_type)
        self._gcloud(
            "create", name, f"--accelerator-type={accel}",
            f"--version={node_config.get('runtime_version', self.runtime_version)}")
        # bootstrap: every TPU VM host joins as a worker node, labelled with
        # the provider node name so the autoscaler can match CP nodes back
        # to cloud instances for idle scale-down
        self._gcloud(
            "ssh", name, "--worker=all", "--command",
            f"python -m ray_tpu start --address {self.cluster_address} "
            f"--labels provider_node_name={name}")
        self._nodes.add(name)
        return name

    def terminate_node(self, name: str) -> None:
        self._gcloud("delete", name, "--quiet")
        self._nodes.discard(name)

    def non_terminated_nodes(self) -> list[str]:
        return sorted(self._nodes)
