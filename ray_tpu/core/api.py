"""Top-level API: init / shutdown / remote / get / put / wait / kill / cancel.

TPU-native analog of the reference's public surface
(/root/reference/python/ray/_private/worker.py — init:1422, shutdown:2067,
get:2815, connect:2444) and the driver bootstrap
(python/ray/_private/node.py:1340 start_head_processes). Head mode hosts the
control plane and a node agent in-process (threads); worker processes are real
subprocesses, so distributed semantics (ownership, borrows, worker death) are
exercised even on one host.
"""

from __future__ import annotations

import atexit
import os
import threading
from typing import Any, Sequence

from ray_tpu.core.config import get_config, reset_config
from ray_tpu.core.ids import JobID
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.remote_function import RemoteFunction
from ray_tpu.core.actor import ActorClass, ActorHandle
from ray_tpu.exceptions import RayTpuError

_lock = threading.RLock()
_runtime = None
_head = None  # (control_plane, node_agent) when we started them
# Per-thread runtime override: in-process workers (the fake_multi_node-style
# scale/autoscaler harness) host several WorkerRuntimes in ONE process, so
# task-executing threads bind "their" runtime here; everything else falls
# through to the process-global one (subprocess workers bind the same object
# the global already holds — a no-op).
_thread_runtime = threading.local()


def _get_runtime():
    rt = getattr(_thread_runtime, "rt", None) or _runtime
    if rt is None:
        raise RayTpuError("ray_tpu.init() has not been called")
    return rt


def _try_get_runtime():
    return getattr(_thread_runtime, "rt", None) or _runtime


def _bind_thread_runtime(rt):
    """Bind the calling thread's API surface to ``rt`` (executor threads of
    in-process workers call this at task entry)."""
    _thread_runtime.rt = rt


def _set_runtime(rt):
    global _runtime
    _runtime = rt


def is_initialized() -> bool:
    return _runtime is not None


def init(address: str | None = None, *, num_cpus: float | None = None,  # graftlint: disable=lock-discipline — the init RLock exists to serialize whole init/shutdown lifecycles, blocking RPCs included
         resources: dict | None = None, labels: dict | None = None,
         object_store_memory: int | None = None,
         _system_config: dict | None = None, log_to_driver: bool = True,
         job_name: str = "") -> "RuntimeContext":
    """Start (head mode) or connect to (address=...) a cluster."""
    global _runtime, _head
    if address is None:
        # job drivers launched by `ray-tpu submit` / the job supervisor get
        # the cluster address through the environment (reference:
        # RAY_ADDRESS)
        address = os.environ.get("RAY_TPU_ADDRESS") or None
    with _lock:
        if _runtime is not None:
            return RuntimeContext(_runtime)
        reset_config()
        cfg = get_config()
        cfg.apply(_system_config)
        if not log_to_driver:
            cfg.log_to_driver = False
        if _system_config:
            # propagate to spawned worker processes
            os.environ.update(cfg.to_env(_system_config))

        if address and address.startswith(("ray_tpu://", "ray://")):
            # remote-driver (client) mode: no shared memory with the cluster;
            # everything proxies through a ClientServer-hosted driver
            # (ref: util/client/ ray:// mode, client_mode_hook.py)
            from ray_tpu.client.client import ClientRuntime
            rt = ClientRuntime(address.split("://", 1)[1])
            _runtime = rt
            atexit.register(_atexit_shutdown)
            return RuntimeContext(rt)

        from ray_tpu.core.worker import WorkerRuntime

        job_id = JobID.from_random()
        if address is None:
            from ray_tpu.core.control_plane import ControlPlane
            from ray_tpu.core.node_agent import NodeAgent
            res = dict(resources or {})
            if num_cpus is not None:
                res["CPU"] = float(num_cpus)
            elif "CPU" not in res:
                res["CPU"] = float(os.cpu_count() or 1)
            cp = ControlPlane()
            agent = NodeAgent(cp.addr, resources=res, labels=labels,
                              object_store_memory=object_store_memory)
            _head = (cp, agent)
            cp_addr, agent_addr, node_id = cp.addr, agent.addr, agent.node_id
        else:
            host, port = address.rsplit(":", 1)
            cp_addr = (host, int(port))
            # adopt the first alive node's agent for local store access
            from ray_tpu.core.rpc import RpcClient
            probe = RpcClient(cp_addr, name="probe")
            nodes = probe.call_with_retry("get_nodes", None, timeout=30.0)
            probe.close()
            alive = [n for n in nodes if n["alive"]]
            if not alive:
                raise RayTpuError(f"no alive nodes in cluster at {address}")
            agent_addr, node_id = tuple(alive[0]["addr"]), alive[0]["node_id"]

        rt = WorkerRuntime(mode="driver", cp_addr=cp_addr, agent_addr=agent_addr,
                           job_id=job_id, node_id=node_id)
        rt.cp_client.call_with_retry(
            "register_job", {"job_id": job_id, "addr": rt.addr}, timeout=30.0)
        _runtime = rt
        atexit.register(_atexit_shutdown)
        return RuntimeContext(rt)


def _atexit_shutdown():
    try:
        shutdown()
    except Exception:
        pass


_shutdown_hooks: list = []


def register_shutdown_hook(fn) -> None:
    """Run `fn()` at the start of shutdown(), before the runtime is torn
    down. Used by libraries (e.g. the data streaming executor) to stop
    background threads that hold runtime handles, so a later init() in the
    same process doesn't race leaked threads from the previous cluster."""
    if fn not in _shutdown_hooks:
        _shutdown_hooks.append(fn)


def shutdown():  # graftlint: disable=lock-discipline — same lifecycle lock as init(); see above
    """(ref: worker.py:2067)"""
    global _runtime, _head
    for hook in list(_shutdown_hooks):
        try:
            hook()
        except Exception:
            pass
    with _lock:
        rt, _runtime = _runtime, None
        head, _head = _head, None
        if rt is not None:
            try:
                rt.cp_client.call("finish_job", {"job_id": rt.job_id}, timeout=5.0)
            except Exception:
                pass
            rt.shutdown()
        if head is not None:
            cp, agent = head
            agent.stop()
            cp.stop()


def remote(*args, **options):
    """Decorator: @remote or @remote(num_cpus=..., num_tpus=..., ...)
    (ref: worker.py remote / remote_function.py:41 / actor.py:1181)."""
    def decorate(obj):
        if isinstance(obj, type):
            return ActorClass(obj, **options)
        return RemoteFunction(obj, **options)

    if len(args) == 1 and callable(args[0]) and not options:
        return decorate(args[0])
    if args:
        raise TypeError("@remote takes keyword options only")
    return decorate


def get(refs, timeout: float | None = None) -> Any:
    rt = _get_runtime()
    if isinstance(refs, ObjectRef):
        return rt.get([refs], timeout=timeout)[0]
    if not isinstance(refs, (list, tuple)):
        raise TypeError(f"get() expects an ObjectRef or list, got {type(refs)}")
    return rt.get(list(refs), timeout=timeout)


def put(value: Any) -> ObjectRef:
    if isinstance(value, ObjectRef):
        raise TypeError("put() of an ObjectRef is not allowed")
    return _get_runtime().put(value)


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: float | None = None):
    if isinstance(refs, ObjectRef):
        raise TypeError("wait() expects a list of ObjectRefs")
    refs = list(refs)
    if len(set(refs)) != len(refs):
        raise ValueError("wait() expects unique ObjectRefs")
    num_returns = min(num_returns, len(refs))
    return _get_runtime().wait(refs, num_returns=num_returns, timeout=timeout)


def kill(actor: ActorHandle, *, no_restart: bool = True):
    rt = _get_runtime()
    rt.cp_client.call_with_retry(
        "kill_actor", {"actor_id": actor.actor_id, "no_restart": no_restart},
        timeout=30.0)


def cancel(ref: ObjectRef, *, force: bool = False):
    rt = _get_runtime()
    spec = rt.task_manager.get_pending_spec(ref.id().task_id())
    if spec is None:
        return
    # best effort: mark cancelled at the executor side isn't addressable until
    # leased; record locally so queued execution fails fast
    from ray_tpu.exceptions import TaskCancelledError, TaskError
    rt.fail_task(spec, TaskError(TaskCancelledError(), task_repr=spec.repr_name()))


def get_actor(name: str, timeout: float = 10.0) -> ActorHandle:
    """(ref: worker.py get_actor — named actors)"""
    rt = _get_runtime()
    with rt.yield_exec_slot():
        reply = rt.cp_client.call_with_retry(
            "get_actor_by_name", {"name": name, "timeout": timeout},
            timeout=timeout + 10)
    if reply is None:
        raise ValueError(f"no actor named {name!r}")
    return ActorHandle(reply["actor_id"], reply["spec"].name,
                       max_task_retries=reply["spec"].max_task_retries)


def exit_actor():
    """Terminate the current actor after the running call returns
    (ref: ray.actor.exit_actor)."""
    rt = _get_runtime()
    if not rt.in_actor():
        raise RuntimeError("exit_actor() called outside an actor")
    rt.request_exit_actor()


class RuntimeContext:
    """(ref: python/ray/runtime_context.py)"""

    def __init__(self, rt):
        self._rt = rt

    @property
    def job_id(self):
        return self._rt.job_id

    @property
    def node_id(self):
        return self._rt.node_id

    @property
    def worker_id(self):
        return self._rt.worker_id

    @property
    def current_actor_id(self):
        return self._rt._actor_state.actor_id

    @property
    def was_current_actor_reconstructed(self) -> bool:
        return False

    def get_task_id(self):
        return self._rt.current_task_id()

    @property
    def control_plane_address(self) -> str:
        return f"{self._rt.cp_addr[0]}:{self._rt.cp_addr[1]}"


def get_runtime_context() -> RuntimeContext:
    return RuntimeContext(_get_runtime())


def get_actor_event_loop():
    """The asyncio event loop of the CURRENT async actor, or None when the
    calling code is not hosted on an async actor. Lets sync actor methods
    drive the actor's coroutines/async generators
    (asyncio.run_coroutine_threadsafe) without reaching into runtime
    internals."""
    rt = _try_get_runtime()
    if rt is None:
        return None
    state = getattr(rt, "_actor_state", None)
    return getattr(state, "loop", None)


def cluster_resources() -> dict:
    rt = _get_runtime()
    nodes = rt.cp_client.call_with_retry("get_nodes", None, timeout=10.0)
    total: dict[str, float] = {}
    for n in nodes:
        if n["alive"]:
            for k, v in n["resources"].items():
                total[k] = total.get(k, 0.0) + v
    return total


def available_resources() -> dict:
    rt = _get_runtime()
    nodes = rt.cp_client.call_with_retry("get_nodes", None, timeout=10.0)
    total: dict[str, float] = {}
    for n in nodes:
        if n["alive"]:
            for k, v in n["available"].items():
                total[k] = total.get(k, 0.0) + v
    return total


def nodes() -> list[dict]:
    rt = _get_runtime()
    return rt.cp_client.call_with_retry("get_nodes", None, timeout=10.0)
