"""ray_tpu.tune — hyperparameter tuning (reference: python/ray/tune/)."""

from ray_tpu.train.context import get_context, report
from ray_tpu.tune.schedulers import (
    ASHAScheduler,
    AsyncHyperBandScheduler,
    FIFOScheduler,
    HyperBandForBOHB,
    MedianStoppingRule,
    PB2,
    PopulationBasedTraining,
    TrialScheduler,
)
from ray_tpu.tune.search import (
    BOHBSearcher,
    ConcurrencyLimiter,
    OptunaSearch,
    RandomSearcher,
    Searcher,
    TPESearcher,
    create_bohb,
    choice,
    grid_search,
    loguniform,
    randint,
    sample_from,
    uniform,
)
from ray_tpu.tune.tuner import (
    ResultGrid,
    Trial,
    TrialResult,
    TuneConfig,
    TuneController,
    Tuner,
)

__all__ = [
    "ASHAScheduler", "AsyncHyperBandScheduler", "BOHBSearcher",
    "ConcurrencyLimiter", "FIFOScheduler", "HyperBandForBOHB",
    "OptunaSearch", "PB2", "RandomSearcher", "Searcher", "TPESearcher",
    "create_bohb",
    "MedianStoppingRule", "PopulationBasedTraining", "ResultGrid", "Trial",
    "TrialResult", "TrialScheduler", "TuneConfig", "TuneController", "Tuner",
    "choice", "get_context", "grid_search", "loguniform", "randint", "report",
    "sample_from", "uniform",
]
