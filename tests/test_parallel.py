"""Parallelism layer tests on the 8-device virtual CPU mesh.

Covers what the reference delegates or lacks (SURVEY.md §2.3, §5.7): ring/
Ulysses context parallelism, GPipe pipeline (fwd+grad), MoE expert parallel,
FSDP sharding inference, mesh construction.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.parallel.expert import moe_layer, moe_layer_tokens_sharded, top_k_gating
from ray_tpu.parallel.mesh import AXIS_ORDER, MeshSpec, build_mesh, validate_spec_for_slice
from ray_tpu.parallel.pipeline import pipeline_apply, stack_stage_params
from ray_tpu.parallel.ring_attention import ring_attention, ulysses_attention
from ray_tpu.parallel.sharding import (
    batch_sharding,
    infer_fsdp_sharding,
    logical_to_shardings,
    num_dp_shards,
)


def dense_attention(q, k, v, causal=True):
    T = q.shape[1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (q.shape[-1] ** -0.5)
    if causal:
        mask = jnp.tril(jnp.ones((T, T), bool))
        logits = jnp.where(mask[None, None], logits, -jnp.inf)
    p = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


@pytest.fixture(scope="module")
def qkv():
    B, T, H, D = 2, 64, 8, 16
    ks = jax.random.split(jax.random.key(0), 3)
    return tuple(jax.random.normal(k, (B, T, H, D), jnp.float32) for k in ks)


def test_mesh_spec_infer():
    spec = MeshSpec.infer(8, tensor=2)
    assert spec.tensor == 2 and spec.fsdp == 4 and spec.total_devices() == 8
    spec2 = MeshSpec.infer(8, tensor=2, fsdp=2)
    assert spec2.data == 2
    with pytest.raises(ValueError):
        MeshSpec.infer(8, tensor=3)


def test_build_mesh_axes(jax_cpu_mesh):
    mesh = build_mesh(MeshSpec(fsdp=4, tensor=2))
    assert mesh.axis_names == AXIS_ORDER
    assert mesh.shape["fsdp"] == 4 and mesh.shape["tensor"] == 2


def test_validate_spec_for_slice():
    validate_spec_for_slice(MeshSpec(data=4, tensor=8), ici_devices=8)
    with pytest.raises(ValueError):
        validate_spec_for_slice(MeshSpec(tensor=16), ici_devices=8)


def test_ring_attention_matches_dense(qkv):
    q, k, v = qkv
    mesh = build_mesh(MeshSpec(context=8))
    ref = dense_attention(q, k, v, causal=True)
    out = ring_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(out, ref, atol=2e-5)
    ref_nc = dense_attention(q, k, v, causal=False)
    out_nc = ring_attention(q, k, v, mesh, causal=False)
    np.testing.assert_allclose(out_nc, ref_nc, atol=2e-5)


def test_ring_attention_grads(qkv):
    q, k, v = qkv
    mesh = build_mesh(MeshSpec(context=8))

    def loss_ring(q, k, v):
        return jnp.mean(ring_attention(q, k, v, mesh) ** 2)

    def loss_dense(q, k, v):
        return jnp.mean(dense_attention(q, k, v) ** 2)

    g1 = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(a, b, atol=2e-5)


def test_ulysses_attention_matches_dense(qkv):
    q, k, v = qkv
    mesh = build_mesh(MeshSpec(context=8))
    ref = dense_attention(q, k, v, causal=True)
    out = ulysses_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(out, ref, atol=2e-5)


def test_pipeline_forward_and_grad():
    mesh = build_mesh(MeshSpec(pipeline=4), jax.devices()[:4])
    D = 8

    def init(r, i):
        return {"w": jax.random.normal(r, (D, D)) * 0.3}

    params = stack_stage_params(init, 4, jax.random.key(1))

    def stage_fn(p, x):
        return jnp.tanh(x @ p["w"])

    x = jax.random.normal(jax.random.key(2), (16, D))
    out = pipeline_apply(stage_fn, params, x, mesh, num_microbatches=8)
    ref = x
    for s in range(4):
        ref = jnp.tanh(ref @ params["w"][s])
    np.testing.assert_allclose(out, ref, atol=1e-6)

    def loss_pp(params):
        return jnp.mean(pipeline_apply(stage_fn, params, x, mesh,
                                       num_microbatches=8) ** 2)

    def loss_seq(params):
        r = x
        for s in range(4):
            r = jnp.tanh(r @ params["w"][s])
        return jnp.mean(r ** 2)

    g1 = jax.grad(loss_pp)(params)["w"]
    g2 = jax.grad(loss_seq)(params)["w"]
    np.testing.assert_allclose(g1, g2, atol=1e-6)


def _moe_fixture():
    E, D = 8, 16
    ep = {"w1": jax.random.normal(jax.random.key(3), (E, D, 32)) * 0.3,
          "w2": jax.random.normal(jax.random.key(4), (E, 32, D)) * 0.3}
    gate_w = jax.random.normal(jax.random.key(5), (D, E)) * 0.3

    def expert_fn(p, tok):
        return jax.nn.relu(tok @ p["w1"]) @ p["w2"]

    x = jax.random.normal(jax.random.key(6), (8, 32, D))

    def dense_ref(x):
        toks = x.reshape(-1, D)
        probs, idx = top_k_gating(toks @ gate_w, 2)
        ref = jnp.zeros_like(toks)
        for slot in range(2):
            for e in range(E):
                m = idx[:, slot] == e
                one = {"w1": ep["w1"][e], "w2": ep["w2"][e]}
                ref = ref + jnp.where(m[:, None],
                                      probs[:, slot][:, None] * expert_fn(one, toks),
                                      0.0)
        return ref.reshape(x.shape)

    return E, ep, gate_w, expert_fn, x, dense_ref(x)


def test_moe_expert_parallel():
    E, ep, gate_w, expert_fn, x, ref = _moe_fixture()
    mesh = build_mesh(MeshSpec(expert=8))
    out = moe_layer(x, gate_w, expert_fn, ep, mesh, num_experts=E,
                    capacity_factor=8.0)
    np.testing.assert_allclose(out, ref, atol=1e-5)


def test_moe_tokens_sharded():
    E, ep, gate_w, expert_fn, x, ref = _moe_fixture()
    mesh = build_mesh(MeshSpec(expert=8))
    out = moe_layer_tokens_sharded(x, gate_w, expert_fn, ep, mesh,
                                   num_experts=E, capacity_factor=8.0)
    np.testing.assert_allclose(out, ref, atol=1e-5)


def test_infer_fsdp_sharding():
    mesh = build_mesh(MeshSpec(fsdp=8))
    shapes = {
        "big": jax.ShapeDtypeStruct((128, 64), jnp.float32),
        "odd": jax.ShapeDtypeStruct((7, 5), jnp.float32),
        "scalar": jax.ShapeDtypeStruct((), jnp.float32),
    }
    sh = infer_fsdp_sharding(shapes, mesh)
    assert sh["big"].spec == jax.sharding.PartitionSpec("fsdp")
    assert sh["odd"].spec == jax.sharding.PartitionSpec()
    assert sh["scalar"].spec == jax.sharding.PartitionSpec()


def test_sharded_matmul_runs_on_mesh():
    """End-to-end: params FSDP-sharded, batch data-sharded, jit runs."""
    mesh = build_mesh(MeshSpec(data=2, fsdp=4))
    w = jnp.ones((64, 32))
    x = jnp.ones((16, 64))
    w_sh = jax.device_put(w, infer_fsdp_sharding(
        jax.ShapeDtypeStruct(w.shape, w.dtype), mesh))
    x_sh = jax.device_put(x, batch_sharding(mesh, extra_dims=1))

    @jax.jit
    def f(w, x):
        return x @ w

    out = f(w_sh, x_sh)
    assert out.shape == (16, 32)
    np.testing.assert_allclose(np.asarray(out), np.full((16, 32), 64.0))
    assert num_dp_shards(mesh) == 8


def test_logical_rules():
    mesh = build_mesh(MeshSpec(fsdp=4, tensor=2))
    tree = {"wq": ("embed", "heads"), "bias": (None,)}
    sh = logical_to_shardings(tree, mesh)
    assert sh["wq"].spec == jax.sharding.PartitionSpec("fsdp", "tensor")
    assert sh["bias"].spec == jax.sharding.PartitionSpec()


def test_chunked_cross_entropy_matches_full():
    """Every chunk size (including non-divisors of T-1 — the padded-tail
    path) must reproduce the unchunked loss."""
    import numpy as np

    from ray_tpu.models import llama

    cfg = llama.llama_tiny()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 64)), jnp.int32)
    inputs, targets = tokens[:, :-1], tokens[:, 1:]  # T-1 = 63
    hidden = llama.hidden_states(params, inputs, cfg)
    logits = (hidden @ params["lm_head"]).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    full = -jnp.mean(
        jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0])
    grad_ref = None
    for chunk in (16, 63, 200):
        for remat in (True, False):
            c = llama.chunked_cross_entropy(
                params["lm_head"], hidden, targets, chunk=chunk, remat=remat)
            assert abs(float(c - full)) < 1e-4, (chunk, remat)
            # both remat modes must produce identical lm_head gradients
            # (remat only changes WHEN logits exist, never the math)
            g = jax.grad(lambda w: llama.chunked_cross_entropy(
                w, hidden, targets, chunk=chunk, remat=remat))(
                params["lm_head"])
            if grad_ref is None:
                grad_ref = g
            else:
                assert jnp.allclose(g, grad_ref, atol=1e-5), (chunk, remat)


def test_default_optimizer_names():
    from ray_tpu.train import spmd

    spmd.default_optimizer(name="adamw")
    spmd.default_optimizer(name="adafactor")
    with pytest.raises(ValueError):
        spmd.default_optimizer(name="lion")


def test_dryrun_collective_accounting(jax_cpu_mesh):
    """Per-axis collective accounting (VERDICT r3 item 9): each parallelism
    axis must insert its signature collective into the compiled HLO —
    tp: all-reduce; sp(context ring) and pp: collective-permute — and the
    accounting helper must see them."""
    import os
    import sys as _sys
    sys_path_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if sys_path_root not in _sys.path:
        _sys.path.insert(0, sys_path_root)
    import importlib
    graft = importlib.import_module("__graft_entry__")

    import jax
    import jax.numpy as jnp
    import numpy as np
    from ray_tpu.models import llama
    from ray_tpu.train import spmd

    # tp=2 x sp=2 x dp=2 llama train step
    mesh = build_mesh(MeshSpec(data=2, tensor=2, context=2))
    cfg = llama.llama_tiny(n_heads=4, n_kv_heads=2, attn_impl="ring")
    opt = spmd.default_optimizer(warmup_steps=1, decay_steps=10)
    state, sh = spmd.sharded_create_state(
        lambda: llama.init_params(jax.random.PRNGKey(0), cfg), opt, mesh,
        params_logical_axes=llama.logical_axes(cfg))
    step = spmd.make_train_step(
        lambda p, b: llama.loss_fn(p, b, cfg, mesh), opt, mesh, sh)
    tokens = jnp.asarray(np.zeros((2, 33), np.int32))
    batch = spmd.shard_batch({"tokens": tokens}, mesh)
    hlo = step.lower(state, batch).compile().as_text()
    counts = graft.collective_counts(hlo)
    assert counts.get("all-reduce", 0) > 0, counts          # tp + dp grads
    assert counts.get("collective-permute", 0) > 0, counts  # sp ring

    # pp=2 pipeline: ppermute ring between stages
    from ray_tpu.parallel.pipeline import pipeline_apply
    mesh_p = build_mesh(MeshSpec(data=4, pipeline=2))
    from jax.sharding import NamedSharding, PartitionSpec as P
    params = jax.device_put(
        {"w": jnp.zeros((2, 8, 8)), "b": jnp.zeros((2, 8))},
        NamedSharding(mesh_p, P("pipeline")))
    x = jnp.zeros((8, 8))

    def pp_fn(params, x):
        return pipeline_apply(lambda p, h: jnp.tanh(h @ p["w"] + p["b"]),
                              params, x, mesh_p, num_microbatches=4).sum()

    hlo_p = jax.jit(pp_fn).lower(params, x).compile().as_text()
    counts_p = graft.collective_counts(hlo_p)
    assert counts_p.get("collective-permute", 0) > 0, counts_p


def test_int8_matmul_close_and_differentiable():
    """int8_matmul (dynamic-quant MXU path, BENCH_NOTES r4): forward close
    to the fp matmul at int8 precision; gradients flow (straight-through)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from ray_tpu.models.llama import int8_matmul

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((32, 64)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((64, 48)), jnp.float32)
    out = int8_matmul(x, w)
    ref = x @ w
    # per-tensor int8: ~1% relative error at these magnitudes
    err = float(jnp.abs(out - ref).max() / jnp.abs(ref).max())
    assert err < 0.05, err

    def loss(x, w):
        return (int8_matmul(x, w) ** 2).mean()

    gx, gw = jax.grad(loss, argnums=(0, 1))(x, w)
    assert float(jnp.abs(gx).max()) > 0 and float(jnp.abs(gw).max()) > 0
    # straight-through backward matches the fp backward at quant precision
    gx_ref, gw_ref = jax.grad(lambda x, w: ((x @ w) ** 2).mean(),
                              argnums=(0, 1))(x, w)
    assert float(jnp.abs(gx - gx_ref).max() / jnp.abs(gx_ref).max()) < 0.1


# ---- partition-rule machinery (ISSUE 20: shared by train + serve) ------


def test_match_partition_rules_first_match_wins_and_scalars():
    from jax.sharding import PartitionSpec as P

    from ray_tpu.parallel.sharding import match_partition_rules

    params = {
        "layers": {"attn": {"wq": jnp.zeros((2, 8, 4, 2)),
                            "wo": jnp.zeros((2, 4, 2, 8))},
                   "mlp": {"w_up": jnp.zeros((2, 8, 16))}},
        "scale": jnp.zeros(()),          # scalar -> P() without any rule
        "final_norm": jnp.zeros((8,)),
    }
    rules = (
        (r"attn/wq$", P(None, None, "tensor", None)),
        # tuple specs are accepted and coerced to PartitionSpec
        (r"attn/", (None, "tensor", None, None)),
        (r".*", P()),
    )
    specs = match_partition_rules(rules, params)
    # first match wins: wq hits its dedicated rule, not the attn/ catch
    assert specs["layers"]["attn"]["wq"] == P(None, None, "tensor", None)
    assert specs["layers"]["attn"]["wo"] == P(None, "tensor", None, None)
    assert specs["layers"]["mlp"]["w_up"] == P()
    assert specs["scale"] == P()
    assert specs["final_norm"] == P()


def test_match_partition_rules_unmatched_raises():
    from ray_tpu.parallel.sharding import match_partition_rules

    with pytest.raises(ValueError, match="layers/mystery"):
        match_partition_rules(
            ((r"attn", jax.sharding.PartitionSpec()),),
            {"layers": {"mystery": jnp.zeros((4, 4))}})


def test_prune_spec_drops_dead_mesh_axes(jax_cpu_mesh):
    from jax.sharding import PartitionSpec as P

    from ray_tpu.parallel.sharding import prune_spec

    mesh = build_mesh(MeshSpec(fsdp=4, tensor=2))
    # present axes survive, absent names and size-1 axes drop, trailing
    # Nones are trimmed
    assert prune_spec(P("tensor", None, "fsdp"), mesh) == \
        P("tensor", None, "fsdp")
    assert prune_spec(P("tensor", "data"), mesh) == P("tensor")
    assert prune_spec(P(None, "data", None), mesh) == P()


def test_rule_shardings_places_params(jax_cpu_mesh):
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from ray_tpu.parallel.sharding import rule_shardings

    mesh = build_mesh(MeshSpec(tensor=2))
    params = {"layers": {"attn": {"wq": jnp.zeros((2, 8, 4, 2))}},
              "final_norm": jnp.zeros((8,))}
    rules = ((r"attn/wq$", P(None, None, "tensor", None)), (r".*", P()))
    sh = rule_shardings(rules, params, mesh)
    assert isinstance(sh["layers"]["attn"]["wq"], NamedSharding)
    placed = jax.device_put(params, sh)
    wq = placed["layers"]["attn"]["wq"]
    # the tensor axis really splits: each shard holds half the q heads
    assert wq.sharding.shard_shape(wq.shape) == (2, 8, 2, 2)
    assert placed["final_norm"].sharding.shard_shape((8,)) == (8,)


def test_serve_and_train_share_rule_machinery():
    """train/spmd.py's partition_rules path and the serve engine's TP
    rules both resolve through parallel.sharding.match_partition_rules —
    one implementation (ISSUE 20 satellite), no serve-side fork."""
    import inspect

    from ray_tpu.parallel import sharding as shd
    from ray_tpu.serve.llm.engine import LLMEngine
    from ray_tpu.train import spmd

    src = inspect.getsource(spmd.state_shardings)
    assert "rule_shardings" in src
    eng_src = inspect.getsource(LLMEngine._setup_tp_mesh)
    assert "rule_shardings" in eng_src
    # and the serve rules themselves are resolvable by the shared matcher
    from ray_tpu.models.llama import init_params, llama_tiny
    params = init_params(jax.random.PRNGKey(0), llama_tiny())
    specs = shd.match_partition_rules(LLMEngine.tp_partition_rules(),
                                      params)
    flat = jax.tree_util.tree_leaves(
        specs, is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec))
    assert all(isinstance(s, jax.sharding.PartitionSpec) for s in flat)
