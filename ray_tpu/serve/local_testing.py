"""Local testing mode: run a serve application IN-PROCESS, no cluster.

TPU-native analog of the reference's local testing mode
(python/ray/serve/_private/local_testing_mode.py): `serve.run(app,
_local_testing_mode=True)` constructs every deployment instance directly
in the caller's process and returns handles whose `.remote()` runs the
method on a thread pool — the full handle surface (options/method
attributes/response futures/composition) with zero cluster, for unit
tests and notebooks.
"""

from __future__ import annotations

import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Optional


class LocalDeploymentResponse:
    """Future-shaped response matching DeploymentResponse.result()."""

    def __init__(self, fut: Future):
        self._fut = fut

    def result(self, timeout_s: Optional[float] = None) -> Any:
        return self._fut.result(timeout=timeout_s)

    @property
    def ref(self):
        return self._fut


class LocalDeploymentHandle:
    """In-process DeploymentHandle: same call surface, direct dispatch."""

    def __init__(self, instance, pool: ThreadPoolExecutor,
                 method_name: str = "__call__"):
        self._instance = instance
        self._pool = pool
        self._method = method_name

    def options(self, *, method_name: Optional[str] = None,
                **_ignored) -> "LocalDeploymentHandle":
        return LocalDeploymentHandle(
            self._instance, self._pool,
            method_name if method_name is not None else self._method)

    def __getattr__(self, name: str) -> "LocalDeploymentHandle":
        if name.startswith("_"):
            raise AttributeError(name)
        return self.options(method_name=name)

    def remote(self, *args, **kwargs) -> LocalDeploymentResponse:
        def resolve(v):
            if isinstance(v, LocalDeploymentResponse):
                return v.result()
            return v

        def call():
            a = tuple(resolve(x) for x in args)
            kw = {k: resolve(v) for k, v in kwargs.items()}
            target = self._instance
            if self._method != "__call__" or not callable(target):
                target = getattr(target, self._method)
            return target(*a, **kw)

        return LocalDeploymentResponse(self._pool.submit(call))


def run_local(app, app_name: str = "default") -> LocalDeploymentHandle:
    """Build every deployment of the application in-process (topological
    order, bound sub-apps become LocalDeploymentHandles) and return the
    ingress handle."""
    ordered: list = []
    app._collect(ordered, set())
    ingress = ordered[-1]
    pool = ThreadPoolExecutor(max_workers=8,
                              thread_name_prefix="serve-local")
    built: dict[int, LocalDeploymentHandle] = {}
    for node in ordered:
        def conv(v):
            if id(v) in built:
                return built[id(v)]
            return v
        args = tuple(conv(a) for a in node.init_args)
        kwargs = {k: conv(v) for k, v in node.init_kwargs.items()}
        obj = node.deployment.func_or_class
        instance = obj(*args, **kwargs) if isinstance(obj, type) else obj
        built[id(node)] = LocalDeploymentHandle(instance, pool)
    return built[id(ingress)]
