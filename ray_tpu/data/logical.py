"""Logical plan + rule-based optimizer.

TPU-native analog of the reference's logical layer
(/root/reference/python/ray/data/_internal/logical/ — logical operators,
optimizers.py, rules/operator_fusion). The plan is a linear-ish DAG of
logical ops; optimization fuses adjacent row/batch transforms into a single
physical map stage (so one object-store round trip per block per fused
chain, the dominant cost in the reference too).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

from ray_tpu.data.datasource import Datasource


@dataclasses.dataclass
class LogicalOp:
    name: str
    inputs: list["LogicalOp"] = dataclasses.field(default_factory=list)

    def __str__(self):
        return self.name


@dataclasses.dataclass
class Read(LogicalOp):
    datasource: Optional[Datasource] = None
    parallelism: int = -1

    def __post_init__(self):
        self.name = f"Read{self.datasource.name if self.datasource else ''}"


@dataclasses.dataclass
class InputData(LogicalOp):
    """Pre-materialized block refs (from_blocks / materialized datasets)."""
    bundles: list = dataclasses.field(default_factory=list)  # [(ref, meta)]


@dataclasses.dataclass
class AbstractMap(LogicalOp):
    fn: Optional[Callable] = None
    fn_args: tuple = ()
    fn_kwargs: dict = dataclasses.field(default_factory=dict)
    # "rows" | "batches" | "flat" | "filter"
    mode: str = "batches"
    batch_size: Optional[int] = None
    batch_format: str = "numpy"
    compute: str = "tasks"            # "tasks" | "actors"
    num_actors: int = 2
    resources: dict = dataclasses.field(default_factory=dict)
    fn_constructor_args: tuple = ()


@dataclasses.dataclass
class MapBatches(AbstractMap):
    mode: str = "batches"


@dataclasses.dataclass
class MapRows(AbstractMap):
    mode: str = "rows"


@dataclasses.dataclass
class FlatMap(AbstractMap):
    mode: str = "flat"


@dataclasses.dataclass
class Filter(AbstractMap):
    mode: str = "filter"


@dataclasses.dataclass
class Limit(LogicalOp):
    limit: int = 0


@dataclasses.dataclass
class Repartition(LogicalOp):
    num_blocks: int = 1
    # hash-partition on this column instead of round-robin (reference:
    # _internal/execution/operators/hash_shuffle.py)
    key: Optional[str] = None


@dataclasses.dataclass
class RandomShuffle(LogicalOp):
    seed: Optional[int] = None


@dataclasses.dataclass
class Sort(LogicalOp):
    key: str = ""
    descending: bool = False


@dataclasses.dataclass
class Aggregate(LogicalOp):
    key: Optional[str] = None
    aggs: list = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class Join(LogicalOp):
    """Hash join of two datasets (reference: _internal/logical/operators/
    join_operator.py + execution/operators/join.py)."""
    on: str = ""
    right_on: Optional[str] = None
    how: str = "inner"  # inner | left outer | right outer | full outer
    num_partitions: int = 0


@dataclasses.dataclass
class Union(LogicalOp):
    pass


@dataclasses.dataclass
class Zip(LogicalOp):
    pass


@dataclasses.dataclass
class Write(LogicalOp):
    path: str = ""
    file_format: str = "parquet"


class LogicalPlan:
    def __init__(self, terminal: LogicalOp):
        self.terminal = terminal

    def ops(self) -> list[LogicalOp]:
        """Post-order (inputs before consumers)."""
        seen: list[LogicalOp] = []

        def visit(op):
            for i in op.inputs:
                visit(i)
            if op not in seen:
                seen.append(op)

        visit(self.terminal)
        return seen

    def __str__(self):
        return " -> ".join(str(o) for o in self.ops())


# ---- optimizer -----------------------------------------------------------


def _fusable(a: LogicalOp, b: LogicalOp) -> bool:
    """Can b be fused onto a? (reference: rules/operator_fusion.py)"""
    if not isinstance(a, AbstractMap) or not isinstance(b, AbstractMap):
        return False
    if a.compute != b.compute or a.resources != b.resources:
        return False
    if a.compute == "actors":
        return False  # keep actor stages separate (stateful fns)
    return True


@dataclasses.dataclass
class FusedMap(AbstractMap):
    stages: list[AbstractMap] = dataclasses.field(default_factory=list)

    def __post_init__(self):
        self.name = "Fused(" + "+".join(s.name for s in self.stages) + ")"


@dataclasses.dataclass
class FusedRead(Read):
    """Read with map/filter stages fused INTO the read tasks: each block is
    transformed in the same remote task that produced it — no object-store
    round trip between read and first transform (reference:
    rules/operator_fusion.py fusing maps onto ReadOp)."""
    stages: list[AbstractMap] = dataclasses.field(default_factory=list)

    def __post_init__(self):
        self.name = (f"FusedRead"
                     f"{self.datasource.name if self.datasource else ''}("
                     + "+".join(s.name for s in self.stages) + ")")


def optimize(plan: LogicalPlan) -> LogicalPlan:
    """Fuse adjacent map-ish ops along single-input chains.

    Pure: never mutates the input plan's ops, so a Dataset can be executed
    repeatedly (count() then iter_batches(), multi-epoch iteration) without
    the fused rewrite leaking back into the shared logical graph.
    """

    def rewrite(op: LogicalOp) -> LogicalOp:
        new_inputs = [rewrite(i) for i in op.inputs]
        if isinstance(op, AbstractMap) and len(new_inputs) == 1:
            child = new_inputs[0]
            if isinstance(child, FusedMap) and _fusable(child, op):
                return FusedMap(name="", inputs=list(child.inputs),
                                compute=op.compute, resources=op.resources,
                                stages=[*child.stages, op])
            if isinstance(child, AbstractMap) and not isinstance(child, FusedMap) \
                    and _fusable(child, op):
                return FusedMap(name="", inputs=list(child.inputs),
                                compute=op.compute, resources=op.resources,
                                stages=[child, op])
            # fuse stateless task maps INTO the read: the transform runs in
            # the remote task that produced the block
            if (isinstance(child, Read) and op.compute == "tasks"
                    and not op.resources):
                prior = child.stages if isinstance(child, FusedRead) else []
                return FusedRead(
                    name="", inputs=list(child.inputs),
                    datasource=child.datasource,
                    parallelism=child.parallelism,
                    stages=[*prior, op])
        if any(n is not o for n, o in zip(new_inputs, op.inputs)):
            op = dataclasses.replace(op, inputs=new_inputs)
        return op

    return LogicalPlan(rewrite(plan.terminal))
