"""Distributed reference counting with ownership.

TPU-native analog of the reference's ReferenceCounter
(/root/reference/src/ray/core_worker/reference_count.cc): every object has a
single owner (the process that created it); the owner's count is the authority
for the object's lifetime. Counted sources:

- the owner process's local python ``ObjectRef``s,
- external borrows: any other process holding refs (registered by the *sender*
  synchronously when a ref is serialized into a message, released by the holder
  when its local count drops to zero — sender-side registration avoids the
  inc-after-dec race of receiver-side registration),
- task dependencies: in-flight tasks using the object as an arg,
- containment: stored objects whose serialized payload embeds the ref
  (ref: reference_count.cc nested-ref tracking).

When the owner's total hits zero the on-zero callback fires: the object is
dropped from the memory store, unpinned/deleted in shared-memory stores, and its
lineage entry is released (ref: task_manager.cc lineage pinning).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable

from ray_tpu.core.ids import ObjectID


@dataclass
class _Count:
    local: int = 0
    borrows: int = 0
    deps: int = 0
    contained_in: int = 0
    deleted: bool = False

    def total(self) -> int:
        return self.local + self.borrows + self.deps + self.contained_in


class ReferenceCounter:
    def __init__(self, runtime):
        self._rt = runtime
        self._lock = threading.RLock()
        # objects owned by this process
        self._owned: dict[ObjectID, _Count] = {}
        # contained refs held alive by an owned stored object
        self._containing: dict[ObjectID, list] = {}
        # borrowed (non-owned) refs: local count + owner address for release
        self._borrowed: dict[ObjectID, list] = {}  # oid -> [count, owner_addr]
        self._on_zero: Callable[[ObjectID], None] | None = None

    def set_on_zero(self, cb: Callable[[ObjectID], None]):
        self._on_zero = cb

    # ---- ownership registration --------------------------------------
    def add_owned(self, object_id: ObjectID, contained_refs=None):
        with self._lock:
            c = self._owned.setdefault(object_id, _Count())
            if contained_refs:
                self._containing[object_id] = list(contained_refs)
                for ref in contained_refs:
                    self._inc_any(ref, "contained_in")

    def is_owned(self, object_id: ObjectID) -> bool:
        with self._lock:
            return object_id in self._owned

    # ---- local python refs -------------------------------------------
    def add_local_ref(self, object_id: ObjectID):
        with self._lock:
            c = self._owned.get(object_id)
            if c is not None:
                c.local += 1
                return
            ent = self._borrowed.get(object_id)
            if ent is not None:
                ent[0] += 1
            else:
                self._borrowed[object_id] = [1, None]

    def remove_local_ref(self, object_id: ObjectID):
        release_owner = None
        with self._lock:
            c = self._owned.get(object_id)
            if c is not None:
                c.local -= 1
                self._maybe_zero(object_id, c)
                return
            ent = self._borrowed.get(object_id)
            if ent is None:
                return
            ent[0] -= 1
            if ent[0] <= 0:
                self._borrowed.pop(object_id, None)
                release_owner = ent[1]
        if release_owner is not None:
            self._notify_owner_dec(object_id, release_owner)

    def on_ref_deserialized(self, ref):
        """Record the owner address for later borrow release. The borrow count
        itself was registered by the sender."""
        with self._lock:
            if ref.id() in self._owned:
                # we own it; the sender's borrow-inc on our behalf is dropped
                # when our local count (incremented by ObjectRef ctor) drops.
                return
            ent = self._borrowed.get(ref.id())
            if ent is not None:
                ent[1] = ref.owner_addr

    # ---- borrows (cross-process) -------------------------------------
    def add_borrow_on_serialize(self, ref):
        """Sender-side: register a borrow with the owner before the message
        carrying the ref leaves this process."""
        oid = ref.id()
        with self._lock:
            c = self._owned.get(oid)
            if c is not None:
                c.borrows += 1
                return
        self._call_owner(oid, ref.owner_addr, "inc_borrow")

    def inc_borrow(self, object_id: ObjectID):
        """Owner-side RPC handler."""
        with self._lock:
            c = self._owned.setdefault(object_id, _Count())
            c.borrows += 1

    def dec_borrow(self, object_id: ObjectID):
        with self._lock:
            c = self._owned.get(object_id)
            if c is None:
                return
            c.borrows -= 1
            self._maybe_zero(object_id, c)

    def release_borrow_after_send(self, ref):
        """Sender-side: after handing a ref to another process, the recipient now
        holds the borrow we registered; if we registered it for an object we own,
        drop the temporary count once the recipient confirms (v1: recipient's
        ObjectRef ctor + our dec make the handoff net-zero, so nothing to do)."""

    # ---- task deps ----------------------------------------------------
    def add_task_dep(self, object_id: ObjectID, owner_addr=None):
        with self._lock:
            c = self._owned.get(object_id)
            if c is not None:
                c.deps += 1
                return
        self._call_owner(object_id, owner_addr, "inc_borrow")
        with self._lock:
            self._borrowed.setdefault(object_id, [0, owner_addr])

    def remove_task_dep(self, object_id: ObjectID, owner_addr=None):
        with self._lock:
            c = self._owned.get(object_id)
            if c is not None:
                c.deps -= 1
                self._maybe_zero(object_id, c)
                return
        if owner_addr is not None:
            self._notify_owner_dec(object_id, owner_addr)

    # ---- internals -----------------------------------------------------
    def _inc_any(self, ref, kind: str):
        oid = ref.id() if hasattr(ref, "id") else ref
        c = self._owned.get(oid)
        if c is not None:
            setattr(c, kind, getattr(c, kind) + 1)

    def _maybe_zero(self, object_id: ObjectID, c: _Count):
        if c.total() <= 0 and not c.deleted:
            c.deleted = True
            self._owned.pop(object_id, None)
            contained = self._containing.pop(object_id, [])
            cb = self._on_zero
            if cb is not None:
                try:
                    cb(object_id)
                except Exception:
                    pass
            for ref in contained:
                with self._lock:
                    cc = self._owned.get(ref.id())
                    if cc is not None:
                        cc.contained_in -= 1
                        self._maybe_zero(ref.id(), cc)
                        continue
                if ref.owner_addr is not None:
                    self._notify_owner_dec(ref.id(), ref.owner_addr)

    def _call_owner(self, object_id: ObjectID, owner_addr, method: str):
        if owner_addr is None or self._rt is None:
            return
        try:
            self._rt.peer_pool.get(owner_addr).call_with_retry(
                method, object_id, timeout=10.0)
        except Exception:
            pass

    def _notify_owner_dec(self, object_id: ObjectID, owner_addr):
        if owner_addr is None or self._rt is None:
            return
        try:
            self._rt.peer_pool.get(owner_addr).notify("dec_borrow", object_id)
        except Exception:
            pass

    # ---- introspection -------------------------------------------------
    def owned_count(self, object_id: ObjectID) -> int:
        with self._lock:
            c = self._owned.get(object_id)
            return c.total() if c else 0

    def num_owned(self) -> int:
        with self._lock:
            return len(self._owned)
