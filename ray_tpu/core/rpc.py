"""RPC transport: multiplexed, length-prefixed frames over TCP.

TPU-native analog of the reference's RPC layer (/root/reference/src/ray/rpc/ —
GrpcServer/ClientCall/RetryableGrpcClient). Control-plane messages are small and
latency-sensitive; data moves through the shared-memory object store, not RPC.
Includes deterministic fault injection for tests, mirroring rpc_chaos.cc
(ray_config_def.h:842-849).

Frame format: [u32 len][u8 kind][payload] where payload is
pickle((msg_id, method, body)) for requests and pickle((msg_id, ok, body)) for
responses. kind: 0=request 1=response 2=oneway.
"""

from __future__ import annotations

import pickle
import random
import socket
import struct
import threading
import time
from typing import Any, Callable

from ray_tpu.core.config import get_config
from ray_tpu.util import metrics as _metrics

_REQ, _RESP, _ONEWAY = 0, 1, 2

# Built-in transport metrics (ISSUE 4). Module-level: one registration per
# process no matter how many servers/clients it hosts; tag cardinality is
# bounded by the method-name set.
_RPC_LATENCY = _metrics.Histogram(
    "ray_tpu_rpc_request_latency_seconds",
    "server-side RPC handler latency per method",
    boundaries=[0.001, 0.01, 0.1, 1, 10],
    tag_keys=("method",))
_RPC_INFLIGHT = _metrics.Gauge(
    "ray_tpu_rpc_inflight_requests",
    "RPC handler invocations currently executing",
    tag_keys=("method",))
_RPC_RECONNECTS = _metrics.Counter(
    "ray_tpu_rpc_reconnects_total",
    "client connections re-established after a drop")

# Process-local server registry for the loopback fast path: when the caller
# and the target server share a process (driver->in-proc CP/agent; the
# whole in-proc multi-node Cluster harness), requests dispatch straight to
# the server's handler pool — no sockets, no per-connection reader threads,
# no syscall round trip. Bodies still take a pickle round trip so loopback
# keeps wire copy semantics (handlers own their body; replies don't alias
# caller state), and chaos fault injection still applies.
_LOCAL_SERVERS: dict[tuple, "RpcServer"] = {}
_LOCAL_LOCK = threading.Lock()


class RpcError(Exception):
    pass


class ConnectionLost(RpcError):
    pass


class _Chaos:
    """Deterministic RPC fault injection (ref: rpc_chaos.h:13-19).

    Spec: ``method:prob_req[:prob_resp[:delay_s]],...`` — drop requests /
    responses with the given probabilities, and/or stall every matched
    handler by ``delay_s`` (the FaultSchedule rpc_delay event)."""

    def __init__(self, spec: str):
        self.rules: dict[str, tuple[float, float, float]] = {}
        self.rng = random.Random(0xC0FFEE)
        for item in filter(None, (spec or "").split(",")):
            parts = item.split(":")
            self.rules[parts[0]] = (
                float(parts[1]),
                float(parts[2]) if len(parts) > 2 else 0.0,
                float(parts[3]) if len(parts) > 3 else 0.0)

    def drop_request(self, method: str) -> bool:
        r = self.rules.get(method) or self.rules.get("*")
        return bool(r) and self.rng.random() < r[0]

    def drop_response(self, method: str) -> bool:
        r = self.rules.get(method) or self.rules.get("*")
        return bool(r) and self.rng.random() < r[1]

    def delay_for(self, method: str) -> float:
        r = self.rules.get(method) or self.rules.get("*")
        return r[2] if r else 0.0


def _chaos() -> _Chaos:
    global _chaos_inst
    spec = get_config().testing_rpc_failure
    if _chaos_inst is None or _chaos_inst_spec != spec:
        _set_chaos(spec)
    return _chaos_inst


_chaos_inst: _Chaos | None = None
_chaos_inst_spec: str | None = None


def _set_chaos(spec: str):
    global _chaos_inst, _chaos_inst_spec
    _chaos_inst = _Chaos(spec)
    _chaos_inst_spec = spec


def _send_frame(sock: socket.socket, kind: int, payload: bytes, lock: threading.Lock):
    header = struct.pack("<IB", len(payload) + 1, kind)
    with lock:
        # the write lock's purpose IS to serialize socket writes — frames
        # from concurrent senders must not interleave on the wire
        # graftlint: disable=lock-discipline
        sock.sendall(header + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        b = sock.recv(min(n, 1 << 20))
        if not b:
            raise ConnectionLost("peer closed")
        chunks.append(b)
        n -= len(b)
    return b"".join(chunks)


def _recv_frame(sock: socket.socket) -> tuple[int, bytes]:
    hdr = _recv_exact(sock, 5)
    ln, kind = struct.unpack("<IB", hdr)
    return kind, _recv_exact(sock, ln - 1)


class _GrowPool:
    """Unbounded-but-reusing executor for loopback dispatch of blocking
    handlers: never queues behind a busy thread (parity with the socket
    path's thread-per-call, so long-poll pileups cannot deadlock), but idle
    threads linger to serve the next call instead of paying a thread spawn
    per RPC, and die after a quiet period."""

    _IDLE_TTL_S = 5.0

    def __init__(self, name: str):
        from collections import deque
        self._name = name
        self._lock = threading.Lock()
        self._tasks: "deque" = deque()
        self._cv = threading.Condition(self._lock)
        # threads waiting AND unclaimed: a submitter that hands work to an
        # idle thread decrements this under the lock at claim time, so two
        # near-simultaneous submits can never both count the same waiter
        # (the second would see 0 and spawn)
        self._idle = 0
        self._seq = 0

    def submit(self, fn) -> None:
        with self._lock:
            self._tasks.append(fn)
            if self._idle > 0:
                self._idle -= 1  # claim one waiter for this task
                self._cv.notify()
                return
            self._seq += 1
            name = f"{self._name}-{self._seq}"
        threading.Thread(target=self._run, name=name, daemon=True).start()

    def _run(self):
        while True:
            fn = None
            with self._lock:
                if self._tasks:
                    fn = self._tasks.popleft()
                else:
                    self._idle += 1
                    signaled = self._cv.wait(self._IDLE_TTL_S)
                    if self._tasks:
                        # claimed (claimer decremented _idle), or timed out
                        # in the same instant a claim landed — either way
                        # the claim-side accounting already happened
                        fn = self._tasks.popleft()
                    else:
                        # no work: un-register. Clamped because a freshly
                        # spawned thread may have taken the task of the
                        # claim that woke us (then our slot was already
                        # decremented by that claimer).
                        self._idle = max(0, self._idle - 1)
                        if not signaled:
                            return  # quiet: let the thread die
                        continue
            try:
                fn()
            except Exception:
                pass


class DeferredReply:
    """Returned by a handler to decouple the RPC reply from the handler
    thread (ref: the reference's reply-later ServerCall — server_call.h —
    where SendReply happens from any thread). The server binds a sender when
    it sees this return value; `send(result)` / `fail(exc)` may be called
    before or after binding, from any thread, exactly once."""

    _UNSET = object()

    def __init__(self):
        self._lock = threading.Lock()
        self._sender = None
        self._ok = None
        self._result = self._UNSET

    def send(self, result: Any) -> None:
        self._finish(True, result)

    def fail(self, exc: BaseException) -> None:
        self._finish(False, exc)

    def _finish(self, ok: bool, result: Any) -> None:
        with self._lock:
            if self._result is not self._UNSET:
                return
            self._ok, self._result = ok, result
            sender = self._sender
        if sender is not None:
            sender(ok, result)

    def _bind(self, sender) -> None:
        with self._lock:
            self._sender = sender
            if self._result is self._UNSET:
                return
            ok, result = self._ok, self._result
        sender(ok, result)


class RpcServer:
    """Threaded RPC server. ``handler(method, body, peer)`` returns the response
    body or raises; the exception is pickled back to the caller. A handler may
    instead return a DeferredReply to free its thread and reply later."""

    def __init__(self, handler: Callable[[str, Any, tuple], Any], host: str = "127.0.0.1",
                 port: int = 0, name: str = "rpc", blocking_methods: set[str] | None = None,
                 pool_size: int = 8):
        from concurrent.futures import ThreadPoolExecutor
        self._handler = handler
        self._name = name
        # Non-blocking handlers run on a bounded pool; handlers that may block
        # for long (waits, long-polls) get a dedicated thread each so they
        # cannot starve the pool (ref: server_call.h io-service separation).
        self._blocking = blocking_methods or set()
        self._pool = ThreadPoolExecutor(max_workers=pool_size, thread_name_prefix=f"{name}-h")
        self._grow_pool = _GrowPool(f"{name}-hb")
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(256)
        self.addr: tuple[str, int] = self._sock.getsockname()
        self._stopped = threading.Event()
        self._conns: set[socket.socket] = set()
        self._conns_lock = threading.Lock()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"{name}-accept", daemon=True)
        self._accept_thread.start()
        with _LOCAL_LOCK:
            _LOCAL_SERVERS[self.addr] = self

    def _dispatch_local(self, kind: int, method: str, body_pickled: bytes,
                        reply_cb) -> None:
        """Loopback entry: run the handler exactly as a socket request would
        (bounded pool, or a dedicated thread for blocking methods), then
        hand (ok, pickled_reply) to ``reply_cb`` — or drop per chaos."""
        def run():
            try:
                body = pickle.loads(body_pickled)
                result, ok = self._timed_handler(
                    method, body, ("loopback", 0)), True
            except BaseException as e:  # noqa: BLE001 — propagate to caller
                result, ok = e, False
            if ok and isinstance(result, DeferredReply):
                if kind == _ONEWAY:
                    result._bind(lambda *_: None)
                else:
                    result._bind(lambda ok2, res2: self._finish_local(
                        method, ok2, res2, reply_cb))
                return
            if kind == _ONEWAY:
                return
            self._finish_local(method, ok, result, reply_cb)

        try:
            if method in self._blocking:
                self._grow_pool.submit(run)
            else:
                self._pool.submit(run)
        except RuntimeError as e:
            # server stopped between the registry check and the dispatch:
            # surface the same failure shape the socket path produces
            raise ConnectionLost(f"server {self.addr} stopped: {e}") from e

    def _finish_local(self, method, ok, result, reply_cb):
        if _chaos().drop_response(method):
            return
        try:
            payload = pickle.dumps(result)
        except Exception as e:
            ok, payload = False, pickle.dumps(
                RpcError(f"unpicklable response: {e}"))
        reply_cb(ok, payload)

    def _accept_loop(self):
        while not self._stopped.is_set():
            try:
                conn, peer = self._sock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._conns_lock:
                self._conns.add(conn)
            threading.Thread(target=self._conn_loop, args=(conn, peer),
                             name=f"{self._name}-conn", daemon=True).start()

    def _conn_loop(self, conn: socket.socket, peer):
        wlock = threading.Lock()
        try:
            while not self._stopped.is_set():
                kind, payload = _recv_frame(conn)
                msg_id, method, body = pickle.loads(payload)
                if _chaos().drop_request(method):
                    continue
                if method in self._blocking:
                    # grow-pool: thread-per-call semantics (a blocked
                    # handler never queues behind another) with idle-thread
                    # reuse instead of a spawn per RPC
                    self._grow_pool.submit(
                        lambda c=conn, w=wlock, k=kind, m=msg_id,
                        me=method, b=body, p=peer:
                        self._dispatch(c, w, k, m, me, b, p))
                else:
                    self._pool.submit(
                        self._dispatch, conn, wlock, kind, msg_id, method, body, peer)
        except (ConnectionLost, OSError):
            pass
        except RuntimeError:
            pass  # pool shut down mid-receive: server is stopping
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _timed_handler(self, method, body, peer):
        """Handler invocation under the per-method latency histogram and
        in-flight gauge (both socket and loopback dispatch paths)."""
        delay = _chaos().delay_for(method)
        if delay > 0:  # chaos rpc_delay: stall on the handler thread
            time.sleep(delay)
        _RPC_INFLIGHT.inc(tags={"method": method})
        t0 = time.monotonic()
        try:
            return self._handler(method, body, peer)
        finally:
            _RPC_LATENCY.observe(time.monotonic() - t0,
                                 tags={"method": method})
            _RPC_INFLIGHT.dec(tags={"method": method})

    def _dispatch(self, conn, wlock, kind, msg_id, method, body, peer):
        try:
            result, ok = self._timed_handler(method, body, peer), True
        except BaseException as e:  # noqa: BLE001 — errors propagate to caller
            result, ok = e, False
        if ok and isinstance(result, DeferredReply):
            if kind == _ONEWAY:
                result._bind(lambda *_: None)
                return
            result._bind(lambda ok2, res2: self._send_reply(
                conn, wlock, msg_id, method, ok2, res2))
            return
        if kind == _ONEWAY:
            return
        self._send_reply(conn, wlock, msg_id, method, ok, result)

    def _send_reply(self, conn, wlock, msg_id, method, ok, result):
        if _chaos().drop_response(method):
            return
        try:
            payload = pickle.dumps((msg_id, ok, result))
        except Exception as e:
            payload = pickle.dumps((msg_id, False, RpcError(f"unpicklable response: {e}")))
        try:
            _send_frame(conn, _RESP, payload, wlock)
        except OSError:
            pass

    def stop(self):
        self._stopped.set()
        with _LOCAL_LOCK:
            if _LOCAL_SERVERS.get(self.addr) is self:
                del _LOCAL_SERVERS[self.addr]
        try:
            self._sock.close()
        except OSError:
            pass
        with self._conns_lock:
            for c in list(self._conns):
                try:
                    c.close()
                except OSError:
                    pass
        # release the handler pool threads — a long-lived process that starts
        # many servers (tests, serve controllers) must not accumulate 8-16
        # idle threads per stopped server
        self._pool.shutdown(wait=False, cancel_futures=True)


class RpcClient:
    """Persistent multiplexed client with reconnect + retry
    (ref: retryable_grpc_client.cc)."""

    def __init__(self, addr: tuple[str, int], name: str = "rpc-client"):
        self.addr = tuple(addr)
        self._name = name
        self._sock: socket.socket | None = None
        self._wlock = threading.Lock()
        self._lock = threading.Lock()
        self._pending: dict[int, list] = {}  # msg_id -> [event, ok, body]
        self._next_id = 0
        self._closed = False
        self._had_conn = False  # a later successful connect is a reconnect

    def _ensure_conn(self, connect_timeout: float | None = None) -> socket.socket:  # graftlint: disable=lock-discipline — the client lock deliberately serializes reconnect attempts (backoff sleep included) so one socket is dialed at a time
        """Returns the live socket (never read self._sock without the lock —
        the reader thread nulls it on connection loss)."""
        with self._lock:
            if self._sock is not None:
                return self._sock
            if self._closed:
                raise ConnectionLost("client closed")
            cfg = get_config()
            if connect_timeout is None:
                connect_timeout = cfg.rpc_connect_timeout_s
            now = time.monotonic()
            deadline = now + connect_timeout
            # refused = nothing listening on a port the peer already
            # published: the peer is almost certainly dead, so fail fast
            # (see config.rpc_refused_grace_s) instead of wedging callers
            # for the full connect budget
            refused_deadline = now + min(connect_timeout,
                                         cfg.rpc_refused_grace_s)
            last = None
            while time.monotonic() < deadline:
                try:
                    s = socket.create_connection(self.addr, timeout=cfg.rpc_connect_timeout_s)
                    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                    s.settimeout(None)
                    self._sock = s
                    if self._had_conn:
                        _RPC_RECONNECTS.inc()
                    self._had_conn = True
                    threading.Thread(target=self._read_loop, args=(s,),
                                     name=f"{self._name}-read", daemon=True).start()
                    return s
                except OSError as e:
                    last = e
                    if isinstance(e, ConnectionRefusedError) and \
                            time.monotonic() >= refused_deadline:
                        break
                    time.sleep(0.05)
            raise ConnectionLost(f"cannot connect to {self.addr}: {last}")

    def _read_loop(self, sock: socket.socket):
        try:
            while True:
                _, payload = _recv_frame(sock)
                msg_id, ok, body = pickle.loads(payload)
                with self._lock:
                    ent = self._pending.pop(msg_id, None)
                if ent is None:
                    continue
                if callable(ent[0]):
                    try:
                        ent[0](ok, body)
                    except Exception:
                        pass
                else:
                    ent[1], ent[2] = ok, body
                    ent[0].set()
        except (ConnectionLost, OSError, EOFError):
            with self._lock:
                if self._sock is sock:
                    self._sock = None
                pending, self._pending = list(self._pending.values()), {}
            err = ConnectionLost(f"connection to {self.addr} lost")
            for ent in pending:
                if callable(ent[0]):
                    try:
                        ent[0](False, err)
                    except Exception:
                        pass
                elif not ent[0].is_set():
                    ent[1], ent[2] = False, err
                    ent[0].set()

    def _local_server(self) -> "RpcServer | None":
        srv = _LOCAL_SERVERS.get(self.addr)
        if srv is None or srv._stopped.is_set():
            return None
        return srv

    def call(self, method: str, body: Any = None, timeout: float | None = None,
             connect_timeout: float | None = None) -> Any:
        srv = self._local_server()
        if srv is not None:
            if self._closed:
                raise ConnectionLost("client closed")
            payload = pickle.dumps(body)
            if _chaos().drop_request(method):
                # dropped on the (virtual) wire: caller waits out its timeout
                # exactly like the socket path
                if timeout is None:
                    raise ConnectionLost(f"rpc {method} dropped by chaos")
                time.sleep(timeout)
                raise TimeoutError(
                    f"rpc {method} to {self.addr} timed out after {timeout}s")
            ev = threading.Event()
            ent = [None, None]

            def reply_cb(ok, res_payload):
                ent[0], ent[1] = ok, res_payload
                ev.set()

            srv._dispatch_local(_REQ, method, payload, reply_cb)
            if not ev.wait(timeout):
                raise TimeoutError(
                    f"rpc {method} to {self.addr} timed out after {timeout}s")
            result = pickle.loads(ent[1])
            if not ent[0]:
                raise result
            return result
        ev = threading.Event()
        with self._lock:
            self._next_id += 1
            msg_id = self._next_id
            self._pending[msg_id] = ent = [ev, None, None]
        try:
            sock = self._ensure_conn(connect_timeout)
            try:
                _send_frame(sock, _REQ, pickle.dumps((msg_id, method, body)), self._wlock)
            except OSError as e:
                raise ConnectionLost(f"send to {self.addr} failed: {e}") from e
            if not ev.wait(timeout):
                raise TimeoutError(f"rpc {method} to {self.addr} timed out after {timeout}s")
            ok, result = ent[1], ent[2]
        finally:
            with self._lock:
                self._pending.pop(msg_id, None)
        if not ok:
            raise result
        return result

    def call_async(self, method: str, body: Any = None,
                   callback: Callable[[bool, Any], None] | None = None):
        """Fire a request; ``callback(ok, body)`` runs on the reader thread when
        the response arrives (ref: client_call.h async ClientCall). Keep
        callbacks short — heavy work must hop to another thread."""
        srv = self._local_server()
        if srv is not None:
            try:
                if self._closed:
                    raise ConnectionLost("client closed")
                payload = pickle.dumps(body)
            except Exception as e:
                if callback is not None:
                    callback(False, e)
                return
            if _chaos().drop_request(method):
                return  # dropped: no reply ever arrives (socket-path parity)

            def reply_cb(ok, res_payload):
                if callback is not None:
                    try:
                        callback(ok, pickle.loads(res_payload))
                    except Exception:
                        pass

            try:
                srv._dispatch_local(_REQ if callback else _ONEWAY, method,
                                    payload, reply_cb)
            except ConnectionLost as e:
                if callback is not None:
                    callback(False, e)
            return
        with self._lock:
            self._next_id += 1
            msg_id = self._next_id
            if callback is not None:
                self._pending[msg_id] = [callback, None, None]
        try:
            sock = self._ensure_conn()
            _send_frame(sock, _REQ if callback else _ONEWAY,
                        pickle.dumps((msg_id, method, body)), self._wlock)
        except Exception as e:
            with self._lock:
                self._pending.pop(msg_id, None)
            if callback is not None:
                callback(False, e)

    def call_with_retry(self, method: str, body: Any = None, timeout: float | None = None,
                        retries: int | None = None) -> Any:
        retries = get_config().rpc_retries if retries is None else retries
        last: Exception | None = None
        for attempt in range(retries + 1):
            try:
                return self.call(method, body, timeout)
            except (ConnectionLost, TimeoutError) as e:
                last = e
                time.sleep(min(0.1 * 2 ** attempt, 1.0))
        raise last  # type: ignore[misc]

    def notify(self, method: str, body: Any = None,
               connect_timeout: float | None = None):
        srv = self._local_server()
        if srv is not None:
            if self._closed:
                raise ConnectionLost("client closed")
            payload = pickle.dumps(body)
            if not _chaos().drop_request(method):
                srv._dispatch_local(_ONEWAY, method, payload, lambda *_: None)
            return
        with self._lock:
            self._next_id += 1
            msg_id = self._next_id
        sock = self._ensure_conn(connect_timeout)
        try:
            _send_frame(sock, _ONEWAY, pickle.dumps((msg_id, method, body)), self._wlock)
        except OSError as e:
            raise ConnectionLost(f"send to {self.addr} failed: {e}") from e

    def close(self):
        with self._lock:
            self._closed = True
            sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass


class ClientPool:
    """Cached RpcClients keyed by address."""

    def __init__(self, name: str = "pool"):
        self._name = name
        self._clients: dict[tuple[str, int], RpcClient] = {}
        self._lock = threading.Lock()

    def get(self, addr: tuple[str, int]) -> RpcClient:
        addr = tuple(addr)
        with self._lock:
            c = self._clients.get(addr)
            if c is None:
                c = self._clients[addr] = RpcClient(addr, name=f"{self._name}-{addr[1]}")
            return c

    def invalidate(self, addr: tuple[str, int]):
        with self._lock:
            c = self._clients.pop(tuple(addr), None)
        if c is not None:
            c.close()

    def close_all(self):
        with self._lock:
            clients, self._clients = list(self._clients.values()), {}
        for c in clients:
            c.close()
