"""Tuner + TuneController: trials as remote actors.

TPU-native analog of the reference's Tune execution layer
(/root/reference/python/ray/tune/tuner.py — Tuner.fit:312;
execution/tune_controller.py:68 TuneController; result_grid.py). Each trial
runs the user trainable in a RayTrainWorker-style actor (thread + report
queue — the same mechanism Train uses, so a Trainer can nest under Tune);
the controller polls trials, feeds results to the scheduler, and applies
stop/exploit decisions.
"""

from __future__ import annotations

import dataclasses
import os
import time
import uuid
from typing import Any, Callable, Optional

import ray_tpu
from ray_tpu.train.checkpoint import Checkpoint, StorageContext, new_run_name
from ray_tpu.train.config import RunConfig
from ray_tpu.train.worker_group import RayTrainWorker
from ray_tpu.tune.schedulers import CONTINUE, STOP, FIFOScheduler, \
    PopulationBasedTraining, TrialScheduler
from ray_tpu.tune.search import BasicVariantGenerator


@dataclasses.dataclass
class TuneConfig:
    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: Optional[int] = None
    scheduler: Optional[TrialScheduler] = None
    # a Searcher (e.g. TPESearcher) that proposes configs sequentially
    # instead of upfront variant expansion (reference search_alg)
    search_alg: object = None
    seed: Optional[int] = None

    def __post_init__(self):
        if self.mode not in ("max", "min"):
            raise ValueError("mode must be 'max' or 'min'")


@dataclasses.dataclass
class Trial:
    trial_id: str
    config: dict
    state: str = "PENDING"   # PENDING/RUNNING/TERMINATED/ERROR/STOPPED
    actor: Any = None
    last_metrics: Optional[dict] = None
    history: list = dataclasses.field(default_factory=list)
    error: Optional[str] = None
    checkpoint: Optional[Checkpoint] = None
    iterations: int = 0


@dataclasses.dataclass
class TrialResult:
    metrics: Optional[dict]
    config: dict
    error: Optional[str]
    checkpoint: Optional[Checkpoint]
    history: list

    @property
    def metrics_dataframe(self):
        import pandas as pd
        return pd.DataFrame(self.history)


class ResultGrid:
    def __init__(self, results: list[TrialResult], metric: Optional[str],
                 mode: str):
        self._results = results
        self._metric = metric
        self._mode = mode

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i) -> TrialResult:
        return self._results[i]

    @property
    def errors(self) -> list:
        return [r.error for r in self._results if r.error]

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> TrialResult:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("metric required (set TuneConfig.metric)")
        valid = [r for r in self._results
                 if r.metrics and metric in r.metrics]
        if not valid:
            raise RuntimeError("no trial reported the target metric")
        key = lambda r: r.metrics[metric]  # noqa: E731
        return max(valid, key=key) if mode == "max" else min(valid, key=key)

    def get_dataframe(self):
        import pandas as pd
        rows = []
        for r in self._results:
            row = dict(r.metrics or {})
            row.update({f"config/{k}": v for k, v in r.config.items()
                        if not isinstance(v, dict)})
            rows.append(row)
        return pd.DataFrame(rows)


class TuneController:
    """Drives all trials to completion (reference tune_controller.py:68)."""

    def __init__(self, trainable: Callable, *, param_space: dict,
                 tune_config: TuneConfig, run_config: RunConfig,
                 poll_interval_s: float = 0.05):
        self._trainable = trainable
        self._tune_config = tune_config
        self._run_config = run_config
        self._poll_interval_s = poll_interval_s
        self._run_name = run_config.name or new_run_name()
        self._storage = StorageContext(run_config.storage_path, self._run_name)
        self._searcher = tune_config.search_alg
        if self._searcher is None:
            variants = BasicVariantGenerator(
                param_space, tune_config.num_samples,
                tune_config.seed).variants()
            self.trials = [
                Trial(trial_id=f"trial_{i:05d}_{uuid.uuid4().hex[:6]}",
                      config=cfg) for i, cfg in enumerate(variants)]
            self._suggest_budget = 0
        else:
            # searcher-driven: trials are created lazily from suggest()
            self.trials = []
            self._suggest_budget = tune_config.num_samples
        self._scheduler = tune_config.scheduler or FIFOScheduler()
        self._max_concurrent = tune_config.max_concurrent_trials or 4

    def _next_suggested_trial(self) -> Optional[Trial]:
        if self._searcher is None or self._suggest_budget <= 0:
            return None
        trial_id = f"trial_{len(self.trials):05d}_{uuid.uuid4().hex[:6]}"
        cfg = self._searcher.suggest(trial_id)
        if cfg is None:
            return None  # concurrency-limited: retry next loop
        self._suggest_budget -= 1
        trial = Trial(trial_id=trial_id, config=cfg)
        self.trials.append(trial)
        return trial

    def _start_trial(self, trial: Trial, resume_from: Optional[Checkpoint] = None):
        trial.actor = RayTrainWorker.remote()
        trial_dir = os.path.join(self._storage.run_path, trial.trial_id)
        os.makedirs(trial_dir, exist_ok=True)
        ray_tpu.get(trial.actor.init_context.remote(
            world_rank=0, world_size=1, local_rank=0, local_world_size=1,
            node_rank=0, experiment_name=self._run_name,
            trial_name=trial.trial_id, trial_id=trial.trial_id,
            trial_dir=trial_dir, hparams=trial.config,
            resume_checkpoint=resume_from, sync_report=True))
        ray_tpu.get(trial.actor.run_train_fn.remote(
            self._trainable, trial.config))
        trial.state = "RUNNING"

    def _stop_trial(self, trial: Trial, state: str):
        trial.state = state
        if trial.actor is not None:
            try:
                ray_tpu.kill(trial.actor)
            except Exception:  # noqa: BLE001
                pass
            trial.actor = None

    def _handle_reports(self, trial: Trial, reports) -> str:
        decision = CONTINUE
        for rep in reports:
            trial.iterations += 1
            metrics = dict(rep.metrics)
            metrics.setdefault("training_iteration", trial.iterations)
            trial.last_metrics = metrics
            trial.history.append(metrics)
            if rep.checkpoint is not None:
                persisted_dir = os.path.join(
                    self._storage.run_path, trial.trial_id,
                    f"checkpoint_{trial.iterations:06d}")
                import shutil
                if os.path.abspath(rep.checkpoint.path) != \
                        os.path.abspath(persisted_dir):
                    if os.path.exists(persisted_dir):
                        shutil.rmtree(persisted_dir)
                    shutil.copytree(rep.checkpoint.path, persisted_dir)
                trial.checkpoint = Checkpoint(persisted_dir)
            d = self._scheduler.on_result(trial, metrics)
            if d == STOP:
                decision = STOP
        return decision

    def _notify_searcher(self, trial: Trial, error: bool = False) -> None:
        if self._searcher is not None:
            try:
                self._searcher.on_trial_complete(
                    trial.trial_id, trial.last_metrics, error=error)
            except Exception:  # noqa: BLE001
                pass

    def _apply_pbt(self):
        sched = self._scheduler
        if not isinstance(sched, PopulationBasedTraining):
            return
        for trial_id, req in list(sched.exploit_requests.items()):
            trial = next((t for t in self.trials if t.trial_id == trial_id),
                         None)
            donor = next((t for t in self.trials
                          if t.trial_id == req["donor"]), None)
            if trial is None or donor is None or trial.state != "RUNNING":
                sched.exploit_requests.pop(trial_id, None)
                continue
            self._stop_trial(trial, "PENDING")
            trial.config = sched.mutate_config(dict(donor.config))
            sched.on_exploit(trial_id)
            self._start_trial(trial, resume_from=donor.checkpoint)
            sched.exploit_requests.pop(trial_id, None)

    def run(self) -> ResultGrid:
        pending = list(self.trials)
        running: list[Trial] = []
        while pending or running or self._suggest_budget > 0:
            while self._suggest_budget > 0 and not pending \
                    and len(running) < self._max_concurrent:
                t = self._next_suggested_trial()
                if t is None:
                    break
                pending.append(t)
            while pending and len(running) < self._max_concurrent:
                trial = pending.pop(0)
                try:
                    self._start_trial(trial)
                    running.append(trial)
                except Exception as e:  # noqa: BLE001 - scheduling failure
                    trial.error = repr(e)
                    trial.state = "ERROR"
                    self._notify_searcher(trial, error=True)
            for trial in list(running):
                try:
                    status = ray_tpu.get(trial.actor.poll.remote(),
                                         timeout=30.0)
                except Exception as e:  # noqa: BLE001 - actor death
                    trial.error = f"trial actor died: {e!r}"
                    self._stop_trial(trial, "ERROR")
                    running.remove(trial)
                    self._notify_searcher(trial, error=True)
                    continue
                decision = self._handle_reports(trial, status.reports)
                if status.error:
                    trial.error = status.error
                    self._stop_trial(trial, "ERROR")
                    running.remove(trial)
                    self._notify_searcher(trial, error=True)
                elif decision == STOP:
                    self._scheduler.on_complete(trial, trial.last_metrics)
                    self._stop_trial(trial, "STOPPED")
                    running.remove(trial)
                    self._notify_searcher(trial)
                elif status.finished:
                    self._scheduler.on_complete(trial, trial.last_metrics)
                    self._stop_trial(trial, "TERMINATED")
                    running.remove(trial)
                    self._notify_searcher(trial)
            self._apply_pbt()
            running = [t for t in self.trials if t.state == "RUNNING"]
            if running or pending or self._suggest_budget > 0:
                time.sleep(self._poll_interval_s)
        results = [TrialResult(metrics=t.last_metrics, config=t.config,
                               error=t.error, checkpoint=t.checkpoint,
                               history=t.history)
                   for t in self.trials]
        return ResultGrid(results, self._tune_config.metric,
                          self._tune_config.mode)


class Tuner:
    """Public entry point (reference tuner.py Tuner.fit:312). Accepts a
    plain trainable fn(config) or a Train trainer instance."""

    def __init__(self, trainable, *, param_space: Optional[dict] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config: Optional[RunConfig] = None):
        self._param_space = param_space or {}
        self._tune_config = tune_config or TuneConfig()
        self._run_config = run_config or RunConfig()
        self._trainable = self._wrap(trainable)

    def _wrap(self, trainable):
        from ray_tpu.train.trainer import DataParallelTrainer
        if isinstance(trainable, DataParallelTrainer):
            base = trainable

            def run_trainer(config):
                import copy
                t = copy.copy(base)
                merged = dict(base._train_loop_config or {})
                merged.update(config.get("train_loop_config", config))
                t._train_loop_config = merged
                result = t.fit()
                if result.error is not None:
                    raise result.error
                from ray_tpu.train.context import report
                if result.metrics:
                    report(result.metrics, checkpoint=result.checkpoint)
            return run_trainer
        return trainable

    def fit(self) -> ResultGrid:
        controller = TuneController(
            self._trainable, param_space=self._param_space,
            tune_config=self._tune_config, run_config=self._run_config)
        return controller.run()
