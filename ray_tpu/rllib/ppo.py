"""PPO (ref: rllib/algorithms/ppo/ppo.py, torch learner in
ppo/torch/ppo_torch_learner.py — rebuilt as a single jitted update).

GAE advantages are computed inside the jitted step with lax.scan (reverse
accumulation), the clipped surrogate + value + entropy losses in one fused
program; minibatch SGD epochs run as a lax-free Python loop over device
arrays (shapes static, so one compile).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig


def _gae(rewards, dones, values, last_value, gamma, lam):
    """Reverse-scan GAE: adv_t = d_t + gamma*lam*(1-done_t)*adv_{t+1}."""
    next_values = jnp.concatenate([values[1:], last_value[None]])
    deltas = rewards + gamma * (1.0 - dones) * next_values - values

    def step(carry, x):
        delta, done = x
        adv = delta + gamma * lam * (1.0 - done) * carry
        return adv, adv

    _, advs = jax.lax.scan(step, 0.0, (deltas, dones), reverse=True)
    return advs, advs + values


class PPO(Algorithm):
    def setup(self) -> None:
        kw = self.config.train_kwargs
        self._clip = kw.get("clip_param", 0.2)
        self._vf_coeff = kw.get("vf_loss_coeff", 0.5)
        self._ent_coeff = kw.get("entropy_coeff", 0.01)
        self._lam = kw.get("lambda_", 0.95)
        self._epochs = kw.get("num_epochs", 4)
        self._minibatch = kw.get("minibatch_size", 128)
        self._opt = optax.adam(self.config.lr)
        self._opt_state = self._opt.init(self.params)
        self._rng = np.random.default_rng(self.config.seed)

        module, gamma, lam = self.module, self.config.gamma, self._lam

        @jax.jit
        def advantages(params, batch):
            _, last_v = module.forward_train(params, batch["last_obs"][None])
            adv, targets = _gae(batch["rewards"], batch["dones"], batch["vf"],
                                last_v[0], gamma, lam)
            adv = (adv - adv.mean()) / (adv.std() + 1e-8)
            return adv, targets

        clip, vf_c, ent_c = self._clip, self._vf_coeff, self._ent_coeff

        def loss_fn(params, mb):
            logits, values = module.forward_train(params, mb["obs"])
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, mb["actions"][:, None], axis=1)[:, 0]
            ratio = jnp.exp(logp - mb["logp"])
            surr = jnp.minimum(
                ratio * mb["adv"],
                jnp.clip(ratio, 1 - clip, 1 + clip) * mb["adv"])
            pi_loss = -surr.mean()
            vf_loss = ((values - mb["targets"]) ** 2).mean()
            entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
            total = pi_loss + vf_c * vf_loss - ent_c * entropy
            return total, (pi_loss, vf_loss, entropy)

        @jax.jit
        def update(params, opt_state, mb):
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, mb)
            updates, opt_state = self._opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss, aux

        self._advantages = advantages
        self._update = update

    def training_step(self) -> dict:
        cfg = self.config
        samples = self.runners.sample(self.params, cfg.rollout_steps)
        self._timesteps += cfg.rollout_steps * cfg.num_env_runners

        # per-runner GAE (trajectories must not cross runner boundaries)
        cols: dict[str, list] = {k: [] for k in
                                 ("obs", "actions", "logp", "adv", "targets")}
        for s in samples:
            adv, targets = self._advantages(self.params, s)
            cols["obs"].append(s["obs"])
            cols["actions"].append(s["actions"])
            cols["logp"].append(s["logp"])
            cols["adv"].append(np.asarray(adv))
            cols["targets"].append(np.asarray(targets))
        batch = {k: np.concatenate(v) for k, v in cols.items()}

        n = len(batch["actions"])
        last_loss, last_aux = 0.0, (0.0, 0.0, 0.0)
        for _ in range(self._epochs):
            perm = self._rng.permutation(n)
            for lo in range(0, n - self._minibatch + 1, self._minibatch):
                idx = perm[lo:lo + self._minibatch]
                mb = {k: v[idx] for k, v in batch.items()}
                self.params, self._opt_state, last_loss, last_aux = \
                    self._update(self.params, self._opt_state, mb)
        pi_l, vf_l, ent = last_aux
        return {"loss": float(last_loss), "policy_loss": float(pi_l),
                "vf_loss": float(vf_l), "entropy": float(ent)}

    @classmethod
    def get_default_config(cls) -> AlgorithmConfig:
        return AlgorithmConfig(algo_cls=cls)


def PPOConfig() -> AlgorithmConfig:
    """(ref: PPOConfig class — here a bound AlgorithmConfig factory)"""
    return PPO.get_default_config()
