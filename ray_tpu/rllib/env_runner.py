"""EnvRunner actors: parallel rollout collection.

TPU-native analog of the reference's EnvRunnerGroup
(/root/reference/rllib/env/env_runner_group.py, single_agent_env_runner.py):
one actor per runner steps its env with the current policy and returns
fixed-size sample batches. Policy weights ship by ObjectRef broadcast (one
put per iteration, every runner gets the same ref) instead of per-runner
NCCL broadcast.

Inference inside a runner is a jitted CPU apply on batch=1 — cheap for the
small nets RL uses; learning happens in the Learner, not here.
"""

from __future__ import annotations

import numpy as np

import ray_tpu
from ray_tpu.rllib.env import make_env, resolve_env_spec
from ray_tpu.rllib.models import RLModule


@ray_tpu.remote
class EnvRunner:
    def __init__(self, env_spec, module: RLModule, seed: int = 0):
        import jax

        self._env = make_env(env_spec)
        self._module = module
        self._rng = np.random.default_rng(seed)
        self._obs = self._env.reset(seed=seed)
        self._ep_return = 0.0
        self._ep_len = 0
        self._done_returns: list[float] = []
        self._done_lens: list[int] = []
        self._logits_fn = jax.jit(module.forward_inference)
        self._value_fn = jax.jit(
            lambda p, o: module.forward_train(p, o)[1])

    def sample(self, params: dict, num_steps: int, *,
               explore: bool = True, epsilon: float = 0.0) -> dict:
        """Collect num_steps transitions with the given policy params.

        Returns a column batch: obs, actions, rewards, dones, next_obs,
        logp (behavior log-prob, for PPO), vf (bootstrap values).
        """
        obs = np.empty((num_steps, self._env.observation_dim), np.float32)
        next_obs = np.empty_like(obs)
        actions = np.empty((num_steps,), np.int32)
        rewards = np.empty((num_steps,), np.float32)
        dones = np.empty((num_steps,), np.float32)
        logps = np.empty((num_steps,), np.float32)

        for t in range(num_steps):
            obs[t] = self._obs
            logits = np.asarray(self._logits_fn(params, self._obs[None]))[0]
            if epsilon > 0.0 and self._rng.random() < epsilon:
                a = int(self._rng.integers(self._env.num_actions))
            elif explore:
                z = logits - logits.max()
                p = np.exp(z) / np.exp(z).sum()
                a = int(self._rng.choice(len(p), p=p))
            else:
                a = int(logits.argmax())
            z = logits - logits.max()
            logps[t] = z[a] - np.log(np.exp(z).sum())
            o2, r, term, trunc = self._env.step(a)
            actions[t], rewards[t] = a, r
            dones[t] = float(term)  # truncation is not a terminal for GAE
            next_obs[t] = o2
            self._ep_return += r
            self._ep_len += 1
            if term or trunc:
                self._done_returns.append(self._ep_return)
                self._done_lens.append(self._ep_len)
                self._ep_return, self._ep_len = 0.0, 0
                o2 = self._env.reset()
            self._obs = o2

        return {"obs": obs, "actions": actions, "rewards": rewards,
                "dones": dones, "next_obs": next_obs, "logp": logps,
                "vf": np.asarray(self._value_fn(params, obs)),
                "last_obs": self._obs.copy(),
                "last_done": 0.0}

    def episode_stats(self) -> dict:
        """Drain completed-episode stats since the last call."""
        rets, self._done_returns = self._done_returns, []
        lens, self._done_lens = self._done_lens, []
        return {"episode_returns": rets, "episode_lens": lens}


class EnvRunnerGroup:
    """Fan-out over n EnvRunner actors (ref: env_runner_group.py)."""

    def __init__(self, env_spec, module: RLModule, num_runners: int = 2,
                 seed: int = 0):
        env_spec = resolve_env_spec(env_spec)
        self._runners = [EnvRunner.remote(env_spec, module, seed=seed + i)
                         for i in range(num_runners)]

    def sample(self, params, steps_per_runner: int, **kw) -> list[dict]:
        params_ref = ray_tpu.put(params)  # one broadcast, n consumers
        return ray_tpu.get([r.sample.remote(params_ref, steps_per_runner, **kw)
                            for r in self._runners], timeout=300.0)

    def episode_stats(self) -> dict:
        stats = ray_tpu.get(
            [r.episode_stats.remote() for r in self._runners], timeout=60.0)
        return {
            "episode_returns": [x for s in stats for x in s["episode_returns"]],
            "episode_lens": [x for s in stats for x in s["episode_lens"]],
        }

    def stop(self) -> None:
        for r in self._runners:
            ray_tpu.kill(r)
